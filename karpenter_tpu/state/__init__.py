from .cluster import Cluster
