"""Operator state snapshots + warm restart (the WarmRestart gate).

A plain process death used to cost a full world re-tensorization: restart
recovery (`Operator.hydrate_cluster`) rebuilds NodeClaims from cloud tags,
loses every pod binding, and the arena/guide/forecast caches start cold.
This module serializes the whole control-plane working set —

* `Cluster` dicts (nodes, claims, pods, PDBs) + mutation epoch,
* the `ClusterArena` slab and registries (`ops/arena.py snapshot_state`),
* solver-adjacent caches: LP mix/stale/support caches (`ops/lpguide.py`),
  the PDHG warm-start cache (`ops/lpsolve.py`), the unavailable-offerings
  ICE cache, the forecast demand series, the solver-health ladders
  (packing and DeviceLP), and every controller supervisor's circuit state,
* the fake-cloud substrate and interruption queue (so a resumed sim run
  replays the exact launch/reclaim stream), and
* the module-level name/id counters (probe-and-reset, net-zero draws) so
  post-restore node names continue the uninterrupted sequence —

into one versioned, checksummed file, written atomically (tmp +
``os.replace``, the LeaderElector idiom) on a cadence and on SIGTERM.

The payload is ONE ``pickle.dumps`` over a sections dict: shared
references (a node's ``pods`` entries are the same objects as
``cluster.pods`` values) survive as shared references, which the arena's
identity-checked ``gather()`` depends on after restore.  Restore
validates magic, version, checksum, and meta↔section epoch consistency;
ANY mismatch is a counted, logged cold fallback — the operator simply
hydrates from cloud state as before, so a corrupt snapshot can never be
worse than no snapshot.  On the happy path the restored arena serves its
first `gather()` warm: no `tensorize_nodes`, reconcile resumes in
milliseconds.

Cross-process cache hygiene: `_class_key` caches (plain content tuples
stored on pods) pickle and stay valid; the *interned* `_cid` tokens from
`ops/tensorize.py` are process-local, so restore bumps the class-id
generation — every restored pod re-interns lazily instead of colliding
with ids minted by the new process.
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import pickle
import time
from typing import Callable, Dict, Optional, Tuple

from ..obs.incidents import publish_incident
from ..utils import metrics

log = logging.getLogger("karpenter_tpu.snapshot")

MAGIC = b"KTSNAP01"
VERSION = 1
_HEADER_LEN = len(MAGIC) + 32  # magic + sha256(payload)


# ---------------------------------------------------------------------------
# module-level counters: probe-and-reset (read the next value, recreate the
# counter at it — net zero draws, so snapshotting never perturbs the run)
# ---------------------------------------------------------------------------

def _counter_sites():
    from ..api import objects as objects_mod
    from ..cloud import fake as fake_mod
    from ..cloud import queue as queue_mod
    from . import cluster as cluster_mod
    return (("node_names", cluster_mod, "_names"),
            ("object_ids", objects_mod, "_ids"),
            ("msg_ids", queue_mod, "_msg_ids"),
            ("fleet_ids", fake_mod, "_fleet_ids"))


def _snapshot_counters() -> Dict[str, int]:
    out: Dict[str, int] = {}
    for key, mod, attr in _counter_sites():
        v = next(getattr(mod, attr))
        setattr(mod, attr, itertools.count(v))
        out[key] = v
    return out


def _restore_counters(data: Dict[str, int]) -> None:
    for key, mod, attr in _counter_sites():
        if key in data:
            setattr(mod, attr, itertools.count(int(data[key])))


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------

def _health_of(manager) -> Optional[object]:
    prov = manager.controllers.get("provisioning") \
        if manager is not None else None
    return getattr(prov, "health", None) if prov is not None else None


def _decode_health_of(manager) -> Optional[object]:
    prov = manager.controllers.get("provisioning") \
        if manager is not None else None
    return getattr(prov, "decode_health", None) if prov is not None else None


def _lp_health_of(manager) -> Optional[object]:
    prov = manager.controllers.get("provisioning") \
        if manager is not None else None
    return getattr(prov, "lp_health", None) if prov is not None else None


def collect_sections(op, manager=None) -> Dict:
    """Assemble the sections dict from a live operator (+ optional
    manager).  Caller holds the state lock; nothing here blocks."""
    from ..ops import lpguide, lpsolve
    cluster = op.cluster
    arena = cluster.arena
    sections: Dict[str, object] = {
        "counters": _snapshot_counters(),
        "cluster": cluster.snapshot_state(),
        "arena": arena.snapshot_state() if arena is not None else None,
        "unavailable": op.unavailable.snapshot_state(),
        "lpguide": lpguide.snapshot_caches(),
        "lpsolve": lpsolve.snapshot_caches(),
        "cloud": op.raw_cloud.snapshot_state(),
        "queue": op.queue.snapshot_state() if op.queue is not None else None,
    }
    observer = cluster.observer
    if observer is not None and hasattr(observer, "snapshot_state"):
        sections["series"] = observer.snapshot_state()
    if manager is not None:
        sections["supervisors"] = {
            name: sup.snapshot_state()
            for name, sup in manager.supervisors.items()}
        bw = manager.batch_window
        sections["manager"] = {
            "entries": {e.name: e.last_run for e in manager._entries},
            "batch_window": {"opened": bw._opened, "last_add": bw._last_add,
                             "last_count": bw._last_count},
        }
        health = _health_of(manager)
        if health is not None:
            sections["health"] = health.snapshot_state()
        dh = _decode_health_of(manager)
        if dh is not None:
            sections["decode"] = dh.snapshot_state()
        lp = _lp_health_of(manager)
        if lp is not None:
            sections["lp_health"] = lp.snapshot_state()
        # HA leader/readiness state (operator/manager.py): present only
        # for a manager that grew the lifecycle (hasattr guards older
        # pickles and stub managers in tests)
        ha = getattr(manager, "ha_snapshot_state", None)
        if ha is not None:
            sections["leader"] = ha()
        # flight-recorder cursor + bus dedup state (FlightRecorder gate):
        # the hook returns None when the gate is off, keeping gate-off
        # snapshots byte-identical
        inc = getattr(manager, "incidents_snapshot_state", None)
        if inc is not None:
            incidents = inc()
            if incidents is not None:
                sections["incidents"] = incidents
        # SLO error budgets + cost-ledger entries (SLOEngine gate): same
        # None-when-off contract as the incidents section above
        slo = getattr(manager, "slo_snapshot_state", None)
        if slo is not None:
            slo_state = slo()
            if slo_state is not None:
                sections["slo"] = slo_state
        led = getattr(manager, "ledger_snapshot_state", None)
        if led is not None:
            led_state = led()
            if led_state is not None:
                sections["ledger"] = led_state
        # gang admission registry (GangScheduling gate): same
        # None-when-off contract — a restart can never observe a
        # half-admitted gang because admission is atomic pre-bind
        gang = getattr(manager, "gang_snapshot_state", None)
        if gang is not None:
            gang_state = gang()
            if gang_state is not None:
                sections["gang"] = gang_state
    sections["meta"] = {
        "version": VERSION,
        "written_at": op.clock(),
        "cluster_epoch": cluster.mutation_epoch,
        "arena_epoch": arena.epoch if arena is not None else None,
    }
    return sections


# ---------------------------------------------------------------------------
# file format: MAGIC ⊕ sha256(payload) ⊕ payload (one pickle)
# ---------------------------------------------------------------------------

def write_snapshot(path: str, op, manager=None, fence=None) -> bool:
    """Serialize + atomically replace `path`.  Returns success; a failed
    write leaves the previous snapshot intact (tmp + rename).  With a
    `fence` (utils/fencing.LeaseFence), the write is REFUSED when the
    fencing epoch is stale — the "two operators, one snapshot file"
    invariant: a deposed leader's late write must lose to the successor,
    and the refusal is counted, never silent."""
    t0 = time.perf_counter()
    if fence is not None and not fence.check("snapshot"):
        metrics.snapshot_writes().inc({"outcome": "stale_fence"})
        return False
    try:
        sections = collect_sections(op, manager)
        if fence is not None:
            sections["meta"]["fence_epoch"] = fence.epoch()
        payload = pickle.dumps(sections,
                               protocol=pickle.HIGHEST_PROTOCOL)
        blob = MAGIC + hashlib.sha256(payload).digest() + payload
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except Exception:
        log.exception("snapshot write to %s failed", path)
        metrics.snapshot_writes().inc({"outcome": "error"})
        return False
    metrics.snapshot_writes().inc({"outcome": "ok"})
    metrics.snapshot_write_duration().observe(time.perf_counter() - t0)
    metrics.snapshot_size().set(len(blob))
    return True


def load_sections(path: str) -> Tuple[Optional[Dict], str]:
    """Read + validate a snapshot file.  Returns (sections, "ok") or
    (None, reason) — reasons are the counted restore outcomes."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None, "missing"
    if len(blob) < _HEADER_LEN or not blob.startswith(MAGIC):
        return None, "bad_magic"
    digest = blob[len(MAGIC):_HEADER_LEN]
    payload = blob[_HEADER_LEN:]
    if hashlib.sha256(payload).digest() != digest:
        return None, "bad_checksum"
    try:
        sections = pickle.loads(payload)
        if int(sections["meta"]["version"]) != VERSION:
            return None, "bad_version"
    except Exception:
        return None, "bad_checksum"
    meta = sections["meta"]
    cluster_sec = sections.get("cluster") or {}
    if meta.get("cluster_epoch") != cluster_sec.get("mutation_epoch"):
        return None, "epoch_mismatch"
    arena_sec = sections.get("arena")
    arena_epoch = arena_sec["epoch"] if arena_sec is not None else None
    if meta.get("arena_epoch") != arena_epoch:
        return None, "epoch_mismatch"
    return sections, "ok"


# ---------------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------------

def restore_snapshot(path: str, op, manager=None) -> str:
    """Warm-restore the operator from `path`.  Returns the counted outcome
    ("restored", or the cold-fallback reason).  Caller holds the state
    lock.  On ANY failure the operator is left on the cold path — arena
    flagged for rebuild, cluster state whatever hydration built — which
    is always correct, just slower."""
    sections, reason = load_sections(path)
    if sections is None:
        log.warning("snapshot restore from %s: cold fallback (%s)",
                    path, reason)
        metrics.snapshot_restores().inc({"outcome": reason})
        publish_incident("snapshot_fallback", {"outcome": reason,
                                               "path": path})
        return reason
    # pre-state for rollback: a half-applied restore must never leave a
    # structurally invalid cluster, so on ANY apply exception we put the
    # hydrated cold state (live dict refs, untouched by the failed apply)
    # back before degrading
    pre_cluster = op.cluster.snapshot_state()
    pre_counters = _snapshot_counters()
    try:
        _apply_sections(sections, op, manager)
    except Exception:
        log.exception("snapshot restore from %s failed mid-apply; "
                      "rolling back to cold state", path)
        try:
            _restore_counters(pre_counters)
            op.cluster.restore_state(pre_cluster)
        except Exception:
            log.exception("rollback after failed restore also failed")
        if op.cluster.arena is not None:
            op.cluster.arena.invalidate("restore_failed")
        metrics.snapshot_restores().inc({"outcome": "apply_error"})
        publish_incident("snapshot_fallback", {"outcome": "apply_error",
                                               "path": path})
        return "apply_error"
    age = max(0.0, op.clock() - float(sections["meta"]["written_at"]))
    metrics.snapshot_restores().inc({"outcome": "restored"})
    metrics.snapshot_age().set(age)
    log.info("warm restore from %s: %d nodes, %d pods, snapshot age %.3fs",
             path, len(op.cluster.nodes), len(op.cluster.pods), age)
    return "restored"


def _apply_sections(sections: Dict, op, manager=None) -> None:
    from ..ops import lpguide, lpsolve
    from ..ops.tensorize import _CLASS_GEN
    _restore_counters(sections.get("counters", {}))
    op.cluster.restore_state(sections["cluster"])
    # restored pods carry _cid intern tokens from the dead process; bump
    # the generation so they re-intern instead of colliding with ids the
    # new process mints (their _ckey content tuples stay valid)
    _CLASS_GEN[0] += 1
    arena = op.cluster.arena
    arena_sec = sections.get("arena")
    if arena is not None:
        if arena_sec is None or not arena.restore_state(arena_sec):
            arena.invalidate("restore_mismatch")
    op.unavailable.restore_state(sections["unavailable"])
    lpguide.restore_caches(sections.get("lpguide", {}))
    lpsolve.restore_caches(sections.get("lpsolve", {}))
    op.raw_cloud.restore_state(sections["cloud"])
    if op.queue is not None and sections.get("queue") is not None:
        op.queue.restore_state(sections["queue"])
    observer = op.cluster.observer
    if observer is not None and hasattr(observer, "restore_state") \
            and "series" in sections:
        observer.restore_state(sections["series"])
    if manager is not None:
        for name, data in sections.get("supervisors", {}).items():
            sup = manager.supervisors.get(name)
            if sup is not None:
                sup.restore_state(data)
        mgr_sec = sections.get("manager")
        if mgr_sec is not None:
            last_runs = mgr_sec.get("entries", {})
            for e in manager._entries:
                if e.name in last_runs:
                    e.last_run = float(last_runs[e.name])
            bw = mgr_sec.get("batch_window")
            if bw is not None:
                manager.batch_window._opened = bw["opened"]
                manager.batch_window._last_add = bw["last_add"]
                manager.batch_window._last_count = int(bw["last_count"])
        health = _health_of(manager)
        if health is not None and "health" in sections:
            health.restore_state(sections["health"])
        dh = _decode_health_of(manager)
        if dh is not None and "decode" in sections:
            dh.restore_state(sections["decode"])
        lp = _lp_health_of(manager)
        if lp is not None and "lp_health" in sections:
            lp.restore_state(sections["lp_health"])
        ha = getattr(manager, "ha_restore_state", None)
        if ha is not None and sections.get("leader") is not None:
            ha(sections["leader"])
        inc = getattr(manager, "incidents_restore_state", None)
        if inc is not None and sections.get("incidents") is not None:
            inc(sections["incidents"])
        slo = getattr(manager, "slo_restore_state", None)
        if slo is not None and sections.get("slo") is not None:
            slo(sections["slo"])
        led = getattr(manager, "ledger_restore_state", None)
        if led is not None and sections.get("ledger") is not None:
            led(sections["ledger"])
        gang = getattr(manager, "gang_restore_state", None)
        if gang is not None and sections.get("gang") is not None:
            gang(sections["gang"])


# ---------------------------------------------------------------------------
# cadence driver (held by the ControllerManager under the WarmRestart gate)
# ---------------------------------------------------------------------------

class SnapshotWriter:
    """Periodic snapshot driver: `maybe_write(now)` from the tick loop,
    `write_final()` from `stop()` (the SIGTERM hook)."""

    def __init__(self, path: str, op, manager=None,
                 interval_s: float = 30.0, fence=None):
        self.path = path
        self.op = op
        self.manager = manager
        self.interval_s = float(interval_s)
        self._last_written = float("-inf")
        # HAFailover: the manager attaches its LeaseFence here, so every
        # cadence AND final write validates the fencing epoch first —
        # a deposed replica's cadence can never clobber the successor's
        # snapshot (the concrete split-brain bug of the unfenced writer)
        self.fence = fence

    def maybe_write(self, now: float) -> bool:
        if not self.path or now - self._last_written < self.interval_s:
            return False
        ok = write_snapshot(self.path, self.op, self.manager,
                            fence=self.fence)
        if ok:
            self._last_written = now
        return ok

    def write_final(self) -> bool:
        if not self.path:
            return False
        return write_snapshot(self.path, self.op, self.manager,
                              fence=self.fence)
