"""Ingestion batcher: coalesce cluster events between ticks (IngestBatch).

At production event rates the eager delta stream is the steady-state cost:
a 50k-events/s firehose of binds/reclaims/price updates pays one
`ClusterArena` row recompute *per event*, even though the solver only
looks at the slab once per reconcile tick.  `IngestBatcher` wraps the
arena behind the same delta-API surface (`Cluster`'s mutators call
``cluster.arena.apply_*`` blindly) and absorbs events into per-node
pending state instead:

* a node needing a **full row** (add / label-taint touch) shadows any
  number of used-only refreshes for the same node;
* a **removal** cancels pending work for the node outright (and an add
  after a removal revives it — the eager remove+add pair collapses to
  one row write);
* pod binds/unbinds collapse to one **used-vector** refresh per node per
  window, no matter how many pods churned;
* pod add/remove and offering events carry no row work at all — they
  fold into the single epoch bump the flush applies.

`flush()` — called by the manager at the top of every tick, and as a
safety net by `gather()`/`snapshot_state()` — applies the whole window
through `ClusterArena.apply_ingest_flush` as ONE delta.  Because every
row re-derives from *current* cluster state through the same exact math
as the eager path, a batched window and its eager equivalent differ only
in slot layout, never in gather output — the gate-on byte-identity tests
in tests/test_ingest.py pin this.

Backpressure: when the pending set grows past ``max_events`` the batcher
degrades to `arena.invalidate()` — the next gather is a full rebuild
that re-derives every event's effect from cluster state.  Degraded, not
dropped: the rebuild is the always-correct path.
"""

from __future__ import annotations

from typing import Dict, List

from ..utils import metrics

_EAGER_FORWARDS = frozenset({"compact", "rebuild"})


class IngestBatcher:
    """Arena-shaped event coalescer (see module docstring).  All calls
    happen under the operator's state lock, like the arena it wraps."""

    def __init__(self, arena, max_events: int = 100_000):
        self._arena = arena
        self.max_events = int(max_events)
        self._touched: Dict[str, object] = {}  # name → Node (full-row work)
        self._removed: Dict[str, None] = {}    # name → (removal pending)
        self._used: Dict[str, None] = {}       # name → (used-only refresh)
        self._bump_only = False   # pod_add/offering events in the window
        self.events_total = 0
        self.flushes_total = 0
        self.overflows_total = 0

    # ---- bookkeeping ------------------------------------------------------
    @property
    def pending(self) -> int:
        return len(self._touched) + len(self._removed) + len(self._used)

    def _event(self, kind: str) -> None:
        self.events_total += 1
        metrics.ingest_events().inc({"kind": kind})
        pending = self.pending
        metrics.ingest_pending().set(pending)
        if pending > self.max_events:
            self._overflow()

    def _overflow(self) -> None:
        self.overflows_total += 1
        metrics.ingest_overflows().inc()
        self._clear()
        self._arena.invalidate("ingest_overflow")

    def _clear(self) -> None:
        self._touched.clear()
        self._removed.clear()
        self._used.clear()
        self._bump_only = False
        metrics.ingest_pending().set(0)

    # ---- the delta-API surface Cluster's mutators call --------------------
    def apply_node_add(self, node) -> None:
        self._removed.pop(node.name, None)
        self._used.pop(node.name, None)
        self._touched[node.name] = node
        self._event("node_add")

    def apply_node_remove(self, name: str) -> None:
        was_pending_add = self._touched.pop(name, None) is not None \
            and name not in self._arena._slot_of
        self._used.pop(name, None)
        if not was_pending_add:
            # tracked (or unknown) node: the arena must tombstone it; a
            # node that only ever existed inside this window cancels out
            self._removed[name] = None
        self._event("node_remove")

    def touch_node(self, node) -> None:
        if node.name in self._touched:
            self._touched[node.name] = node
        elif node.name not in self._removed and \
                node.name in self._arena._slot_of:
            self._used.pop(node.name, None)
            self._touched[node.name] = node
        # untracked or removal-pending: the eager path would no-op too
        self._event("touch")

    def apply_pod_bind(self, pod, node_name: str,
                       old_node_name: str = "") -> None:
        if old_node_name and old_node_name != node_name:
            self._mark_used(old_node_name)
        self._mark_used(node_name)
        self._event("pod_bind")

    def apply_pod_unbind(self, node_name: str) -> None:
        self._mark_used(node_name)
        self._event("pod_unbind")

    def apply_pod_add(self, pod) -> None:
        self._bump_only = True
        self._event("pod_add")

    def apply_pod_remove(self, pod, node_name: str = "") -> None:
        if node_name:
            self._mark_used(node_name)
        self._bump_only = True
        self._event("pod_remove")

    def apply_offering_change(self) -> None:
        self._bump_only = True
        self._event("offering")

    def _mark_used(self, name: str) -> None:
        if name in self._touched or name in self._removed:
            return  # full-row work (or removal) already shadows it
        self._used[name] = None

    # ---- flush + pass-throughs --------------------------------------------
    def flush(self) -> bool:
        """Apply the whole pending window as one arena delta.  Returns
        True when anything was applied."""
        if not (self._touched or self._removed or self._used
                or self._bump_only):
            return False
        touched: List[object] = list(self._touched.values())
        removed = [n for n in self._removed]
        used = [n for n in self._used]
        self._clear()
        self._arena.apply_ingest_flush(touched, removed, used)
        self.flushes_total += 1
        metrics.ingest_flushes().inc()
        return True

    def gather(self, *args, **kwargs):
        # safety net: a consumer that gathers before the manager's
        # top-of-tick flush must still see every absorbed event
        self.flush()
        return self._arena.gather(*args, **kwargs)

    def invalidate(self, reason: str = "") -> None:
        # pending work is subsumed by the rebuild the flag forces
        self._clear()
        self._arena.invalidate(reason)

    def snapshot_state(self):
        self.flush()
        return self._arena.snapshot_state()

    def restore_state(self, data) -> bool:
        self._clear()
        return self._arena.restore_state(data)

    def __getattr__(self, name):
        # everything else (epoch, live_count, slab reads in tests, compact,
        # rebuild, ...) forwards to the wrapped arena untouched
        return getattr(self._arena, name)
