"""In-memory cluster state.

Analog of karpenter-core's `state.Cluster` (constructed at
/root/reference/cmd/controller/main.go:51): the nodes+pods+bindings snapshot
that provisioning packs against and the consolidation simulator replays.

TPU-first addition: `tensorize_nodes` lowers the live node set to the dense
arrays (allocatable/used E×R, per-class compat C×E) that the packing kernel
takes as pre-opened slots, so "schedule against existing capacity" and
"simulate without node X" are array slices, not object-graph walks."""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as wk
from ..api.objects import Node, NodeClaim, Pod, PodDisruptionBudget
from ..api.requirements import Requirements
from ..api.resources import DEFAULT_AXES, DEFAULT_SCALES, PODS, ResourceList
from ..ops.constraints import pod_is_soft
from ..ops.tensorize import _class_key
from ..api.taints import tolerates_all
from ..utils import metrics

_names = itertools.count(1)

# How long a fresh node stays protected from disruption while its pods are
# still in flight (the reference's nomination window in state.Cluster).
NOMINATION_WINDOW_S = 20.0


class Cluster:
    def __init__(self, clock: Callable[[], float] = time.time):
        self.clock = clock
        # the cluster has no lock of its own: every mutation happens under
        # the Operator's state_lock, held by the manager's tick loop, the
        # /v1 apply surface, and the metrics collector (graftlint LK)
        self.nodes: Dict[str, Node] = {}        # guarded-by: caller(state_lock)
        self.nodeclaims: Dict[str, NodeClaim] = {}  # guarded-by: caller(state_lock)
        self.pods: Dict[str, Pod] = {}          # guarded-by: caller(state_lock)
        self.pdbs: Dict[str, PodDisruptionBudget] = {}  # guarded-by: caller(state_lock)
        # optional demand observer (forecast/series.py DemandSeries): gets
        # pod_added/pod_removed/pod_bound callbacks under the caller's
        # state lock; None unless the Forecast gate wires one
        self.observer = None
        # optional persistent delta arena (ops/arena.py ClusterArena): every
        # mutator below forwards its delta so consumers can gather warm
        # tensors instead of re-running tensorize_nodes; None unless the
        # IncrementalArena gate attaches one
        self.arena = None                       # guarded-by: caller(state_lock)
        # monotone mutation counter, bumped by EVERY mutator (arena attached
        # or not): cached tensorizations (SimulationArena faces, the
        # disruption fingerprint) compare it to detect staleness lazily
        self.mutation_epoch = 0                 # guarded-by: caller(state_lock)

    def attach_arena(self, **kwargs):
        """Create and attach a ClusterArena seeded from current state; every
        subsequent mutation streams into it as a typed delta."""
        from ..ops.arena import ClusterArena
        self.arena = ClusterArena(self, **kwargs)
        self.arena.rebuild()
        return self.arena

    # ---- warm restart (state/snapshot.py) ----
    def snapshot_state(self) -> Dict:  # guarded-by: caller(state_lock)
        """The object-graph half of the WarmRestart snapshot: the four
        state dicts plus the epoch.  NOT copied — the whole snapshot
        payload pickles in one pass under the state lock, and sharing the
        live dicts keeps node.pods entries identical to pods.values()
        entries in the pickled graph (identity the arena's `_node_at`
        rewiring and `gather()`'s `is` check depend on after restore)."""
        return {
            "nodes": self.nodes,
            "nodeclaims": self.nodeclaims,
            "pods": self.pods,
            "pdbs": self.pdbs,
            "mutation_epoch": self.mutation_epoch,
        }

    def restore_state(self, data: Dict) -> None:  # guarded-by: caller(state_lock)
        """Adopt unpickled state dicts wholesale.  The caller re-attaches
        (or restores) the arena and observer afterwards — this method
        leaves both wiring hooks untouched."""
        self.nodes = data["nodes"]
        self.nodeclaims = data["nodeclaims"]
        self.pods = data["pods"]
        self.pdbs = data["pdbs"]
        self.mutation_epoch = int(data["mutation_epoch"])

    # ---- pods ----
    def add_pod(self, pod: Pod) -> Pod:
        pod.created_at = self.clock()   # informer-arrival stamp (bind latency)
        self.pods[pod.uid] = pod
        # admission-time lowering: compute the pod's equivalence-class key
        # and softness flag here (the informer-decode analog), so the
        # scheduling hot window (lower_pods + tensorize + solve) never pays
        # them — every later tensorize of this object hits the caches
        _class_key(pod)
        pod_is_soft(pod)
        if self.observer is not None:
            self.observer.pod_added(pod)
        self.mutation_epoch += 1
        if self.arena is not None:
            self.arena.apply_pod_add(pod)
        return pod

    def add_pods(self, pods: Sequence[Pod]) -> List[Pod]:
        return [self.add_pod(p) for p in pods]

    def delete_pod(self, pod: Pod):
        existed = self.pods.pop(pod.uid, None) is not None
        bound_to = ""
        if pod.node_name and pod.node_name in self.nodes:
            node = self.nodes[pod.node_name]
            node.pods = [p for p in node.pods if p.uid != pod.uid]
            bound_to = node.name
        if existed and self.observer is not None:
            self.observer.pod_removed(pod)
        self.mutation_epoch += 1
        if self.arena is not None:
            self.arena.apply_pod_remove(pod, bound_to)

    def bind_pod(self, pod: Pod, node_name: str):
        rebind = bool(pod.node_name)
        old_node = pod.node_name if rebind else ""
        if pod.node_name and pod.node_name in self.nodes:
            old = self.nodes[pod.node_name]
            old.pods = [p for p in old.pods if p.uid != pod.uid]
        pod.node_name = node_name
        node = self.nodes[node_name]
        node.pods.append(pod)
        node.nominated_until = 0.0  # nomination fulfilled
        if not rebind:
            # first bind only: arrival → placement latency
            # (karpenter_pods_bound_duration_seconds)
            metrics.pods_bound_duration().observe(
                max(0.0, self.clock() - pod.created_at))
            # pod "startup": running on a READY node.  Bound to an
            # already-initialized node -> now; else the lifecycle
            # controller observes it when initialization completes.  Once
            # per pod LIFETIME (flag survives requeue): an evicted pod
            # rebinding hours later would otherwise log its age, not its
            # startup latency.
            if (node.labels.get(wk.NODE_INITIALIZED) == "true"
                    and not pod.__dict__.get("_startup_observed")):
                pod.__dict__["_startup_observed"] = True
                metrics.pods_startup_time().observe(
                    max(0.0, self.clock() - pod.created_at))
        if not rebind and self.observer is not None:
            self.observer.pod_bound(pod)
        self.mutation_epoch += 1
        if self.arena is not None:
            self.arena.apply_pod_bind(pod, node_name, old_node)

    def unbind_pod(self, pod: Pod):
        was_on = ""
        if pod.node_name and pod.node_name in self.nodes:
            node = self.nodes[pod.node_name]
            node.pods = [p for p in node.pods if p.uid != pod.uid]
            was_on = node.name
        pod.node_name = ""
        self.mutation_epoch += 1
        if self.arena is not None and was_on:
            self.arena.apply_pod_unbind(was_on)

    def pending_pods(self) -> List[Pod]:
        return [p for p in self.pods.values() if not p.node_name]

    def original(self, pod: Pod) -> Pod:
        """Map a constraint-lowered pod copy (ops/constraints.py) back to the
        cluster's original object.  Controllers must always bind the
        original, never a rewritten copy."""
        return self.pods.get(pod.uid, pod)

    # ---- nodes / claims ----
    def add_node(self, node: Node) -> Node:
        self.nodes[node.name] = node
        self.mutation_epoch += 1
        if self.arena is not None:
            self.arena.apply_node_add(node)
        return node

    def remove_node(self, name: str) -> Optional[Node]:
        node = self.nodes.pop(name, None)
        if node:
            metrics.nodes_terminated().inc({"nodepool": node.nodepool or ""})
            for p in node.pods:
                p.node_name = ""
                # evicted pods with owners get recreated as pending; ownerless
                # pods are gone for good (termination semantics)
                if not p.owner_kind:
                    self.pods.pop(p.uid, None)
                    if self.observer is not None:
                        self.observer.pod_removed(p)
            node.pods = []
            self.mutation_epoch += 1
            if self.arena is not None:
                self.arena.apply_node_remove(name)
        return node

    def touch_node(self, node: Node):
        """Callers that edit a node's labels/taints/allocatable IN PLACE
        (lifecycle initialization, termination + disruption tainting, sim
        boot-taint stripping) must report it here so the arena re-derives
        the node's row and cached tensorizations notice the change."""
        self.mutation_epoch += 1
        if self.arena is not None:
            self.arena.touch_node(node)

    def register_nodeclaim(self, claim: NodeClaim, allocatable: ResourceList,
                           capacity: Optional[ResourceList] = None,
                           initialized: bool = True,
                           rehydrate: bool = False) -> Node:
        """NodeClaim → Node on (simulated) kubelet join; lifecycle per
        SURVEY §2.2 NodeClaim lifecycle.  The sync provisioning path
        registers+initializes in one step (instant fake kubelet); the async
        LifecycleController passes initialized=False and runs the
        initialization pass separately.  ``rehydrate`` marks restart
        recovery — rebuilding state for an already-registered node is not a
        registration event, so the latency histograms stay clean."""
        claim.registered = True
        claim.registered_at = claim.registered_at or self.clock()
        claim.initialized = initialized
        if initialized and not claim.initialized_at:
            claim.initialized_at = self.clock()
        if not rehydrate:
            # registration/initialization latency families — the sync path
            # records its true (instant) joins, the async lifecycle path its
            # real delays (reference karpenter_nodeclaims_* durations)
            if claim.launched_at:
                metrics.nodeclaim_registration_duration().observe(
                    max(0.0, claim.registered_at - claim.launched_at))
            if initialized:
                metrics.nodeclaim_initialization_duration().observe(
                    max(0.0, claim.initialized_at - claim.registered_at))
                metrics.nodeclaims_initialized().inc(
                    {"nodepool": claim.nodepool})
            metrics.nodeclaims_registered().inc({"nodepool": claim.nodepool})
            metrics.nodes_created().inc({"nodepool": claim.nodepool})
        self.nodeclaims[claim.name] = claim
        node = Node(
            name=f"node-{next(_names):06d}",
            provider_id=claim.provider_id,
            labels=dict(claim.labels),
            taints=list(claim.taints),
            allocatable=allocatable,
            capacity=capacity or allocatable,
            nodepool=claim.nodepool,
            instance_type=claim.instance_type,
            zone=claim.zone,
            capacity_type=claim.capacity_type,
            price=claim.price,
            created_at=self.clock(),
            # protected from disruption until its pods bind (or the window
            # lapses) — the reference's in-flight nomination blocker
            nominated_until=self.clock() + NOMINATION_WINDOW_S,
        )
        node.labels.setdefault(wk.HOSTNAME, node.name)
        if initialized:
            node.labels[wk.NODE_INITIALIZED] = "true"
        return self.add_node(node)

    def node_for_provider_id(self, provider_id: str) -> Optional[Node]:
        for n in self.nodes.values():
            if n.provider_id == provider_id:
                return n
        return None

    def claim_for_provider_id(self, provider_id: str) -> Optional[NodeClaim]:
        for c in self.nodeclaims.values():
            if c.provider_id == provider_id:
                return c
        return None

    def nodepool_usage(self) -> Dict[str, ResourceList]:
        """Capacity in use per NodePool — feeds limits enforcement
        (/root/reference/designs/limits.md)."""
        out: Dict[str, ResourceList] = {}
        for n in self.nodes.values():
            if n.nodepool:
                out[n.nodepool] = out.get(n.nodepool, ResourceList()) + n.capacity
        return out

    # ---- PDBs / eviction safety ----
    def add_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        self.pdbs[pdb.name] = pdb
        return pdb

    def remove_pdb(self, name: str):
        self.pdbs.pop(name, None)

    def pdb_budget(self, pdb: PodDisruptionBudget) -> int:
        """Remaining voluntary evictions the budget allows right now. Bound
        pods count as healthy; pending ones as unavailable."""
        matching = [p for p in self.pods.values() if pdb.matches(p)]
        healthy = sum(1 for p in matching if p.node_name)
        return pdb.allowed_disruptions(healthy, len(matching))

    def pdb_budgets(self) -> Dict[str, int]:
        """All budgets in one pass — candidates() precomputes this so the
        per-node evictable() checks don't rescan the pod set."""
        return {name: self.pdb_budget(pdb) for name, pdb in self.pdbs.items()}

    def evictable(self, pods: Sequence[Pod],
                  budgets: Optional[Dict[str, int]] = None) -> bool:
        """Would evicting ALL of `pods` at once violate any PDB? The blocker
        the consolidation candidate filter and the drain flow share
        (/root/reference/designs/consolidation.md:44-52)."""
        if not self.pdbs:
            return True
        draw: Dict[str, int] = {}
        for p in pods:
            for pdb in self.pdbs.values():
                if pdb.matches(p):
                    draw[pdb.name] = draw.get(pdb.name, 0) + 1
        if budgets is None:
            budgets = self.pdb_budgets()
        return all(budgets[name] >= n for name, n in draw.items())

    # ---- tensorization of live capacity ----
    def snapshot_nodes(self) -> List[Node]:
        """Point-in-time node copies for lock-free solves: shallow node
        copies with their pods list, labels dict, and taints list copied —
        a concurrent tick's bind/remove AND the lifecycle controller's
        label/taint edits (initialized marker, startup-taint removal)
        cannot change them mid-solve.  Taken under the caller's state
        lock in microseconds; everything downstream
        (`tensorize_nodes(nodes=…)`, constraint lowering) then runs off
        the lock.  Pod objects themselves are shared — the solver only
        reads fields that are stable after admission — so the copy is
        O(nodes + pods) pointers, not a deep clone."""
        import copy
        out = []
        for n in self.nodes.values():
            c = copy.copy(n)
            c.pods = list(n.pods)
            c.labels = dict(n.labels)
            c.taints = list(n.taints)
            out.append(c)
        return out

    def tensorize_nodes(self, pod_classes: Sequence[Pod],
                        axes: Tuple[str, ...] = DEFAULT_AXES,
                        exclude: Sequence[str] = (),
                        nodes: Optional[Sequence[Node]] = None,
                        scales=None):
        """Lower live nodes to pre-opened packing slots.

        Returns (node_list, alloc E×R, used E×R, compat C×E) where compat is
        label/taint feasibility of each pod class rep on each node. `exclude`
        masks candidate nodes out — the consolidation simulator's "what if
        this node were gone" (SURVEY.md §7.6)."""
        node_list = [n for n in (nodes if nodes is not None else self.nodes.values())
                     if n.name not in exclude and not n.marked_for_deletion]
        if scales is None:
            scales = DEFAULT_SCALES
        E, R, C = len(node_list), len(axes), len(pod_classes)
        alloc = np.zeros((E, R), np.float32)
        used = np.zeros((E, R), np.float32)
        compat = np.zeros((C, E), bool)
        for e, n in enumerate(node_list):
            alloc[e] = n.allocatable.to_vector(axes, scales)
            req = n.requested()
            req[PODS] = len(n.pods)
            used[e] = req.to_vector(axes, scales, round_up=True)
            node_labels = dict(n.labels)
            # hostname defaults to the node name so hostname-NotIn lowerings
            # (anti-affinity) bind even for externally-seeded nodes that never
            # got the label from register_nodeclaim
            node_labels.setdefault(wk.HOSTNAME, n.name)
            provided = Requirements.from_labels(node_labels)
            for ci, rep in enumerate(pod_classes):
                if not tolerates_all(rep.tolerations, n.taints):
                    continue
                if any(b.compatible(provided) for b in rep.scheduling_requirements()):
                    compat[ci, e] = True
        return node_list, alloc, used, compat
