"""Process entry point: `python -m karpenter_tpu`.

The analog of /root/reference/cmd/controller/main.go:32-73 — parse options,
build the operator, assemble core + provider controllers, serve endpoints,
run the manager until interrupted.  Runs against the in-memory substrate;
a real deployment swaps the substrate handles in `Operator`.
"""

from __future__ import annotations

import logging
import os
import signal
import sys

from .operator import ControllerManager, Operator, Options, build_controllers
from .utils.tracing import configure_logging


def _build_leader(options):
    """Leadership elector for --leader-elect: a TTL'd lease file shared by
    the replicas on this host (charts' 2-replica HA analog).  The lease
    carries the fencing epoch the HAFailover gate validates on every
    snapshot/cloud write."""
    if not options.leader_elect:
        return None
    import socket
    import tempfile
    from .operator.manager import LeaderElector
    lease = options.lease_path or os.path.join(
        tempfile.gettempdir(),
        f"karpenter-{options.cluster_name}.lease")
    identity = f"{socket.gethostname()}-{os.getpid()}"
    return LeaderElector(lease, identity, ttl=options.lease_ttl_s)


def main(argv=None) -> int:
    # options first: the log handler (text vs json, slow-span threshold)
    # is itself configured by flags/env
    options = Options.from_args(argv)
    configure_logging(options)
    op = Operator(options)
    manager = ControllerManager(op, build_controllers(op),
                                leader=_build_leader(options))
    # readiness ladder BEFORE serving: warm restore (hydration already
    # rebuilt what it could from cloud tags; a valid snapshot supersedes
    # it, any mismatch falls back cold), then the arena parity probe,
    # then the role phase — /readyz stays 503 until the ladder completes
    outcome = manager.startup()
    if options.gate("WarmRestart") and options.snapshot_path:
        logging.info("warm restart: %s", outcome)
    port = manager.serve_endpoints()
    logging.info("karpenter-tpu up: cluster=%s endpoints=127.0.0.1:%s "
                 "controllers=%s", options.cluster_name, port,
                 sorted(manager.controllers))
    signal.signal(signal.SIGTERM, lambda *_: manager.stop())
    signal.signal(signal.SIGINT, lambda *_: manager.stop())
    try:
        manager.run()
    finally:
        manager.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
