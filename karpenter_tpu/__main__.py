"""Process entry point: `python -m karpenter_tpu`.

The analog of /root/reference/cmd/controller/main.go:32-73 — parse options,
build the operator, assemble core + provider controllers, serve endpoints,
run the manager until interrupted.  Runs against the in-memory substrate;
a real deployment swaps the substrate handles in `Operator`.
"""

from __future__ import annotations

import logging
import signal
import sys

from .operator import ControllerManager, Operator, Options, build_controllers
from .utils.tracing import configure_logging


def main(argv=None) -> int:
    # options first: the log handler (text vs json, slow-span threshold)
    # is itself configured by flags/env
    options = Options.from_args(argv)
    configure_logging(options)
    op = Operator(options)
    manager = ControllerManager(op, build_controllers(op))
    if options.gate("WarmRestart") and options.snapshot_path:
        # warm restore AFTER construction: hydration already rebuilt what
        # it could from cloud tags; a valid snapshot supersedes it with
        # the full pre-crash working set (any mismatch falls back cold)
        from .state.snapshot import restore_snapshot
        with op.state_lock:
            outcome = restore_snapshot(options.snapshot_path, op, manager)
        logging.info("warm restart: %s", outcome)
    port = manager.serve_endpoints()
    logging.info("karpenter-tpu up: cluster=%s endpoints=127.0.0.1:%s "
                 "controllers=%s", options.cluster_name, port,
                 sorted(manager.controllers))
    signal.signal(signal.SIGTERM, lambda *_: manager.stop())
    signal.signal(signal.SIGINT, lambda *_: manager.stop())
    try:
        manager.run()
    finally:
        manager.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
