"""Per-decision cost ledger: $·h attribution for every capacity decision.

The sim report's `cost.dollar_hours` integral and the live fleet's
nodepool spend are single opaque scalars: nothing says *which* decision
— a provisioning launch, a consolidation replacement, a spot reclaim —
spent the money.  This module is the attribution seam.  Every launch
opens a ledger entry `{decision_source, nodepool, pod_class, expected
$/h, fence epoch, trace id}` at the provider's `_launch` funnel; every
termination/reclaim closes it with the realized lifetime, so
`realized $·h = instance price × lifetime` while `expected $·h` uses the
price of the cheapest offering the launch *intended* (`overrides[0]`) —
the two diverge exactly when ICE landed the claim on a pricier
offering, which is the drift the detector watches per nodepool and
publishes as `cost_drift` incidents.

Like the `IncidentBus` and `CHAOS`, the ledger is process-global and
DISARMED by default: `LEDGER.enabled` is a single boolean check at each
hook, so gate-off runs pay nothing and stay byte-identical.  Decision
attribution rides a thread-local context (`LEDGER.decision(...)`) set by
the disruption/interruption controllers around their actuation funnels;
anything not inside an explicit context is a provisioning launch.

Clock discipline matches `obs/incidents.py`: the wall default is a
stored reference that is never read while disarmed — arming injects the
operator's (virtual or wall) clock, so DT001 stays clean on the sim
path.  Headroom placeholders never launch instances themselves (their
pods flow through normal provisioning), so their entries are
*reservation annotations* kept out of the per-source capacity sums —
without that exclusion the ledger's expected $·h could double-count a
pre-provisioned node.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .incidents import publish_incident

# decision sources form a closed, bounded label set (OB003): controllers
# tag their actuation funnels; untagged launches are provisioning.
DECISION_SOURCES = frozenset({
    "provisioning",      # pending-pod launch (default attribution)
    "consolidation",     # disruption replacement / delete
    "emptiness",         # empty-node disruption
    "expiration",        # expired-node disruption
    "drift",             # drifted-node disruption
    "interruption",      # spot interruption recycle
    "spot_reclaim",      # forced reclaim (warning not honored)
    "liveness",          # failed-launch / liveness termination
    "headroom",          # forecast placeholder reservation (annotation)
    "termination",       # untagged delete (GC, manual)
})


@dataclass
class LedgerEntry:
    """One capacity decision.  `expected_rate` is the $/h the decision
    planned to pay (cheapest intended offering); `realized_rate` the $/h
    the instance actually bills.  `closed_at is None` = still running."""
    id: str
    decision_source: str
    nodepool: str
    pod_class: str
    expected_rate: float
    realized_rate: float
    opened_at: float
    fence_epoch: int = 0
    trace_id: str = ""
    closed_at: Optional[float] = None
    close_reason: str = ""

    def expected_dh(self, now: float) -> float:
        end = self.closed_at if self.closed_at is not None else now
        return self.expected_rate * max(0.0, end - self.opened_at) / 3600.0

    def realized_dh(self, now: float) -> float:
        end = self.closed_at if self.closed_at is not None else now
        return self.realized_rate * max(0.0, end - self.opened_at) / 3600.0

    def to_dict(self) -> Dict:
        return {
            "id": self.id, "decision_source": self.decision_source,
            "nodepool": self.nodepool, "pod_class": self.pod_class,
            "expected_rate": self.expected_rate,
            "realized_rate": self.realized_rate,
            "opened_at": self.opened_at, "fence_epoch": self.fence_epoch,
            "trace_id": self.trace_id, "closed_at": self.closed_at,
            "close_reason": self.close_reason,
        }


@dataclass
class Reservation:
    """A headroom placeholder's planned spend — an annotation, not
    capacity (the node it pre-warms is ledgered by its own launch)."""
    nodepool: str
    expected_dh: float
    opened_at: float
    ttl_s: float


class CostLedger:
    """Bounded per-decision $·h ledger with expected-vs-realized drift
    detection.  All bookkeeping is behind a lock: launches arrive from
    the manager tick while reclaims land from the cloud-delivery path.
    """

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._clock: Callable[[], float] = time.time  # reference, never read while disarmed
        self._retention = 256
        self._drift_threshold = 0.15
        self._drift_min_entries = 3
        self._open: Dict[str, LedgerEntry] = {}        # guarded-by: _lock
        self._closed: deque = deque(maxlen=256)        # guarded-by: _lock
        self._reservations: deque = deque(maxlen=256)  # guarded-by: _lock
        # ids ever ledgered (bounded LRU): the restart-dedup set — a
        # rehydrated launch hook must not re-open an entry the snapshot
        # already carries
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._seen_cap = 4096
        # closed-entry aggregates survive deque eviction: totals are
        # exact even after old entries age out of the bounded window
        self._agg_source: Dict[str, Dict[str, float]] = {}
        self._agg_pool: Dict[str, Dict[str, float]] = {}
        self._drift_active: Dict[str, bool] = {}
        self.drift_alerts = 0
        self.entries_opened = 0
        self.entries_closed = 0
        self._ctx = threading.local()

    # ---- lifecycle -------------------------------------------------------
    def arm(self, clock: Callable[[], float], *, retention: int = 256,
            drift_threshold: float = 0.15,
            drift_min_entries: int = 3) -> None:
        with self._lock:
            self._clock = clock
            self._retention = int(retention)
            self._drift_threshold = float(drift_threshold)
            self._drift_min_entries = int(drift_min_entries)
            self._closed = deque(self._closed, maxlen=self._retention)
            self._reservations = deque(self._reservations,
                                       maxlen=self._retention)
            self.enabled = True

    def disarm(self) -> None:
        with self._lock:
            self.enabled = False
            self._open.clear()
            self._closed.clear()
            self._reservations.clear()
            self._seen.clear()
            self._agg_source.clear()
            self._agg_pool.clear()
            self._drift_active.clear()
            self.drift_alerts = 0
            self.entries_opened = 0
            self.entries_closed = 0

    # ---- decision-context attribution ------------------------------------
    def decision(self, source: str):
        """Context manager tagging launches/terminations inside it with
        `source` (a DECISION_SOURCES member)."""
        if source not in DECISION_SOURCES:
            raise ValueError(f"unregistered decision source: {source!r} "
                             "(add it to obs.ledger.DECISION_SOURCES)")
        ledger = self

        class _Ctx:
            def __enter__(self):
                ledger._ctx.source = source
                return ledger

            def __exit__(self, *exc):
                ledger._ctx.source = None
                return False

        return _Ctx()

    def current_source(self, default: str = "provisioning") -> str:
        src = getattr(self._ctx, "source", None)
        return src if src else default

    # ---- record hooks (free when disarmed) --------------------------------
    def record_launch(self, entry_id: str, *, nodepool: str,
                      pod_class: str = "", expected_rate: float = 0.0,
                      realized_rate: float = 0.0, at: float,
                      fence_epoch: int = 0, trace_id: str = "",
                      source: Optional[str] = None) -> bool:
        """Open an entry for one launched instance.  Returns False when
        the id was already ledgered (warm-restart replay) — the dedup
        the chaos × restart test proves."""
        if not self.enabled:
            return False
        src = source or self.current_source()
        with self._lock:
            if not self.enabled:
                return False
            if entry_id in self._seen or entry_id in self._open:
                return False
            self._seen[entry_id] = None
            while len(self._seen) > self._seen_cap:
                self._seen.popitem(last=False)
            self._open[entry_id] = LedgerEntry(
                id=entry_id, decision_source=src, nodepool=nodepool or "",
                pod_class=pod_class, expected_rate=float(expected_rate),
                realized_rate=float(realized_rate), opened_at=float(at),
                fence_epoch=int(fence_epoch), trace_id=trace_id)
            self.entries_opened += 1
        from ..utils import metrics
        metrics.ledger_entries().inc({"decision_source": src})
        metrics.ledger_open_entries().set(len(self._open))
        return True

    def record_close(self, entry_id: str, *, at: float,
                     reason: Optional[str] = None) -> bool:
        """Close the open entry for `entry_id` at its termination or
        reclaim instant.  Idempotent: a second close is a no-op, so a
        drain→delete that already closed the entry is never
        double-counted by the forced-reclaim path."""
        if not self.enabled:
            return False
        src = reason or self.current_source(default="termination")
        with self._lock:
            if not self.enabled:
                return False
            entry = self._open.pop(entry_id, None)
            if entry is None:
                return False
            entry.closed_at = float(at)
            entry.close_reason = src
            self._closed.append(entry)
            self.entries_closed += 1
            self._accumulate(entry)
        from ..utils import metrics
        metrics.ledger_open_entries().set(len(self._open))
        self._check_drift(float(at))
        return True

    def record_reservation(self, *, nodepool: str, expected_dh: float,
                           at: float, ttl_s: float) -> bool:
        """Annotate a headroom placeholder's planned spend.  Kept out of
        the per-source capacity sums (see module docstring)."""
        if not self.enabled:
            return False
        with self._lock:
            if not self.enabled:
                return False
            self._reservations.append(Reservation(
                nodepool=nodepool or "", expected_dh=float(expected_dh),
                opened_at=float(at), ttl_s=float(ttl_s)))
        from ..utils import metrics
        metrics.ledger_entries().inc({"decision_source": "headroom"})
        return True

    # ---- aggregation ------------------------------------------------------
    def _accumulate(self, entry: LedgerEntry) -> None:  # graftlint: holds(_lock)
        end = entry.closed_at
        for agg, key in ((self._agg_source, entry.decision_source),
                         (self._agg_pool, entry.nodepool)):
            slot = agg.setdefault(key, {"expected_dh": 0.0,
                                        "realized_dh": 0.0, "entries": 0})
            slot["expected_dh"] += entry.expected_dh(end)
            slot["realized_dh"] += entry.realized_dh(end)
            slot["entries"] += 1

    def summary(self, now: float) -> Dict:
        """Deterministic rollup: closed aggregates + open entries accrued
        to `now`, so the per-source expected $·h sums match a cost
        integral taken at the same instant."""
        with self._lock:
            by_source = {k: dict(v) for k, v in self._agg_source.items()}
            by_pool = {k: dict(v) for k, v in self._agg_pool.items()}
            for entry in self._open.values():
                for agg, key in ((by_source, entry.decision_source),
                                 (by_pool, entry.nodepool)):
                    slot = agg.setdefault(key, {"expected_dh": 0.0,
                                                "realized_dh": 0.0,
                                                "entries": 0})
                    slot["expected_dh"] += entry.expected_dh(now)
                    slot["realized_dh"] += entry.realized_dh(now)
                    slot["entries"] += 1
            reservations_dh = sum(
                (r.expected_dh for r in self._reservations), 0.0)
            out = {
                "entries_opened": self.entries_opened,
                "entries_closed": self.entries_closed,
                "open": len(self._open),
                "by_decision_source": {
                    k: {"expected_dh": round(v["expected_dh"], 6),
                        "realized_dh": round(v["realized_dh"], 6),
                        "entries": v["entries"]}
                    for k, v in sorted(by_source.items())},
                "by_nodepool": {
                    k: {"expected_dh": round(v["expected_dh"], 6),
                        "realized_dh": round(v["realized_dh"], 6),
                        "entries": v["entries"],
                        "drift": round(self._drift_of(v), 6)}
                    for k, v in sorted(by_pool.items())},
                "headroom_reservations": {
                    "count": len(self._reservations),
                    "expected_dh": round(reservations_dh, 6)},
                "drift_alerts": self.drift_alerts,
            }
        return out

    def recent(self, limit: int = 50) -> List[Dict]:
        with self._lock:
            closed = [e.to_dict() for e in list(self._closed)[-limit:]]
            open_ = [e.to_dict() for _, e in sorted(self._open.items())]
        return closed + open_[:max(0, limit - len(closed))]

    # ---- drift detection --------------------------------------------------
    @staticmethod
    def _drift_of(slot: Dict[str, float]) -> float:
        exp = slot["expected_dh"]
        if exp <= 0.0:
            return 0.0
        return abs(slot["realized_dh"] - exp) / exp

    def _check_drift(self, now: float) -> None:
        """Per-nodepool expected-vs-realized drift over CLOSED entries
        (realized is only measurable at close).  Activation-edge
        publishing + the bus's own per-kind dedup keep a drifting storm
        at one incident per window."""
        fired: List[Dict] = []
        with self._lock:
            if not self.enabled:
                return
            for pool in sorted(self._agg_pool):
                slot = self._agg_pool[pool]
                if slot["entries"] < self._drift_min_entries:
                    continue
                drift = self._drift_of(slot)
                active = drift > self._drift_threshold
                was = self._drift_active.get(pool, False)
                if active and not was:
                    self.drift_alerts += 1
                    fired.append({"nodepool": pool,
                                  "drift": round(drift, 6),
                                  "expected_dh": round(slot["expected_dh"], 6),
                                  "realized_dh": round(slot["realized_dh"], 6),
                                  "at": now})
                self._drift_active[pool] = active
        from ..utils import metrics
        for detail in fired:
            metrics.ledger_drift_alerts().inc(
                {"nodepool": detail["nodepool"]})
            publish_incident("cost_drift", detail)

    # ---- warm-restart support (the `ledger` snapshot section) -------------
    def snapshot_state(self) -> Dict:
        with self._lock:
            return {
                "open": [e.to_dict() for _, e in sorted(self._open.items())],
                "closed": [e.to_dict() for e in self._closed],
                "reservations": [
                    {"nodepool": r.nodepool, "expected_dh": r.expected_dh,
                     "opened_at": r.opened_at, "ttl_s": r.ttl_s}
                    for r in self._reservations],
                "seen": list(self._seen),
                "agg_source": {k: dict(v)
                               for k, v in self._agg_source.items()},
                "agg_pool": {k: dict(v) for k, v in self._agg_pool.items()},
                "drift_active": dict(self._drift_active),
                "drift_alerts": self.drift_alerts,
                "entries_opened": self.entries_opened,
                "entries_closed": self.entries_closed,
            }

    def restore_state(self, state: Dict) -> None:
        def _entry(d: Dict) -> LedgerEntry:
            return LedgerEntry(
                id=str(d["id"]), decision_source=str(d["decision_source"]),
                nodepool=str(d["nodepool"]), pod_class=str(d["pod_class"]),
                expected_rate=float(d["expected_rate"]),
                realized_rate=float(d["realized_rate"]),
                opened_at=float(d["opened_at"]),
                fence_epoch=int(d["fence_epoch"]),
                trace_id=str(d["trace_id"]),
                closed_at=None if d["closed_at"] is None
                else float(d["closed_at"]),
                close_reason=str(d["close_reason"]))
        with self._lock:
            self._open = {str(d["id"]): _entry(d)
                          for d in state.get("open", [])}
            self._closed = deque((_entry(d) for d in state.get("closed", [])),
                                 maxlen=self._retention)
            self._reservations = deque(
                (Reservation(nodepool=str(r["nodepool"]),
                             expected_dh=float(r["expected_dh"]),
                             opened_at=float(r["opened_at"]),
                             ttl_s=float(r["ttl_s"]))
                 for r in state.get("reservations", [])),
                maxlen=self._retention)
            self._seen = OrderedDict(
                (str(k), None) for k in state.get("seen", []))
            self._agg_source = {str(k): dict(v) for k, v
                                in state.get("agg_source", {}).items()}
            self._agg_pool = {str(k): dict(v) for k, v
                              in state.get("agg_pool", {}).items()}
            self._drift_active = {str(k): bool(v) for k, v
                                  in state.get("drift_active", {}).items()}
            self.drift_alerts = int(state.get("drift_alerts", 0))
            self.entries_opened = int(state.get("entries_opened", 0))
            self.entries_closed = int(state.get("entries_closed", 0))


LEDGER = CostLedger()


def current_trace_id() -> str:
    """Trace id of the span currently open on this thread, "" when no
    trace is active (the sim's untraced paths)."""
    try:
        from ..utils.tracing import TRACER
        cur = TRACER.current()
        return cur.trace_id if cur is not None else ""
    except Exception:
        return ""
