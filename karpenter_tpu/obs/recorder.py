"""The FlightRecorder: ring + bus + bundles behind one gate.

Lifecycle: the manager builds one when the `FlightRecorder` gate is on,
hands it the same injectable clock every other subsystem runs on, wires
context callbacks (health snapshot, fencing/leader state, provenance,
trace export), then `arm()`s the global incident bus.  From that moment:

  * every manager tick calls `sample()` — a cadence-bounded pass over
    the metric registry into the history ring;
  * every trip-site `publish_incident` that clears the per-kind dedup
    window lands in `_capture`, which assembles one forensic bundle:
    the metric deltas over the preceding window, the trace ring, the
    full health snapshot, chaos/fencing state, and provenance for any
    pods the detail names — then stores it in memory (bounded) and,
    when a directory is configured, atomically on disk (bounded
    retention).

Capture runs inline on the tripping thread and is exception-proof: the
bus counts a sink error rather than re-raising into a reconcile, and a
failed disk write degrades to memory-only (counted) — the recorder must
never convert an incident into a second incident.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional

from ..utils import metrics
from .bundle import bundle_id, prune, write_bundle
from .incidents import BUS
from .ring import MetricsRing


class FlightRecorder:
    def __init__(self, clock: Callable[[], float], *,
                 cadence_s: float = 30.0,
                 window_s: float = 600.0,
                 dedup_s: float = 300.0,
                 retention: int = 32,
                 ring_slots: int = 512,
                 trace_cap: int = 64,
                 dirpath: Optional[str] = None,
                 registry=None):
        self._clock = clock
        self.window_s = float(window_s)
        self.dedup_s = float(dedup_s)
        self.retention = int(retention)
        self.trace_cap = int(trace_cap)
        self.dirpath = dirpath
        self._registry = registry if registry is not None else metrics.REGISTRY
        self.ring = MetricsRing(clock, cadence_s=cadence_s, slots=ring_slots)
        self.bundles: deque = deque(maxlen=self.retention)
        self._restored: List[Dict] = []   # summaries carried over a warm restart
        self._seq = 0
        self.write_errors = 0
        # context callbacks the manager wires after construction; each is
        # optional so the recorder also works bare in tests/tools
        self.health_cb: Optional[Callable[[], Dict]] = None
        self.fence_cb: Optional[Callable[[], Dict]] = None
        self.chaos_cb: Optional[Callable[[], Dict]] = None
        self.provenance_cb: Optional[Callable[[List[str]], List[Dict]]] = None
        self.traces_cb: Optional[Callable[[], List[Dict]]] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def arm(self) -> None:
        BUS.arm(self._capture, self._clock, dedup_s=self.dedup_s,
                on_suppressed=self._suppressed)

    def disarm(self) -> None:
        BUS.disarm()

    # ------------------------------------------------------------------
    # sampling (called from the manager tick; cadence-bounded)
    # ------------------------------------------------------------------
    def sample(self) -> bool:
        took = self.ring.sample(self._registry)
        if took:
            metrics.obs_ring_samples().inc()
            metrics.obs_ring_entries().set(float(len(self.ring)))
        return took

    # ------------------------------------------------------------------
    # capture (the bus sink)
    # ------------------------------------------------------------------
    def _suppressed(self, kind: str, now: float) -> None:
        """A deduped repeat extends the open episode rather than opening
        a new bundle: the newest bundle of this kind grows its window
        end (and a repeat counter), so a storm that trips every tick for
        ten minutes is recorded as one incident COVERING ten minutes.
        Memory-only — the on-disk copy keeps the window at capture."""
        metrics.incident_suppressed().inc({"kind": kind})
        for b in reversed(self.bundles):
            if b["kind"] == kind:
                b["window"][1] = max(float(b["window"][1]), now)
                b["repeats"] = b.get("repeats", 0) + 1
                break

    def _context(self, cb: Optional[Callable], *args):
        if cb is None:
            return None
        try:
            return cb(*args)
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _capture(self, kind: str, detail: Dict, now: float) -> None:
        self._seq += 1
        bid = bundle_id(now, kind, self._seq)
        traces = self._context(self.traces_cb) or []
        bundle = {
            "id": bid,
            "kind": kind,
            "t": now,
            "seq": self._seq,
            "window": [now - self.window_s, now],
            "detail": detail,
            "metrics": self.ring.deltas(self.window_s, now),
            "ring_entries": len(self.ring),
            "traces": traces[:self.trace_cap],   # tracer export is newest-first
            "health": self._context(self.health_cb),
            "chaos": self._context(self.chaos_cb),
            "fencing": self._context(self.fence_cb),
            "provenance": self._context(
                self.provenance_cb, list(detail.get("pods", []))),
            "suppressed": dict(BUS.suppressed),
        }
        self.bundles.append(bundle)
        metrics.incident_bundles().inc({"kind": kind})
        if self.dirpath:
            try:
                write_bundle(self.dirpath, bundle)
                prune(self.dirpath, self.retention)
            except OSError:
                self.write_errors += 1
                metrics.incident_write_errors().inc()

    # ------------------------------------------------------------------
    # export (report section, /debug/incidents, snapshot section)
    # ------------------------------------------------------------------
    @staticmethod
    def _summary_entry(b: Dict) -> Dict:
        return {"id": b["id"], "kind": b["kind"], "t": b["t"],
                "window": list(b["window"]),
                "repeats": int(b.get("repeats", 0))}

    def summary(self) -> Dict:
        """Deterministic view for the sim report and `/debug/incidents`:
        ids/kinds/windows plus bus counters — no wall-clock payloads."""
        entries = list(self._restored) + \
            [self._summary_entry(b) for b in self.bundles]
        by_kind: Dict[str, int] = {}
        for e in entries:
            by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
        return {
            "bundles": entries,
            "by_kind": by_kind,
            "published": dict(BUS.published),
            "suppressed": dict(BUS.suppressed),
            "sink_errors": BUS.sink_errors,
            "write_errors": self.write_errors,
            "ring": {"entries": len(self.ring),
                     "samples_taken": self.ring.samples_taken},
        }

    def get_bundle(self, bid: str) -> Optional[Dict]:
        for b in self.bundles:
            if b["id"] == bid:
                return b
        if self.dirpath:
            from .bundle import read_bundle
            return read_bundle(self.dirpath, bid)
        return None

    def snapshot_state(self) -> Dict:
        return {
            "ring": self.ring.snapshot_state(),
            "bus": BUS.snapshot_state(),
            "seq": self._seq,
            "bundles": [self._summary_entry(b) for b in self.bundles],
            "restored": list(self._restored),
        }

    def restore_state(self, state: Dict) -> None:
        """Warm-restart: restore the ring cursor and the bus dedup state
        (so a trip captured just before the restart is not re-captured
        right after it), and carry the bundle summaries forward (so the
        incident record is not lost).  Full payloads live on disk when a
        directory is configured; memory-only runs keep the summary."""
        self.ring.restore_state(dict(state.get("ring", {})))
        BUS.restore_state(dict(state.get("bus", {})))
        self._seq = int(state.get("seq", 0))
        self._restored = list(state.get("restored", [])) + \
            [dict(e) for e in state.get("bundles", [])]
