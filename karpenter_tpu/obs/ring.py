"""Bounded in-process metrics time-series ring.

Samples every registered metric family (via `Registry.sample_all`) on a
cadence measured against the *injectable* clock — the sim hands it the
virtual clock, so a 24h replay records 24h of virtual history
deterministically and DT001 never sees a wall read.  The ring is a
fixed-size deque: steady-state memory is `slots × series_count` floats,
and sampling never blocks a reconcile (it runs inline in the manager
tick, bounded by one pass over the registry).

The payload of one sample is `{series_key: value}` where `series_key`
is the Prometheus-style `name{label="v",...}` string — stable, sorted,
and directly diffable for the bundle's metric-delta view.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional, Tuple


def series_key(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRing:
    def __init__(self, clock: Callable[[], float], cadence_s: float = 30.0,
                 slots: int = 512):
        self._clock = clock
        self.cadence_s = float(cadence_s)
        self.slots = int(slots)
        self._ring: deque = deque(maxlen=self.slots)  # (t, {key: value})
        self._last_t: Optional[float] = None
        self.samples_taken = 0

    def sample(self, registry) -> bool:
        """Take one sample if the cadence has elapsed.  Returns True iff
        a sample was recorded (the caller incs the sample counter on
        True, keeping the metric out of the disarmed path)."""
        now = self._clock()
        if self._last_t is not None and (now - self._last_t) < self.cadence_s:
            return False
        snap: Dict[str, float] = {}
        for name, labels, value in registry.sample_all():
            snap[series_key(name, labels)] = float(value)
        self._ring.append((now, snap))
        self._last_t = now
        self.samples_taken += 1
        return True

    def __len__(self) -> int:
        return len(self._ring)

    def tip(self) -> Tuple[float, Dict]:
        """Newest sample — the registry as of the last cadence pass (the
        SLO engine reads cumulative counter tips from here instead of
        re-walking the registry)."""
        if not self._ring:
            return (0.0, {})
        return self._ring[-1]

    def window(self, start: float, end: float) -> List[Tuple[float, Dict]]:
        return [(t, snap) for t, snap in self._ring if start <= t <= end]

    def deltas(self, window_s: float, now: float) -> Dict:
        """Per-series change over the trailing window: newest sample vs
        the baseline at the window start — the newest sample at-or-before
        `now - window_s` (so counter deltas cover the whole window), or
        the oldest sample inside it when history is shorter.  Only
        changed series are reported — a forensic bundle wants what moved,
        not the whole registry."""
        if not self._ring:
            return {"from_t": None, "to_t": None, "changed": {}}
        lo = now - float(window_s)
        base_t, base = None, None
        for t, snap in self._ring:
            if t <= lo:
                base_t, base = t, snap      # newest before the window
            else:
                if base is None:
                    base_t, base = t, snap  # oldest inside the window
                break
        tip_t, tip = self._ring[-1]
        if base is None:
            base_t, base = tip_t, tip
        changed: Dict[str, float] = {}
        for key in sorted(tip):
            d = tip[key] - base.get(key, 0.0)
            if d != 0.0:
                changed[key] = round(d, 9)
        return {"from_t": base_t, "to_t": tip_t, "changed": changed}

    # ---- warm-restart support: the cursor, not the payload ----
    def snapshot_state(self) -> Dict:
        return {"last_t": self._last_t, "samples_taken": self.samples_taken}

    def restore_state(self, state: Dict) -> None:
        last_t = state.get("last_t")
        self._last_t = float(last_t) if last_t is not None else None
        self.samples_taken = int(state.get("samples_taken", 0))
