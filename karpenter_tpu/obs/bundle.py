"""Forensic bundle files: atomic writes, bounded retention, tolerant reads.

One bundle is one JSON file named `incident-<id>.json` where `<id>` is
`<t_ms>-<kind>-<seq>` — millisecond injectable-clock time (virtual in
sim, so ids are deterministic), the incident kind, and a monotone
per-process sequence number that breaks ties when several kinds trip in
the same tick.  Writes follow the repo's snapshot discipline: serialize
to `<name>.tmp`, then `os.replace` — a crash mid-write leaves the
previous bundle set intact, never a half-file under the final name.

Read-back is forensic-grade paranoid: a truncated or corrupted file (the
very crash the recorder exists to explain may have interrupted the
write) comes back as `{"id": ..., "corrupt": true, "error": ...}` rather
than an exception, so one bad bundle never hides its siblings from
`/debug/incidents` or `tools/incident_report.py`.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

_PREFIX = "incident-"
_SUFFIX = ".json"


def bundle_id(t: float, kind: str, seq: int) -> str:
    return f"{int(round(t * 1000.0)):013d}-{kind}-{seq:04d}"


def bundle_path(dirpath: str, bid: str) -> str:
    return os.path.join(dirpath, f"{_PREFIX}{bid}{_SUFFIX}")


def write_bundle(dirpath: str, bundle: Dict) -> str:
    """Atomically persist one bundle; returns the final path."""
    os.makedirs(dirpath, exist_ok=True)
    path = bundle_path(dirpath, bundle["id"])
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(bundle, sort_keys=True, indent=2,
                            default=str) + "\n")
    os.replace(tmp, path)
    return path


def list_bundle_ids(dirpath: str) -> List[str]:
    """Bundle ids on disk, oldest first (ids sort chronologically)."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    out = [n[len(_PREFIX):-len(_SUFFIX)] for n in names
           if n.startswith(_PREFIX) and n.endswith(_SUFFIX)]
    return sorted(out)


def read_bundle(dirpath: str, bid: str) -> Optional[Dict]:
    """One bundle by id; `None` if absent, a `corrupt` stub if unreadable."""
    path = bundle_path(dirpath, bid)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except OSError:
        return None
    except ValueError as e:
        return {"id": bid, "corrupt": True, "error": str(e)}
    if not isinstance(doc, dict):
        return {"id": bid, "corrupt": True,
                "error": f"expected object, got {type(doc).__name__}"}
    return doc


def prune(dirpath: str, retention: int) -> List[str]:
    """Delete the oldest bundles past `retention`; returns deleted ids."""
    ids = list_bundle_ids(dirpath)
    doomed = ids[:-retention] if retention > 0 else ids
    deleted = []
    for bid in doomed:
        try:
            os.remove(bundle_path(dirpath, bid))
            deleted.append(bid)
        except OSError:
            pass
    return deleted
