"""Declarative SLI registry + error-budget / burn-rate engine.

The repo *records* everything (metric ring, trace ring, incident
bundles) but *judges* nothing: no layer turns raw counters into "are we
meeting our objectives, and how fast are we spending the error budget".
This module is that layer, computed entirely as recording rules over the
existing `MetricsRing` — windowed counter deltas and gauge samples on
the *injectable* clock, so the sim evaluates 4 virtual hours of SLOs
deterministically and DT001 never sees a wall read.

Three SLI computation modes cover the registry:

  * ``histogram_threshold`` — good = observations in the cumulative
    bucket at ``threshold`` (``F_bucket{le=...}``), total = ``F_count``;
    time-to-bind and tick-duration SLIs.
  * ``counter_ratio`` — bad/good counter families summed across labels;
    unschedulable-ratio and fence-refusal SLIs.
  * ``gauge_uptime`` — fraction of evaluations where every series of a
    gauge family sits at-or-below ``max_value`` (absent series = healthy,
    the gauge was never set); solver/decode ladder uptime.

Error budgets accumulate from registry tips with a counter-reset guard
(a warm restart zeroes the registry; ``tip < last_seen`` treats the tip
itself as the delta, so pre-restart history — restored from the
snapshot's ``slo`` section — is never double-counted).  Burn rates are
evaluated multi-window multi-burn-rate (SRE workbook): a fast 5m/1h
pair at 14.4x and a slow 30m/6h pair at 6x; an alert activates only
when BOTH windows of a pair burn, and the activation edge publishes one
``slo_burn`` incident through the `IncidentBus` — whose per-kind dedup
turns a flapping burn into exactly one bundle per window.

graftlint OB007 reads ``DEFAULT_SLIS`` statically: every family literal
in an ``SLI(...)`` spec must resolve to a registered metric family.
"""

from __future__ import annotations

from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .incidents import publish_incident
from .ring import MetricsRing

# (short_window_s, long_window_s, burn-rate threshold) — the SRE-workbook
# pairing: the fast pair catches cliffs, the slow pair slow leaks.
BURN_WINDOW_PAIRS: Tuple[Tuple[float, float, float], ...] = (
    (300.0, 3600.0, 14.4),
    (1800.0, 21600.0, 6.0),
)

SLI_MODES = ("histogram_threshold", "counter_ratio", "gauge_uptime")


@dataclass(frozen=True)
class SLI:
    """One service-level indicator, declared against literal metric
    family names (the OB007 contract: every name here must be a
    registered family, modulo the ``_count``/``_bucket``/``_sum``
    histogram suffixes)."""
    name: str
    objective: float                 # e.g. 0.99 → 1% error budget
    mode: str
    description: str = ""
    families: Tuple[str, ...] = ()   # histogram_threshold / gauge_uptime
    bad_families: Tuple[str, ...] = ()    # counter_ratio numerator
    good_families: Tuple[str, ...] = ()   # counter_ratio denominator part
    threshold: float = 0.0           # histogram_threshold bucket bound
    max_value: float = 0.0           # gauge_uptime healthy ceiling

    def validate(self) -> None:
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"SLI {self.name}: objective must be in (0,1)")
        if self.mode not in SLI_MODES:
            raise ValueError(f"SLI {self.name}: unknown mode {self.mode!r}")
        if self.mode in ("histogram_threshold", "gauge_uptime") \
                and not self.families:
            raise ValueError(f"SLI {self.name}: needs families")
        if self.mode == "counter_ratio" and not self.bad_families:
            raise ValueError(f"SLI {self.name}: needs bad_families")

    def all_families(self) -> Tuple[str, ...]:
        return self.families + self.bad_families + self.good_families


DEFAULT_SLIS: Tuple[SLI, ...] = (
    SLI(name="bind_latency", objective=0.99, mode="histogram_threshold",
        families=("karpenter_pods_bound_duration_seconds",),
        threshold=10.0,
        description="pods bound within the latency bucket bound"),
    SLI(name="tick_duration", objective=0.99, mode="histogram_threshold",
        families=("controller_runtime_reconcile_time_seconds",),
        threshold=1.0,
        description="controller reconciles completing within 1s"),
    SLI(name="unschedulable_ratio", objective=0.95, mode="counter_ratio",
        bad_families=("karpenter_provenance_records_total",),
        good_families=("karpenter_pods_bound_duration_seconds_count",),
        description="pods placed vs unschedulable-provenance records"),
    SLI(name="solver_uptime", objective=0.999, mode="gauge_uptime",
        families=("karpenter_degradation_active_rung",),
        max_value=2.0,
        description="solver ladder above the greedy floor"),
    SLI(name="decode_uptime", objective=0.999, mode="gauge_uptime",
        families=("karpenter_decode_demoted",),
        max_value=0.0,
        description="device decode not demoted to host assembly"),
    SLI(name="fence_refusal", objective=0.999, mode="counter_ratio",
        bad_families=("karpenter_leader_fence_refusals_total",),
        good_families=("karpenter_nodeclaims_launched",
                       "karpenter_snapshot_writes_total"),
        description="guarded mutations vs stale-fence refusals"),
)


def _family_series_sum(snap: Dict[str, float], family: str) -> float:
    """Sum every series of `family` in one ring payload (exact name or
    any labeled variant)."""
    total = snap.get(family, 0.0)
    prefix = family + "{"
    for key, value in snap.items():
        if key.startswith(prefix):
            total += value
    return total


def _bucket_series_sum(snap: Dict[str, float], family: str,
                       threshold: float) -> float:
    """Cumulative-bucket sum for `family` at `le=threshold` across all
    label sets (series keys carry sorted labels, so ``le=`` may sit
    anywhere inside the braces)."""
    needle = f'le="{threshold!r}"'
    prefix = f"{family}_bucket{{"
    total = 0.0
    for key, value in snap.items():
        if key.startswith(prefix) and needle in key:
            total += value
    return total


@dataclass
class _BudgetState:
    """Cumulative good/bad accounting for one SLI, with the last-seen
    tips the counter-reset guard compares against."""
    bad: float = 0.0
    total: float = 0.0
    last_bad_tip: float = 0.0
    last_total_tip: float = 0.0
    alert_active: bool = False
    alerts: int = 0
    last_burns: Dict[str, float] = field(default_factory=dict)


def _guarded_delta(tip: float, last: float) -> float:
    """Counter delta with restart guard: a tip below the last-seen value
    means the registry reset (kill -9 warm restart) — the tip itself is
    the post-restart delta."""
    return tip - last if tip >= last else tip


class SLOEngine:
    """Recording rules + error budgets + multi-window burn alerts over a
    `MetricsRing`.  Mirrors the `FlightRecorder` lifecycle: constructed
    by the manager under the `SLOEngine` gate, ticked from the manager
    loop, snapshot/restored through the operator snapshot's ``slo``
    section.  When the flight recorder is also armed the engine shares
    its ring (one sampling pass); otherwise it owns one and samples it
    on its own cadence."""

    def __init__(self, clock: Callable[[], float], *,
                 registry=None,
                 ring: Optional[MetricsRing] = None,
                 slis: Tuple[SLI, ...] = DEFAULT_SLIS,
                 eval_cadence_s: float = 60.0,
                 sample_cadence_s: float = 30.0,
                 ring_slots: int = 512,
                 window_pairs: Tuple[Tuple[float, float, float], ...]
                 = BURN_WINDOW_PAIRS):
        if registry is None:
            from ..utils import metrics
            registry = metrics.REGISTRY
        for sli in slis:
            sli.validate()
        self._clock = clock
        self.registry = registry
        self._owns_ring = ring is None
        self.ring = ring if ring is not None else MetricsRing(
            clock, cadence_s=sample_cadence_s, slots=ring_slots)
        self.slis = tuple(slis)
        self.eval_cadence_s = float(eval_cadence_s)
        self.window_pairs = tuple(window_pairs)
        self._budget: Dict[str, _BudgetState] = {
            s.name: _BudgetState() for s in self.slis}
        self._last_eval: Optional[float] = None
        self._window_cache: Dict = {}
        # per-SLI {sample_t: healthy} memo — a ring sample is immutable,
        # so its gauge verdict never changes; without this every eval
        # re-scans every sample in the 6h window against the registry
        self._gauge_memo: Dict[str, Dict[float, bool]] = {}
        self.evals = 0

    # ---- tick -------------------------------------------------------------
    def tick(self) -> bool:
        """Sample (when the engine owns its ring) and evaluate on the
        cadence.  Returns True iff an evaluation ran."""
        now = self._clock()
        if self._owns_ring:
            self.ring.sample(self.registry)
        if self._last_eval is not None and \
                (now - self._last_eval) < self.eval_cadence_s:
            return False
        if not len(self.ring):
            return False
        self._last_eval = now
        self.evals += 1
        tip = self._tip_snap()
        from ..utils import metrics
        metrics.slo_evaluations().inc()
        # one ring scan per unique window per eval, shared by every SLI
        # (deltas sorts the whole tip payload — per-SLI recomputation
        # would multiply that by the registry size)
        self._window_cache = {}
        for sli in self.slis:
            self._evaluate(sli, tip, now)
        self._window_cache = {}
        return True

    def _tip_snap(self) -> Dict[str, float]:
        # newest ring payload = the registry as of the latest sample
        return self.ring.tip()[1] if len(self.ring) else {}

    # ---- per-SLI evaluation ----------------------------------------------
    def _counters_of(self, sli: SLI, snap: Dict[str, float]
                     ) -> Tuple[float, float]:
        """(bad, total) cumulative counters for one SLI from one ring
        payload."""
        if sli.mode == "histogram_threshold":
            family = sli.families[0]
            total = _family_series_sum(snap, f"{family}_count")
            good = _bucket_series_sum(snap, family, sli.threshold)
            return max(0.0, total - good), total
        if sli.mode == "counter_ratio":
            bad = sum(_family_series_sum(snap, f)
                      for f in sli.bad_families)
            good = sum(_family_series_sum(snap, f)
                       for f in sli.good_families)
            return bad, bad + good
        raise AssertionError(sli.mode)   # gauge_uptime handled separately

    def _gauge_healthy(self, sli: SLI, snap: Dict[str, float]) -> bool:
        """Every series of the gauge family at-or-below the ceiling;
        absent series are healthy (the gauge was never set)."""
        for family in sli.families:
            if snap.get(family, 0.0) > sli.max_value:
                return False
            prefix = family + "{"
            for key, value in snap.items():
                if key.startswith(prefix) and value > sli.max_value:
                    return False
        return True

    def _evaluate(self, sli: SLI, tip: Dict[str, float],
                  now: float) -> None:
        from ..utils import metrics
        state = self._budget[sli.name]
        budget_frac = 1.0 - sli.objective
        if sli.mode == "gauge_uptime":
            healthy = self._gauge_healthy(sli, tip)
            state.total += 1.0
            if not healthy:
                state.bad += 1.0
            burns = self._gauge_burns(sli, now, budget_frac)
        else:
            bad_tip, total_tip = self._counters_of(sli, tip)
            state.bad += max(0.0, _guarded_delta(bad_tip,
                                                 state.last_bad_tip))
            state.total += max(0.0, _guarded_delta(total_tip,
                                                   state.last_total_tip))
            state.last_bad_tip = bad_tip
            state.last_total_tip = total_tip
            burns = self._counter_burns(sli, now, budget_frac)
        state.last_burns = burns
        for window, burn in burns.items():
            metrics.slo_burn_rate().set(burn, {"slo": sli.name,
                                               "window": window})
        metrics.slo_budget_remaining().set(
            self._budget_remaining(sli, state), {"slo": sli.name})
        self._update_alert(sli, state, burns, now)

    def _window_deltas(self, window_s: float, now: float) -> Dict[str, float]:
        key = ("d", window_s)
        cached = self._window_cache.get(key)
        if cached is None:
            cached = self.ring.deltas(window_s, now)["changed"]
            self._window_cache[key] = cached
        return cached

    def _window_samples(self, window_s: float, now: float):
        key = ("w", window_s)
        cached = self._window_cache.get(key)
        if cached is None:
            cached = self.ring.window(now - window_s, now)
            self._window_cache[key] = cached
        return cached

    def _counter_burns(self, sli: SLI, now: float,
                       budget_frac: float) -> Dict[str, float]:
        burns: Dict[str, float] = {}
        for short_s, long_s, _thr in self.window_pairs:
            for window_s in (short_s, long_s):
                key = f"{int(window_s)}s"
                if key in burns:
                    continue
                delta = self._window_deltas(window_s, now)
                bad_w, total_w = self._counters_of(sli, delta)
                if total_w <= 0.0:
                    burns[key] = 0.0
                else:
                    burns[key] = round(
                        (bad_w / total_w) / budget_frac, 6)
        return burns

    def _gauge_burns(self, sli: SLI, now: float,
                     budget_frac: float) -> Dict[str, float]:
        memo = self._gauge_memo.setdefault(sli.name, {})
        max_w = max(long_s for _s, long_s, _t in self.window_pairs)
        samples = self._window_samples(max_w, now)
        for t, snap in samples:
            if t not in memo:
                memo[t] = self._gauge_healthy(sli, snap)
        if len(memo) > 2 * len(samples) + 16:
            cutoff = now - max_w
            for t in [t for t in memo if t < cutoff]:
                del memo[t]
        # samples are time-ordered: one prefix-sum of bad verdicts serves
        # every window via bisect instead of a scan per window
        ts = [t for t, _snap in samples]
        bad_prefix = [0]
        for t in ts:
            bad_prefix.append(bad_prefix[-1] + (0 if memo[t] else 1))
        burns: Dict[str, float] = {}
        for short_s, long_s, _thr in self.window_pairs:
            for window_s in (short_s, long_s):
                key = f"{int(window_s)}s"
                if key in burns:
                    continue
                i = bisect_left(ts, now - window_s)
                count = len(ts) - i
                if count <= 0:
                    burns[key] = 0.0
                    continue
                bad = bad_prefix[-1] - bad_prefix[i]
                burns[key] = round(
                    (bad / count) / budget_frac, 6)
        return burns

    def _update_alert(self, sli: SLI, state: _BudgetState,
                      burns: Dict[str, float], now: float) -> None:
        active = any(
            burns.get(f"{int(short_s)}s", 0.0) > thr and
            burns.get(f"{int(long_s)}s", 0.0) > thr
            for short_s, long_s, thr in self.window_pairs)
        if active and not state.alert_active:
            state.alerts += 1
            from ..utils import metrics
            metrics.slo_burn_alerts().inc({"slo": sli.name})
            publish_incident("slo_burn", {
                "slo": sli.name, "objective": sli.objective,
                "burns": dict(sorted(burns.items())),
                "budget_remaining": round(
                    self._budget_remaining(sli, state), 6),
                "at": now})
        state.alert_active = active

    @staticmethod
    def _budget_remaining(sli: SLI, state: _BudgetState) -> float:
        if state.total <= 0.0:
            return 1.0
        consumed = (state.bad / state.total) / (1.0 - sli.objective)
        return 1.0 - consumed

    # ---- surfaces ---------------------------------------------------------
    def summary(self) -> Dict:
        """Deterministic rollup for /debug/slo and the sim report's
        gated ``slo.budgets`` sub-section."""
        slos: Dict[str, Dict] = {}
        for sli in self.slis:
            state = self._budget[sli.name]
            slos[sli.name] = {
                "objective": sli.objective,
                "mode": sli.mode,
                "bad": round(state.bad, 6),
                "total": round(state.total, 6),
                "budget_remaining": round(
                    self._budget_remaining(sli, state), 6),
                "burn": dict(sorted(state.last_burns.items())),
                "alerting": state.alert_active,
                "alerts": state.alerts,
            }
        return {"evaluations": self.evals,
                "ring_samples": len(self.ring),
                "slos": slos}

    # ---- warm-restart support (the `slo` snapshot section) ----------------
    def snapshot_state(self) -> Dict:
        return {
            "last_eval": self._last_eval,
            "evals": self.evals,
            "ring": self.ring.snapshot_state() if self._owns_ring else None,
            "budgets": {
                name: {"bad": st.bad, "total": st.total,
                       "last_bad_tip": st.last_bad_tip,
                       "last_total_tip": st.last_total_tip,
                       "alert_active": st.alert_active,
                       "alerts": st.alerts,
                       "last_burns": dict(st.last_burns)}
                for name, st in sorted(self._budget.items())},
        }

    def restore_state(self, state: Dict) -> None:
        last_eval = state.get("last_eval")
        self._last_eval = float(last_eval) if last_eval is not None else None
        self.evals = int(state.get("evals", 0))
        if self._owns_ring and state.get("ring") is not None:
            self.ring.restore_state(state["ring"])
        for name, st in state.get("budgets", {}).items():
            cur = self._budget.get(name)
            if cur is None:
                continue    # SLI registry changed across restart
            cur.bad = float(st.get("bad", 0.0))
            cur.total = float(st.get("total", 0.0))
            cur.last_bad_tip = float(st.get("last_bad_tip", 0.0))
            cur.last_total_tip = float(st.get("last_total_tip", 0.0))
            cur.alert_active = bool(st.get("alert_active", False))
            cur.alerts = int(st.get("alerts", 0))
            cur.last_burns = {str(k): float(v) for k, v
                              in dict(st.get("last_burns", {})).items()}
