"""The incident trigger bus.

Every trip site in the fault-handling stack publishes here — circuit
open/quarantine, watchdog trip, solver/decode ladder demotion, fencing
refusal, cold-restore fallback, parity-probe mismatch, leader loss — and
graftlint OB006 keeps the set closed the same way RS004 keeps the
snapshot/cloud mutation funnels closed: a trip counter incremented
without a `publish_incident` in the same function is a lint finding.

The bus is process-global and DISARMED by default: `publish_incident`
is a single boolean check until a `FlightRecorder` arms it, so the hot
reconcile path pays nothing when the gate is off (the same zero-cost
pattern as `CHAOS.enabled`).  When armed, publishes are deduplicated
per kind inside a rate-limit window — a chaos storm that trips the same
circuit every tick produces one bundle per window, not a bundle flood —
and delivery happens inline on the tripping thread but is hard-bounded:
a sink failure is counted, never raised back into a reconcile.

stdlib-only on purpose: watchdog/fencing/health sit below utils.metrics
in the import order and must be able to publish without cycles.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

# Closed registry of incident kinds (the `kind` label of
# karpenter_incident_bundles_total stays enumerable, like chaos POINTS
# and watchdog PHASES).  One kind per trip-site family:
INCIDENT_KINDS = frozenset({
    "circuit_open",        # supervisor circuit opened / controller quarantined
    "watchdog_trip",       # hard deadline abandoned a phase
    "solver_demotion",     # SolverHealth ladder demoted a rung
    "decode_demotion",     # DecodeHealth breaker demoted to host decode
    "fence_refusal",       # stale fencing epoch refused a guarded mutation
    "snapshot_fallback",   # warm restore fell back to a cold rebuild
    "parity_mismatch",     # arena parity probe found divergence
    "leader_loss",         # leadership lost mid-term (deposed, not released)
    "slo_burn",            # error budget burning in both windows of a pair
    "cost_drift",          # ledger expected-vs-realized $·h drift per pool
    "gang_rejected",       # all-or-nothing gang admission rejected a gang
})


class IncidentBus:
    """Per-kind deduplicating publish/subscribe seam for trip sites.

    `armed` is the fast path: False (the default) makes `publish` a
    near-free early return.  Arming installs a sink callback, the
    injectable clock the dedup window is measured on, and the window
    itself.  All bookkeeping is behind a lock because watchdog trips
    arrive from worker threads while the manager thread reconciles.
    """

    def __init__(self) -> None:
        self.armed = False
        self._lock = threading.Lock()
        self._clock: Callable[[], float] = time.time  # reference, never read while disarmed
        self._sink: Optional[Callable[[str, Dict, float], None]] = None
        self._on_suppressed: Optional[Callable[[str, float], None]] = None
        self._dedup_s = 300.0
        self._last: Dict[str, float] = {}
        self.published: Dict[str, int] = {}
        self.suppressed: Dict[str, int] = {}
        self.sink_errors = 0

    def arm(self, sink: Callable[[str, Dict, float], None],
            clock: Callable[[], float],
            dedup_s: float = 300.0,
            on_suppressed: Optional[Callable[[str, float], None]] = None
            ) -> None:
        with self._lock:
            self._sink = sink
            self._clock = clock
            self._dedup_s = float(dedup_s)
            self._on_suppressed = on_suppressed
            self.armed = True

    def disarm(self) -> None:
        with self._lock:
            self.armed = False
            self._sink = None
            self._on_suppressed = None
            self._last.clear()
            self.published.clear()
            self.suppressed.clear()
            self.sink_errors = 0

    def publish(self, kind: str, detail: Optional[Dict] = None) -> bool:
        """Publish one trip.  Returns True iff the sink saw it (False =
        disarmed or deduplicated).  Never raises into the caller."""
        if not self.armed:
            return False
        if kind not in INCIDENT_KINDS:
            raise ValueError(f"unregistered incident kind: {kind!r} "
                             f"(add it to obs.incidents.INCIDENT_KINDS)")
        with self._lock:
            if not self.armed or self._sink is None:
                return False
            now = self._clock()
            last = self._last.get(kind)
            if last is not None and (now - last) < self._dedup_s:
                self.suppressed[kind] = self.suppressed.get(kind, 0) + 1
                cb = self._on_suppressed
                if cb is not None:
                    # the recorder uses (kind, now) to extend the open
                    # episode's window — a deduped storm is one growing
                    # incident, not a blind spot
                    try:
                        cb(kind, now)
                    except Exception:
                        pass
                return False
            self._last[kind] = now
            self.published[kind] = self.published.get(kind, 0) + 1
            sink = self._sink
        try:
            sink(kind, dict(detail or {}), now)
        except Exception:
            with self._lock:
                self.sink_errors += 1
            return False
        return True

    # ---- warm-restart support (the `incidents` snapshot section) ----
    def snapshot_state(self) -> Dict:
        """Dedup bookkeeping only — enough that a warm restart neither
        replays a just-captured incident nor forgets the counts."""
        with self._lock:
            return {"last": dict(self._last),
                    "published": dict(self.published),
                    "suppressed": dict(self.suppressed)}

    def restore_state(self, state: Dict) -> None:
        with self._lock:
            self._last = {str(k): float(v)
                          for k, v in dict(state.get("last", {})).items()}
            self.published = {str(k): int(v) for k, v
                              in dict(state.get("published", {})).items()}
            self.suppressed = {str(k): int(v) for k, v
                               in dict(state.get("suppressed", {})).items()}


BUS = IncidentBus()


def publish_incident(kind: str, detail: Optional[Dict] = None) -> bool:
    """The one seam trip sites call (graftlint OB006 pattern-matches this
    name).  Free when the bus is disarmed."""
    return BUS.publish(kind, detail)
