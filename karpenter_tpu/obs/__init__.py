"""Flight recorder: incident trigger bus, metric history ring, bundles.

The fault-handling stack (supervisor circuits, watchdogs, the solver and
decode ladders, fencing refusals, cold-restore fallbacks) already *counts*
everything, but counters are point-in-time: by the time a human looks at
a 3am circuit-open, the evidence is gone.  This package captures it at
the moment of the trip:

  * `incidents` — the process-global trigger bus every trip site
    publishes to (`publish_incident`).  Disarmed by default: a single
    boolean check and the trip site has paid its entire cost.
  * `ring` — a bounded metrics time-series ring sampled on the
    *injectable* clock, so the sim records virtual time deterministically
    and DT001 stays clean.
  * `bundle` — atomic (tmp + os.replace) forensic bundle files with
    bounded retention and corruption-tolerant read-back.
  * `recorder` — the `FlightRecorder` that ties them together behind the
    `FlightRecorder` feature gate (default off; gate-off runs are
    byte-identical).
  * `slo` — the declarative SLI registry + error-budget/burn-rate engine
    evaluated as recording rules over the ring (the `SLOEngine` gate).
  * `ledger` — the per-decision cost ledger attributing $·h to the
    launch/terminate decisions that spent it, with expected-vs-realized
    drift detection (same gate as `slo`).

Import discipline: `incidents` is stdlib-only so the low-level trip
sites (utils/watchdog.py, utils/fencing.py, ops/health.py, …) can import
it without cycles; `ledger` and `slo` keep their utils.metrics imports
lazy for the same reason (the provider's launch funnel hooks the
ledger); only `recorder` reaches back into utils eagerly.
"""

from .incidents import BUS, INCIDENT_KINDS, IncidentBus, publish_incident
from .ledger import DECISION_SOURCES, LEDGER, CostLedger

__all__ = ["BUS", "INCIDENT_KINDS", "IncidentBus", "publish_incident",
           "DECISION_SOURCES", "LEDGER", "CostLedger"]
