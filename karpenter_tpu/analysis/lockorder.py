"""Test-time lock-order recorder.

The static checker (analysis/locks.py) proves writes happen under the
right lock; it cannot prove locks are taken in a consistent *order*
across threads — the refinery daemon, batcher flushers, manager HTTP
workers, and metrics scrapes all interleave.  This module records the
order at runtime and fails the suite on observed inversions.

Design: components create their locks through `named_lock("role")`.
When the recorder is inactive (production, and any test that doesn't
opt in) that returns a plain `threading.Lock` — zero overhead.  A test
session that enables `RECORDER` first (tests/conftest.py does, unless
KARPENTER_TPU_LOCK_ORDER=0) gets recording proxies instead: each
acquire records `held-lock → new-lock` edges in a process-wide order
graph keyed by role name (instances share a role; ordering discipline
is a property of roles, not objects).  Self-edges are ignored
(re-entrant RLock roles and sibling instances of one role).  A cycle in
the graph — most commonly A→B on one thread and B→A on another — is a
potential deadlock even if the run never actually deadlocked.

`RECORDER.inversions()` returns the offending cycles with the
stack-free witness edges (role names + thread names) so the failure
message names the two code paths to reconcile.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set, Tuple


class LockOrderRecorder:
    def __init__(self) -> None:
        self.enabled = False
        self._meta = threading.Lock()
        # (held, acquired) -> witness "thread=... count=N"
        self._edges: Dict[Tuple[str, str], str] = {}
        self._held = threading.local()

    # ---- lifecycle ----
    def enable(self) -> None:
        self.enabled = True

    def reset(self) -> None:
        with self._meta:
            self._edges.clear()

    # ---- recording (called by _RecordingLock) ----
    def _stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def note_acquire(self, name: str) -> None:
        st = self._stack()
        if st:
            tname = threading.current_thread().name
            with self._meta:
                for held in st:   # setdefault dedups repeated holds
                    if held != name:
                        self._edges.setdefault(
                            (held, name), f"thread={tname}")
        st.append(name)

    def note_release(self, name: str) -> None:
        st = self._stack()
        # release order may differ from acquire order (nested `with`
        # blocks always match, but remove the right entry regardless)
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                break

    # ---- analysis ----
    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._meta:
            return dict(self._edges)

    def inversions(self) -> List[str]:
        """Cycles in the observed order graph, rendered as messages.
        Pairwise inversions (A→B and B→A) and longer cycles both count."""
        edges = self.edges()
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        out: List[str] = []
        seen_pairs: Set[Tuple[str, str]] = set()
        for (a, b), witness in sorted(edges.items()):
            if (b, a) in edges and (b, a) not in seen_pairs:
                seen_pairs.add((a, b))
                out.append(
                    f"lock-order inversion: {a!r} -> {b!r} ({witness}) "
                    f"but also {b!r} -> {a!r} ({edges[(b, a)]})")
        # longer cycles: DFS with a path stack
        state: Dict[str, int] = {}   # 0=visiting, 1=done

        def dfs(node: str, path: List[str]) -> None:
            state[node] = 0
            path.append(node)
            for nxt in sorted(graph.get(node, ())):
                if state.get(nxt) == 0:
                    cycle = path[path.index(nxt):] + [nxt]
                    if len(cycle) > 3:   # pairs already reported above
                        out.append("lock-order cycle: " +
                                   " -> ".join(repr(c) for c in cycle))
                elif nxt not in state:
                    dfs(nxt, path)
            path.pop()
            state[node] = 1

        for node in sorted(graph):
            if node not in state:
                dfs(node, [])
        return out


RECORDER = LockOrderRecorder()


class _RecordingLock:
    """Wraps a real lock, reporting acquires/releases to the recorder."""

    def __init__(self, lock, name: str, recorder: LockOrderRecorder):
        self._lock = lock
        self._name = name
        self._recorder = recorder

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._recorder.note_acquire(self._name)
        return got

    def release(self) -> None:
        self._lock.release()
        self._recorder.note_release(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __repr__(self) -> str:
        return f"<RecordingLock {self._name} {self._lock!r}>"


def named_lock(name: str,
               factory: Callable[[], object] = threading.Lock):
    """A lock participating in test-time order recording under `name`.

    Inactive recorder (the default) → the factory's plain lock, no
    wrapper, no overhead.  The decision is made at construction: enable
    the recorder before building the components under test."""
    lock = factory()
    if RECORDER.enabled:
        return _RecordingLock(lock, name, RECORDER)
    return lock
