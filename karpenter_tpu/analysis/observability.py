"""observability-contract checker family (OB*).

Three contracts, all repo-level (they need more than one file at once):

  * metrics ↔ docs — every family registered in `utils/metrics.py`
    (`REGISTRY.counter/gauge/histogram("name", ...)`) has a row in
    `docs/metrics.md`, and every table row names a registered family.
    Legacy aliases (`LEGACY_ALIASES`) are served, not registered; they
    are excluded from both directions.
  * bounded labels — label names whose value space grows with workload
    (`pod`, `uid`, `provider_id`, …) are rejected at registration sites.
    `node_name` is allowed: the scrape-time collector deletes stale
    series when nodes terminate, which is the upstream convention.
  * span-name registry — every literal `tracing.span("...")` name is
    drawn from `utils/tracing.SPAN_NAMES`; dynamic names must go through
    `tracing.registered(...)` (which asserts membership at runtime).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Checker, Finding, SourceFile, rule

rule("OB001", "observability",
     "metric family registered but not documented in docs/metrics.md",
     "add a `| family | type | labels | meaning |` row to the table in "
     "docs/metrics.md")
rule("OB002", "observability",
     "docs/metrics.md documents a family that is not registered",
     "remove the stale row, or register the family in utils/metrics.py")
rule("OB003", "observability",
     "metric label with unbounded cardinality",
     "drop the label or key it on a bounded dimension (nodepool, reason, "
     "method); per-object series need scrape-time stale-series cleanup "
     "like the node_name collector")
rule("OB004", "observability",
     "span name not in the utils/tracing.SPAN_NAMES registry",
     "add the literal to SPAN_NAMES (one registry keeps the "
     "trace_span_duration label set enumerable)")
rule("OB005", "observability",
     "dynamic span name bypasses the registry",
     "wrap the expression in tracing.registered(...) so membership is "
     "asserted at runtime, or switch to a literal from SPAN_NAMES")
rule("OB006", "observability",
     "trip-site counter incremented without publishing to the incident bus",
     "call obs.incidents.publish_incident(kind, detail) in the same "
     "function that increments the trip counter — the flight recorder "
     "only captures what the bus sees (RS004-style funnel rule)")
rule("OB007", "observability",
     "SLI references a metric family that is not registered",
     "every family literal in an obs/slo.py SLI(...) spec must name a "
     "REGISTRY family (modulo the _count/_bucket/_sum histogram "
     "suffixes) — a typo here silently evaluates the SLO against an "
     "always-empty series (OB001-style two-way contract)")

METRICS_MODULE = "karpenter_tpu/utils/metrics.py"
TRACING_MODULE = "karpenter_tpu/utils/tracing.py"
SLO_MODULE = "karpenter_tpu/obs/slo.py"
DOCS_PAGE = "docs/metrics.md"

UNBOUNDED_LABELS = {"pod", "pod_name", "uid", "provider_id", "instance_id",
                    "trace_id", "span_id", "request_id", "message_id"}

_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_*]+)`")

# OB006: metric factories whose `.inc()` marks a fault-handling trip
# site.  Every increment site must also publish to the incident bus —
# otherwise the flight recorder has a blind spot for exactly the events
# it exists to capture.  The obs/ package itself is exempt (it IS the
# bus; the recorder increments bundle/suppression counters there).
TRIP_FAMILIES = frozenset({
    "supervisor_quarantines",     # circuit opened / controller quarantined
    "watchdog_trips",             # hard deadline abandoned a phase
    "leader_fence_refusals",      # stale fencing epoch refused a mutation
    "degradation_transitions",    # SolverHealth ladder moved
    "decode_transitions",         # DecodeHealth breaker moved
    "gang_rejections",            # all-or-nothing gang admission rejected
})

_OB006_EXEMPT_PREFIX = "karpenter_tpu/obs/"


def _trip_inc_family(node: ast.AST) -> Optional[str]:
    """`metrics.watchdog_trips().inc(...)` → "watchdog_trips"; None for
    any call that is not a trip-family increment."""
    if not (isinstance(node, ast.Call) and
            isinstance(node.func, ast.Attribute) and
            node.func.attr == "inc"):
        return None
    inner = node.func.value
    if not isinstance(inner, ast.Call):
        return None
    f = inner.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else ""
    return name if name in TRIP_FAMILIES else None


def _publishes_incident(func: ast.AST) -> bool:
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            f.id if isinstance(f, ast.Name) else ""
        if name == "publish_incident":
            return True
    return False


def registered_families(metrics_sf: SourceFile
                        ) -> Dict[str, Tuple[int, Tuple[str, ...]]]:
    """family name → (lineno, label names) from REGISTRY.<kind>() calls."""
    out: Dict[str, Tuple[int, Tuple[str, ...]]] = {}
    for node in ast.walk(metrics_sf.tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr in ("counter", "gauge", "histogram")):
            continue
        base = node.func.value
        if not (isinstance(base, ast.Name) and
                base.id in ("REGISTRY", "self")):
            continue
        if base.id == "self":   # Registry's own factory methods
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant) and
                isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        labels: Tuple[str, ...] = ()
        for kw in node.keywords:
            if kw.arg == "labels":
                labels = tuple(
                    c.value for c in ast.walk(kw.value)
                    if isinstance(c, ast.Constant) and
                    isinstance(c.value, str))
        out[name] = (node.lineno, labels)
    return out


_SLI_FAMILY_KEYWORDS = ("families", "bad_families", "good_families")
_HISTOGRAM_SUFFIXES = ("_count", "_bucket", "_sum")


def sli_family_refs(slo_sf: SourceFile) -> List[Tuple[str, int, str]]:
    """Every family literal referenced by an `SLI(...)` spec in
    obs/slo.py, as (family, lineno, sli_name) tuples.  An SLI call whose
    three family keywords are all empty is surfaced as ("", lineno,
    name) — an indicator with no inputs can never be computed."""
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(slo_sf.tree):
        if not (isinstance(node, ast.Call) and (
                (isinstance(node.func, ast.Name) and
                 node.func.id == "SLI") or
                (isinstance(node.func, ast.Attribute) and
                 node.func.attr == "SLI"))):
            continue
        sli_name = next(
            (kw.value.value for kw in node.keywords
             if kw.arg == "name" and isinstance(kw.value, ast.Constant) and
             isinstance(kw.value.value, str)), "?")
        refs = 0
        for kw in node.keywords:
            if kw.arg not in _SLI_FAMILY_KEYWORDS:
                continue
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and \
                        isinstance(c.value, str) and c.value:
                    out.append((c.value, c.lineno, sli_name))
                    refs += 1
        if refs == 0:
            out.append(("", node.lineno, sli_name))
    return out


def _strip_histogram_suffix(family: str) -> str:
    for suffix in _HISTOGRAM_SUFFIXES:
        if family.endswith(suffix):
            return family[: -len(suffix)]
    return family


def legacy_aliases(metrics_sf: SourceFile) -> Set[str]:
    for node in ast.walk(metrics_sf.tree):
        if isinstance(node, ast.Assign) and \
                any(isinstance(t, ast.Name) and t.id == "LEGACY_ALIASES"
                    for t in node.targets) and \
                isinstance(node.value, ast.Dict):
            return {v.value for v in node.value.values
                    if isinstance(v, ast.Constant) and
                    isinstance(v.value, str)}
    return set()


def documented_families(docs_path: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open(docs_path, "r", encoding="utf-8") as fh:
            for i, line in enumerate(fh, start=1):
                m = _ROW_RE.match(line.strip())
                if m and "*" not in m.group(1):
                    out.setdefault(m.group(1), i)
    except OSError:
        pass
    return out


def span_registry(tracing_sf: SourceFile) -> Set[str]:
    for node in ast.walk(tracing_sf.tree):
        if isinstance(node, ast.Assign) and \
                any(isinstance(t, ast.Name) and t.id == "SPAN_NAMES"
                    for t in node.targets):
            return {c.value for c in ast.walk(node.value)
                    if isinstance(c, ast.Constant) and
                    isinstance(c.value, str)}
    return set()


def _is_registered_call(node: ast.AST) -> bool:
    """`tracing.registered(...)` / `registered(...)` wrapper."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else ""
    return name == "registered"


class ObservabilityChecker(Checker):
    family = "observability"

    def check_repo(self, sources: Sequence[SourceFile],
                   root: str) -> List[Finding]:
        by_rel = {sf.rel: sf for sf in sources}
        findings: List[Finding] = []
        metrics_sf = by_rel.get(METRICS_MODULE)
        tracing_sf = by_rel.get(TRACING_MODULE)
        if metrics_sf is not None:
            findings.extend(self._check_metrics_docs(metrics_sf, root))
            findings.extend(self._check_labels(metrics_sf))
            slo_sf = by_rel.get(SLO_MODULE)
            if slo_sf is not None:
                findings.extend(self._check_sli_families(slo_sf, metrics_sf))
        spans = span_registry(tracing_sf) if tracing_sf is not None else set()
        for sf in sources:
            if sf.rel == TRACING_MODULE:
                continue    # the registry itself; Tracer.span(name) is the API
            findings.extend(self._check_spans(sf, spans))
        for sf in sources:
            findings.extend(self._check_trip_funnel(sf))
        return findings

    def _check_trip_funnel(self, sf: SourceFile) -> List[Finding]:
        """OB006: every trip-counter increment shares a function with a
        publish_incident call.  Lexical like RS004 — the contract is
        that the SAME code path feeds both the metric and the bus."""
        if sf.rel.startswith(_OB006_EXEMPT_PREFIX) or \
                sf.rel == METRICS_MODULE:
            return []
        findings: List[Finding] = []
        parents = sf.parents()
        for node in ast.walk(sf.tree):
            family = _trip_inc_family(node)
            if family is None:
                continue
            func: Optional[ast.AST] = node
            while func is not None and not isinstance(
                    func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = parents.get(func)
            if func is not None and _publishes_incident(func):
                continue
            findings.append(Finding(
                "OB006", sf.rel, node.lineno, sf.scope_of(node), family,
                f"trip counter {family} incremented without a "
                "publish_incident in the same function — the flight "
                "recorder cannot see this trip"))
        return findings

    def _check_sli_families(self, slo_sf: SourceFile,
                            metrics_sf: SourceFile) -> List[Finding]:
        """OB007: the SLI registry must reference only registered metric
        families — the two-way half that matters here is SLI→registry
        (registry→docs is already OB001's job).  Histogram-derived
        series (`_count`/`_bucket`/`_sum`) resolve to their base family.
        """
        findings: List[Finding] = []
        defined = set(registered_families(metrics_sf))
        for family, lineno, sli_name in sli_family_refs(slo_sf):
            if family == "":
                findings.append(Finding(
                    "OB007", slo_sf.rel, lineno, "<module>", sli_name,
                    f"SLI {sli_name} declares no metric families — an "
                    "indicator with no inputs always reads empty"))
                continue
            if _strip_histogram_suffix(family) not in defined:
                findings.append(Finding(
                    "OB007", slo_sf.rel, lineno, "<module>",
                    f"{sli_name}:{family}",
                    f"SLI {sli_name} references unregistered family "
                    f"{family} — the SLO would evaluate against an "
                    "always-empty series"))
        return findings

    def _check_metrics_docs(self, metrics_sf: SourceFile,
                            root: str) -> List[Finding]:
        findings: List[Finding] = []
        defined = registered_families(metrics_sf)
        aliases = legacy_aliases(metrics_sf)
        documented = documented_families(os.path.join(root, DOCS_PAGE))
        for name in sorted(set(defined) - set(documented) - aliases):
            lineno, _ = defined[name]
            findings.append(Finding(
                "OB001", METRICS_MODULE, lineno, "<module>", name,
                f"family {name} registered but undocumented in "
                f"{DOCS_PAGE}"))
        for name in sorted(set(documented) - set(defined) - aliases):
            findings.append(Finding(
                "OB002", METRICS_MODULE, documented[name], "<docs>", name,
                f"{DOCS_PAGE} row {documented[name]} documents unknown "
                f"family {name}"))
        return findings

    def _check_labels(self, metrics_sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for name, (lineno, labels) in registered_families(metrics_sf).items():
            bad = sorted(set(labels) & UNBOUNDED_LABELS)
            if bad:
                findings.append(Finding(
                    "OB003", METRICS_MODULE, lineno, "<module>",
                    f"{name}:{','.join(bad)}",
                    f"family {name} uses unbounded label(s) {bad}"))
        return findings

    def _check_spans(self, sf: SourceFile,
                     spans: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else ""
            if name != "span":
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if spans and arg.value not in spans:
                    findings.append(Finding(
                        "OB004", sf.rel, node.lineno, sf.scope_of(node),
                        arg.value,
                        f"span name {arg.value!r} missing from "
                        "tracing.SPAN_NAMES"))
            elif not _is_registered_call(arg):
                findings.append(Finding(
                    "OB005", sf.rel, node.lineno, sf.scope_of(node),
                    ast.unparse(arg)[:60] if hasattr(ast, "unparse")
                    else "dynamic",
                    "dynamic span name bypasses the SPAN_NAMES registry"))
        return findings
