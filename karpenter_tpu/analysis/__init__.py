"""graftlint: codebase-aware static analysis for karpenter-tpu.

Four checker families tuned to this repo's correctness regimes:

  * jax-hotpath (JH*)      — host-device syncs, tracer branching, dynamic
    static_argnums, missing buffer donation in the `ops/` kernels.
  * determinism (DT*)      — wall-clock reads, unseeded global RNG, and
    unordered set iteration in modules reachable from `sim/` (the golden
    reports are byte-identical; any of these breaks them).
  * lock-discipline (LK*)  — `# guarded-by: <lock>` annotations on shared
    attributes, checked lexically; plus a test-time lock-order recorder
    (analysis/lockorder.py) that fails the suite on observed inversions.
  * observability (OB*)    — metrics families ↔ docs/metrics.md contract,
    bounded label sets, span names drawn from utils/tracing.SPAN_NAMES.

Entry points: `tools/graftlint.py` CLI, `make lint-analysis`, and the
tier-1 gate in tests/test_graftlint.py (zero non-baselined findings).
See docs/static-analysis.md for the conventions and baseline workflow.
"""

from .core import (  # noqa: F401
    Finding,
    Rule,
    RULES,
    SourceFile,
    default_checkers,
    iter_sources,
    load_baseline,
    partition,
    run_analysis,
    write_baseline,
)
