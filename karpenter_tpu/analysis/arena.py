"""arena-discipline checker family (AR*).

The persistent cluster arena (ops/arena.py) keeps the solver's input
tensors alive across ticks; its bit-identity contract with the
from-scratch `tensorize_nodes` path holds only if every slab mutation
flows through the typed delta API, under the state lock.  Two lexical
rules keep that closed:

  * AR001 — a write to an arena slab tensor (``slab_alloc``,
    ``slab_used``, ``slab_compat``, ``slab_live``) anywhere OUTSIDE
    `karpenter_tpu/ops/arena.py`.  Consumers get copies from `gather()`;
    nothing else may reach into the slab.
  * AR002 — a function inside `ops/arena.py` that writes a slab tensor
    without a `# guarded-by:` / `# graftlint: holds(...)` lock annotation
    on its `def` line (or the line above).  Every delta-API entry point
    documents the externally-held state lock the same way the Cluster's
    maps do (see analysis/locks.py for the convention).

Writes are: assignment / augmented assignment whose target chain touches
a slab attribute (``self.slab_used[slot] = ...``, ``arena.slab_live[i] =
False``), `del` on such a chain, and in-place ndarray mutator calls
(``.fill(...)``, ``.sort()``, ``.resize(...)``, ``.put(...)``) on one.
Reads are out of scope — `gather()`'s fancy indexing copies, so reads
can't corrupt the slab.

The WarmRestart layer adds a third rule with a WIDER net on a NARROWER
scope:

  * AR003 — snapshot-path code (`state/snapshot.py`, `state/ingest.py`)
    touching a slab attribute AT ALL (read or write), or a
    `setattr`/`getattr` anywhere outside `ops/arena.py` whose name
    argument is a slab-attr string literal.  Serialization is exactly
    the place a generic ``for k, v in sections: setattr(arena, k, v)``
    loop slips past AR001's lexical write detection — restore must flow
    through ``ClusterArena.snapshot_state()/restore_state()`` so slab ⇄
    registry consistency stays arena-owned.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .core import Checker, Finding, SourceFile, rule

rule("AR001", "arena-discipline",
     "arena slab tensor mutated outside the delta API module",
     "route the mutation through a ClusterArena delta method "
     "(apply_*/touch_node/compact/rebuild) in ops/arena.py — consumers "
     "must treat gather() output as read-only copies")
rule("AR002", "arena-discipline",
     "slab-mutating arena method lacks a lock annotation",
     "annotate the def line with `# guarded-by: caller(state_lock)` (or "
     "`# graftlint: holds(<lock>)`) — every slab write happens under the "
     "operator's state lock")
rule("AR003", "arena-discipline",
     "snapshot-path code touches arena slab tensors directly",
     "serialize/restore slabs only through ClusterArena.snapshot_state() "
     "/ restore_state() — the snapshot layer must never read, write, or "
     "setattr/getattr slab_* attributes itself")

ARENA_MODULE = "karpenter_tpu/ops/arena.py"
SNAPSHOT_MODULES = ("karpenter_tpu/state/snapshot.py",
                    "karpenter_tpu/state/ingest.py")
SLAB_ATTRS = frozenset({"slab_alloc", "slab_used", "slab_compat",
                        "slab_live"})
_NDARRAY_MUTATORS = frozenset({"fill", "sort", "resize", "put"})
_ANNOT_RE = re.compile(
    r"#\s*(guarded-by:|graftlint:\s*holds\()")


def _chain_slab_attr(node: ast.AST) -> Optional[str]:
    """First slab attribute named anywhere in an Attribute/Subscript
    chain (``self.slab_used[slot]`` → 'slab_used')."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute) and node.attr in SLAB_ATTRS:
            return node.attr
        node = node.value
    return None


def _slab_writes(tree: ast.AST) -> List[Tuple[ast.AST, str, str]]:
    """(node, slab-attr, kind) for every slab write site under `tree`."""
    writes: List[Tuple[ast.AST, str, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                attr = _chain_slab_attr(tgt)
                if attr is not None:
                    writes.append((node, attr, "assign"))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = _chain_slab_attr(tgt)
                if attr is not None:
                    writes.append((node, attr, "del"))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _NDARRAY_MUTATORS:
            attr = _chain_slab_attr(node.func.value)
            if attr is not None:
                writes.append((node, attr, node.func.attr))
    return writes


def _def_annotated(sf: SourceFile, fn: ast.FunctionDef) -> bool:
    for lineno in (fn.lineno, fn.lineno - 1):
        if _ANNOT_RE.search(sf.line_text(lineno)):
            return True
    return False


class ArenaDisciplineChecker(Checker):
    family = "arena-discipline"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        if sf.rel == ARENA_MODULE:
            return self._check_arena_module(sf)
        findings: List[Finding] = []
        for node, attr, kind in _slab_writes(sf.tree):
            findings.append(Finding(
                "AR001", sf.rel, node.lineno, sf.scope_of(node),
                f"{attr}:{kind}",
                f"mutation of arena slab tensor {attr!r} ({kind}) outside "
                f"the delta API ({ARENA_MODULE})"))
        findings.extend(self._check_snapshot_path(sf))
        return findings

    def _check_snapshot_path(self, sf: SourceFile) -> List[Finding]:
        """AR003: snapshot-path slab access + string-driven setattr/getattr
        (the generic restore-loop escape hatch AR001's lexical write
        detection cannot see)."""
        findings: List[Finding] = []
        snapshot_mod = sf.rel in SNAPSHOT_MODULES
        for node in ast.walk(sf.tree):
            if snapshot_mod and isinstance(node, ast.Attribute) and \
                    node.attr in SLAB_ATTRS:
                findings.append(Finding(
                    "AR003", sf.rel, node.lineno, sf.scope_of(node),
                    f"{node.attr}:access",
                    f"snapshot-path access to slab tensor {node.attr!r} — "
                    f"use ClusterArena.snapshot_state()/restore_state()"))
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in ("setattr", "getattr") and \
                    len(node.args) >= 2 and \
                    isinstance(node.args[1], ast.Constant) and \
                    node.args[1].value in SLAB_ATTRS:
                findings.append(Finding(
                    "AR003", sf.rel, node.lineno, sf.scope_of(node),
                    f"{node.args[1].value}:{node.func.id}",
                    f"{node.func.id}() on slab tensor "
                    f"{node.args[1].value!r} outside the delta API"))
        return findings

    def _check_arena_module(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        parents = sf.parents()
        flagged = set()
        for node, attr, kind in _slab_writes(sf.tree):
            # walk up to the enclosing def; __init__ (slab creation) and
            # module level are exempt, everything else needs the annotation
            cur: Optional[ast.AST] = node
            fn: Optional[ast.FunctionDef] = None
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = cur
                    break
                cur = parents.get(cur)
            if fn is None or fn.name == "__init__" or fn in flagged:
                continue
            if not _def_annotated(sf, fn):
                flagged.add(fn)
                findings.append(Finding(
                    "AR002", sf.rel, fn.lineno, sf.scope_of(node),
                    fn.name,
                    f"method {fn.name!r} mutates slab tensor {attr!r} "
                    f"without a lock annotation on its def line"))
        return findings
