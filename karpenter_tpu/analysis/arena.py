"""arena-discipline checker family (AR*).

The persistent cluster arena (ops/arena.py) keeps the solver's input
tensors alive across ticks; its bit-identity contract with the
from-scratch `tensorize_nodes` path holds only if every slab mutation
flows through the typed delta API, under the state lock.  Two lexical
rules keep that closed:

  * AR001 — a write to an arena slab tensor (``slab_alloc``,
    ``slab_used``, ``slab_compat``, ``slab_live``) anywhere OUTSIDE
    `karpenter_tpu/ops/arena.py`.  Consumers get copies from `gather()`;
    nothing else may reach into the slab.
  * AR002 — a function inside `ops/arena.py` that writes a slab tensor
    without a `# guarded-by:` / `# graftlint: holds(...)` lock annotation
    on its `def` line (or the line above).  Every delta-API entry point
    documents the externally-held state lock the same way the Cluster's
    maps do (see analysis/locks.py for the convention).

Writes are: assignment / augmented assignment whose target chain touches
a slab attribute (``self.slab_used[slot] = ...``, ``arena.slab_live[i] =
False``), `del` on such a chain, and in-place ndarray mutator calls
(``.fill(...)``, ``.sort()``, ``.resize(...)``, ``.put(...)``) on one.
Reads are out of scope — `gather()`'s fancy indexing copies, so reads
can't corrupt the slab.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .core import Checker, Finding, SourceFile, rule

rule("AR001", "arena-discipline",
     "arena slab tensor mutated outside the delta API module",
     "route the mutation through a ClusterArena delta method "
     "(apply_*/touch_node/compact/rebuild) in ops/arena.py — consumers "
     "must treat gather() output as read-only copies")
rule("AR002", "arena-discipline",
     "slab-mutating arena method lacks a lock annotation",
     "annotate the def line with `# guarded-by: caller(state_lock)` (or "
     "`# graftlint: holds(<lock>)`) — every slab write happens under the "
     "operator's state lock")

ARENA_MODULE = "karpenter_tpu/ops/arena.py"
SLAB_ATTRS = frozenset({"slab_alloc", "slab_used", "slab_compat",
                        "slab_live"})
_NDARRAY_MUTATORS = frozenset({"fill", "sort", "resize", "put"})
_ANNOT_RE = re.compile(
    r"#\s*(guarded-by:|graftlint:\s*holds\()")


def _chain_slab_attr(node: ast.AST) -> Optional[str]:
    """First slab attribute named anywhere in an Attribute/Subscript
    chain (``self.slab_used[slot]`` → 'slab_used')."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute) and node.attr in SLAB_ATTRS:
            return node.attr
        node = node.value
    return None


def _slab_writes(tree: ast.AST) -> List[Tuple[ast.AST, str, str]]:
    """(node, slab-attr, kind) for every slab write site under `tree`."""
    writes: List[Tuple[ast.AST, str, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                attr = _chain_slab_attr(tgt)
                if attr is not None:
                    writes.append((node, attr, "assign"))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = _chain_slab_attr(tgt)
                if attr is not None:
                    writes.append((node, attr, "del"))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _NDARRAY_MUTATORS:
            attr = _chain_slab_attr(node.func.value)
            if attr is not None:
                writes.append((node, attr, node.func.attr))
    return writes


def _def_annotated(sf: SourceFile, fn: ast.FunctionDef) -> bool:
    for lineno in (fn.lineno, fn.lineno - 1):
        if _ANNOT_RE.search(sf.line_text(lineno)):
            return True
    return False


class ArenaDisciplineChecker(Checker):
    family = "arena-discipline"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        if sf.rel == ARENA_MODULE:
            return self._check_arena_module(sf)
        findings: List[Finding] = []
        for node, attr, kind in _slab_writes(sf.tree):
            findings.append(Finding(
                "AR001", sf.rel, node.lineno, sf.scope_of(node),
                f"{attr}:{kind}",
                f"mutation of arena slab tensor {attr!r} ({kind}) outside "
                f"the delta API ({ARENA_MODULE})"))
        return findings

    def _check_arena_module(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        parents = sf.parents()
        flagged = set()
        for node, attr, kind in _slab_writes(sf.tree):
            # walk up to the enclosing def; __init__ (slab creation) and
            # module level are exempt, everything else needs the annotation
            cur: Optional[ast.AST] = node
            fn: Optional[ast.FunctionDef] = None
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = cur
                    break
                cur = parents.get(cur)
            if fn is None or fn.name == "__init__" or fn in flagged:
                continue
            if not _def_annotated(sf, fn):
                flagged.add(fn)
                findings.append(Finding(
                    "AR002", sf.rel, fn.lineno, sf.scope_of(node),
                    fn.name,
                    f"method {fn.name!r} mutates slab tensor {attr!r} "
                    f"without a lock annotation on its def line"))
        return findings
