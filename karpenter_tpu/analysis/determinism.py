"""determinism checker family (DT*).

The simulator's contract is byte-identical reports for identical seeds
(tests/golden/); PR 4's Operator truthiness bug showed how a single
wall-clock or ordering leak breaks a golden three layers away.  These
rules police the leak classes in every module *reachable from
`karpenter_tpu.sim`* (computed from the static import graph — the sim
drives the real controller stack, so most of the package is in scope):

  * DT001 — wall-clock reads (`time.time()`, `datetime.now()`, …).
    Injectable-clock *defaults* (`clock: ... = time.time`) are references,
    not calls, and are fine; the allowlisted shims (`utils/tracing.py`
    display timestamps, `sim/harness.py` wall-speedup metric) are the two
    places a real clock is read on purpose.
  * DT002 — unseeded global RNG (`random.*`, `np.random.*`); all sim
    randomness flows through `np.random.default_rng([seed, ...])` streams.
  * DT003 — iteration over a `set` (literal, constructor, comprehension,
    or set-algebra expression) feeding control flow or output.  Set order
    is hash-randomized across runs for str keys; `sorted(...)` it.  Dict
    iteration is NOT flagged: CPython dicts are insertion-ordered, and
    deterministic insertions give deterministic iteration.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Checker, Finding, SourceFile, rule

rule("DT001", "determinism",
     "wall-clock read in a sim-reachable module",
     "take an injectable `clock: Callable[[], float]` (default time.time) "
     "and call self.clock(); the simulator substitutes virtual time")
rule("DT002", "determinism",
     "unseeded global RNG in a sim-reachable module",
     "use a seeded np.random.default_rng([seed, stream_id]) stream owned "
     "by the caller; never the process-global random/np.random state")
rule("DT003", "determinism",
     "iteration over an unordered set in a sim-reachable module",
     "wrap the set in sorted(...) before iterating (hash randomization "
     "makes str-keyed set order differ across runs)")

# the two intentional wall-clock reads (display timestamps / wall speedup)
DT001_ALLOWLIST = ("karpenter_tpu/utils/tracing.py",
                   "karpenter_tpu/sim/harness.py")

_WALLCLOCK = {("time", "time"), ("datetime", "now"), ("datetime", "utcnow"),
              ("datetime", "today"), ("date", "today")}
_NP_RNG_OK = {"default_rng", "Generator", "SeedSequence", "PCG64", "Philox",
              "BitGenerator"}
_RANDOM_OK = {"Random", "SystemRandom", "getstate"}
_SET_CTORS = {"set", "frozenset"}
_SET_METHODS = {"union", "intersection", "difference",
                "symmetric_difference", "copy"}


def module_name(rel: str) -> str:
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def import_graph(sources: Sequence[SourceFile]) -> Dict[str, Set[str]]:
    """module → imported package-internal modules (static, best-effort)."""
    known = {module_name(sf.rel) for sf in sources}
    graph: Dict[str, Set[str]] = {}

    def resolve(candidates: List[str]) -> Optional[str]:
        for c in candidates:
            if c in known:
                return c
        return None

    for sf in sources:
        mod = module_name(sf.rel)
        pkg_parts = mod.split(".")
        deps: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = resolve([alias.name])
                    if target:
                        deps.add(target)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # relative: level 1 = containing package
                    base = ".".join(pkg_parts[: len(pkg_parts) - node.level])
                else:
                    base = ""
                stem = ".".join(p for p in (base, node.module or "") if p)
                for alias in node.names:
                    target = resolve([f"{stem}.{alias.name}" if stem
                                      else alias.name, stem])
                    if target:
                        deps.add(target)
        graph[mod] = deps
    return graph


def reachable_from_sim(sources: Sequence[SourceFile]) -> Set[str]:
    graph = import_graph(sources)
    frontier = [m for m in graph if m.startswith("karpenter_tpu.sim")]
    seen: Set[str] = set(frontier)
    while frontier:
        cur = frontier.pop()
        for dep in graph.get(cur, ()):
            if dep not in seen:
                seen.add(dep)
                frontier.append(dep)
    return seen


# ---------------------------------------------------------------------------
# set-expression classification (DT003)
# ---------------------------------------------------------------------------

def _collect_set_names(scope: ast.AST) -> Set[str]:
    """Names bound to set-like values anywhere in the scope subtree.  Two
    passes so `prev = cur`-style rebinds of an already-known set resolve."""
    known: Set[str] = set()
    for _ in range(2):
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign):
                pairs: List[Tuple[ast.AST, ast.AST]] = []
                for tgt in node.targets:
                    if isinstance(tgt, ast.Tuple) and \
                            isinstance(node.value, ast.Tuple) and \
                            len(tgt.elts) == len(node.value.elts):
                        pairs.extend(zip(tgt.elts, node.value.elts))
                    else:
                        pairs.append((tgt, node.value))
                for tgt, val in pairs:
                    if isinstance(tgt, ast.Name) and is_set_expr(val, known):
                        known.add(tgt.id)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                ann = ast.dump(node.annotation).lower()
                if "'set'" in ann or (node.value is not None and
                                      is_set_expr(node.value, known)):
                    known.add(node.target.id)
    return known


def is_set_expr(node: ast.AST, known: Set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name):
        return node.id in known
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id in _SET_CTORS:
            return True
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SET_METHODS:
            return is_set_expr(node.func.value, known)
        return False
    if isinstance(node, ast.BinOp) and \
            isinstance(node.op, (ast.BitOr, ast.BitAnd, ast.BitXor, ast.Sub)):
        return is_set_expr(node.left, known) or \
            is_set_expr(node.right, known)
    return False


class DeterminismChecker(Checker):
    family = "determinism"

    def check_repo(self, sources: Sequence[SourceFile],
                   root: str) -> List[Finding]:
        in_scope = reachable_from_sim(sources)
        findings: List[Finding] = []
        for sf in sources:
            if module_name(sf.rel) not in in_scope:
                continue
            findings.extend(self._check_clock_rng(sf))
            findings.extend(self._check_set_iteration(sf))
        return findings

    def _check_clock_rng(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        clock_ok = sf.rel in DT001_ALLOWLIST
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and
                    isinstance(f.value, ast.Name)):
                continue
            base, attr = f.value.id, f.attr
            if (base, attr) in _WALLCLOCK and not clock_ok:
                findings.append(Finding(
                    "DT001", sf.rel, node.lineno, sf.scope_of(node),
                    f"{base}.{attr}",
                    f"{base}.{attr}() reads the wall clock in a "
                    "sim-reachable module"))
            elif base == "random" and attr not in _RANDOM_OK:
                findings.append(Finding(
                    "DT002", sf.rel, node.lineno, sf.scope_of(node),
                    f"random.{attr}",
                    f"random.{attr}() uses the unseeded process-global RNG"))
        # np.random.<fn>: one attribute deeper (np.random is an Attribute)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Attribute) and \
                    f.value.attr == "random" and \
                    isinstance(f.value.value, ast.Name) and \
                    f.value.value.id in ("np", "numpy") and \
                    f.attr not in _NP_RNG_OK:
                findings.append(Finding(
                    "DT002", sf.rel, node.lineno, sf.scope_of(node),
                    f"np.random.{f.attr}",
                    f"np.random.{f.attr}() uses the unseeded global "
                    "NumPy RNG"))
        return findings

    def _check_set_iteration(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        scopes: List[ast.AST] = [sf.tree]
        scopes += [n for n in ast.walk(sf.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        flagged: Set[int] = set()
        for scope in scopes:
            known = _collect_set_names(scope)
            for node in ast.walk(scope):
                iters: List[ast.AST] = []
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    iters.append(node.iter)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    iters.extend(g.iter for g in node.generators)
                for it in iters:
                    if is_set_expr(it, known) and it.lineno not in flagged:
                        flagged.add(it.lineno)
                        findings.append(Finding(
                            "DT003", sf.rel, it.lineno, sf.scope_of(node),
                            ast.unparse(it)[:60] if hasattr(ast, "unparse")
                            else "set-iter",
                            "iteration order over a set is not "
                            "deterministic across runs"))
        return findings
