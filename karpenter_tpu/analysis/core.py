"""graftlint core: findings, rules, suppression, and the baseline.

The framework is deliberately small: a `SourceFile` wraps one parsed
module (AST + raw lines, so trailing-comment conventions like
`# guarded-by:` stay visible), a `Checker` contributes findings either
per file or across the whole repo (the observability contract needs the
metrics module AND the docs page at once), and `run_analysis` stitches
them together, applies `# graftlint: disable=` suppressions, and sorts.

Finding identity (`Finding.key`) is `rule|path|scope|detail` — no line
numbers — so the committed baseline survives unrelated edits that shift
lines.  `scope` is the enclosing qualified name (`Cls.method` or
`<module>`); `detail` is a rule-chosen discriminator (the attribute
written, the call flagged) that keeps two findings in one scope apart.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclass(frozen=True)
class Rule:
    rule_id: str
    family: str
    summary: str
    hint: str


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, family: str, summary: str, hint: str) -> Rule:
    r = Rule(rule_id, family, summary, hint)
    RULES[rule_id] = r
    return r


@dataclass
class Finding:
    rule: str
    path: str       # repo-relative, forward slashes
    line: int
    scope: str      # enclosing qualname or '<module>'
    detail: str     # stable discriminator within the scope
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.scope}|{self.detail}"

    def render(self, fix_hints: bool = False) -> str:
        out = f"{self.path}:{self.line}: {self.rule} {self.message} [{self.scope}]"
        if fix_hints and self.rule in RULES:
            out += f"\n    fix: {RULES[self.rule].hint}"
        return out


class SourceFile:
    """One parsed module: AST, raw lines, repo-relative path."""

    def __init__(self, path: str, rel: str, text: str, tree: ast.Module):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    @classmethod
    def load(cls, path: str, root: str) -> Optional["SourceFile"]:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                text = fh.read()
            tree = ast.parse(text, filename=path)
        except (OSError, SyntaxError, ValueError):
            return None
        return cls(path, os.path.relpath(path, root), text, tree)

    # ---- structure helpers used by every checker ----
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def scope_of(self, node: ast.AST) -> str:
        """Qualified enclosing scope name, e.g. `Batcher.add`."""
        parts: List[str] = []
        parents = self.parents()
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = parents.get(cur)
        return ".".join(reversed(parts)) if parts else "<module>"

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9,\s]+)")


def _suppressed_rules(line: str) -> Set[str]:
    m = _SUPPRESS_RE.search(line)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def is_suppressed(sf: SourceFile, finding: Finding) -> bool:
    """A finding is suppressed by `# graftlint: disable=<RULE>` on its own
    line or on the line directly above (for lines too long to annotate)."""
    for lineno in (finding.line, finding.line - 1):
        if finding.rule in _suppressed_rules(sf.line_text(lineno)):
            return True
    return False


class Checker:
    """Base checker.  Subclasses override `check_file` (per module) and/or
    `check_repo` (whole-source-set rules like the metrics↔docs contract)."""

    family = "generic"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        return []

    def check_repo(self, sources: Sequence[SourceFile],
                   root: str) -> List[Finding]:
        return []


_SKIP_DIRS = {"__pycache__", ".git", "csrc"}


def iter_sources(root: str,
                 subdirs: Sequence[str] = ("karpenter_tpu",)) -> List[SourceFile]:
    out: List[SourceFile] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                sf = SourceFile.load(os.path.join(dirpath, fn), root)
                if sf is not None:
                    out.append(sf)
    return out


def default_checkers() -> List[Checker]:
    from .arena import ArenaDisciplineChecker
    from .decodepath import DecodePathChecker
    from .determinism import DeterminismChecker
    from .jaxhot import JaxHotPathChecker
    from .locks import LockDisciplineChecker
    from .observability import ObservabilityChecker
    from .robustness import RobustnessChecker
    return [JaxHotPathChecker(), DecodePathChecker(), DeterminismChecker(),
            LockDisciplineChecker(), ObservabilityChecker(),
            ArenaDisciplineChecker(), RobustnessChecker()]


def run_analysis(root: str,
                 checkers: Optional[Sequence[Checker]] = None,
                 families: Optional[Sequence[str]] = None,
                 sources: Optional[Sequence[SourceFile]] = None) -> List[Finding]:
    """Run every checker over the package; returns suppression-filtered
    findings sorted by (path, line, rule)."""
    if checkers is None:
        checkers = default_checkers()
    if families:
        checkers = [c for c in checkers if c.family in set(families)]
    if sources is None:
        sources = iter_sources(root)
    by_rel = {sf.rel: sf for sf in sources}
    findings: List[Finding] = []
    for checker in checkers:
        for sf in sources:
            findings.extend(checker.check_file(sf))
        findings.extend(checker.check_repo(sources, root))
    kept = []
    for f in findings:
        sf = by_rel.get(f.path)
        if sf is not None and is_suppressed(sf, f):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.detail))
    return kept


# ---------------------------------------------------------------------------
# Baseline: grandfathered findings we decided not to fix (yet).
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Set[str]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return set()
    return set(doc.get("findings", []))


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    doc = {
        "comment": "graftlint grandfathered findings; regenerate with "
                   "`python tools/graftlint.py --write-baseline`. Keys are "
                   "rule|path|scope|detail (line-number free, so unrelated "
                   "edits don't invalidate them).",
        "findings": sorted({f.key for f in findings}),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")


def partition(findings: Sequence[Finding], baseline: Set[str]
              ) -> Tuple[List[Finding], List[Finding], Set[str]]:
    """Split into (new, grandfathered) and report baseline keys that no
    longer match anything (stale — fixed or renamed; prune them)."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen: Set[str] = set()
    for f in findings:
        seen.add(f.key)
        (old if f.key in baseline else new).append(f)
    return new, old, baseline - seen
