"""jax-hotpath checker family (JH*).

The solver hot path is a set of `@partial(jax.jit, ...)` kernels under
`ops/` and `parallel/` fed by tensorize; the disciplines that keep them
fast are exactly the ones that silently rot: no host-device syncs inside
the window (`.item()`, `float()` / `np.asarray` on traced values,
`.block_until_ready()` belongs in bench code only), no Python branching
on tracers (works under `jit` only until the branch actually depends on
data, then dies at trace time — or worse, constant-folds), static
argument specs that stay literal (a dynamic `static_argnums` turns every
call into a fresh trace), and donation of the scratch buffers the scan
kernels consume (missed donation = one extra device copy per solve).

Detection is scoped to where the rule is meaningful: JH001/JH002 to the
hot modules (`ops/`, `parallel/`), JH003/JH005/JH006 to jit-decorated
functions anywhere, JH004 to any jit spec.  JH005 additionally covers
CALL-FORM jit specs — `partial(jax.jit, ...)(fn)` and `jax.jit(fn, ...)`
assignments (the `parallel/driver.py` init-slab wrappers) — by resolving
`fn` to its same-file def and applying the same scratch-donation check.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from .core import Checker, Finding, SourceFile, rule

rule("JH001", "jax-hotpath",
     ".item() host-device sync in a hot-path module",
     "keep the value on device; decode once at the host boundary with "
     "np.asarray over the whole result batch")
rule("JH002", "jax-hotpath",
     ".block_until_ready() outside bench code",
     "remove it — only bench.py timing loops need an explicit barrier; "
     "the decode's np.asarray is already a sync point")
rule("JH003", "jax-hotpath",
     "Python branch on a traced value inside a jit function",
     "replace `if`/`while` on a traced array with jnp.where / lax.cond / "
     "lax.while_loop, or mark the argument static if it is host data")
rule("JH004", "jax-hotpath",
     "dynamic or non-literal static_argnums/static_argnames",
     "static specs must be literal ints/strings (or tuples of them); a "
     "computed spec retraces per call and an unhashable one raises")
rule("JH005", "jax-hotpath",
     "jit kernel consumes scratch buffers without donating them",
     "add donate_argnames for init_*/scratch buffers the kernel overwrites "
     "— or baseline this finding when the caller reuses the buffer "
     "(the arena cache does)")
rule("JH006", "jax-hotpath",
     "host conversion (float/int/np.asarray) of a traced value inside jit",
     "move the conversion outside the jit boundary or keep the math in "
     "jnp; inside a trace this forces a concretization error or a sync")

HOT_PREFIXES = ("karpenter_tpu/ops/", "karpenter_tpu/parallel/")
_HOST_CONVERTERS = {"float", "int", "bool"}
_NP_CONVERTERS = {"asarray", "array"}


def _is_jax_jit(node: ast.AST) -> bool:
    """`jax.jit` or bare `jit` as an expression."""
    if isinstance(node, ast.Attribute) and node.attr == "jit" and \
            isinstance(node.value, ast.Name) and node.value.id == "jax":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call_of(deco: ast.AST) -> Optional[ast.Call]:
    """The `partial(jax.jit, ...)` / `jax.jit(...)` call of a decorator,
    or None when the decorator is a bare `@jax.jit`."""
    if isinstance(deco, ast.Call):
        if _is_jax_jit(deco.func):
            return deco
        # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
        fn = deco.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else ""
        if name == "partial" and deco.args and _is_jax_jit(deco.args[0]):
            return deco
    return None


def _is_jit_decorated(fn: ast.FunctionDef) -> Optional[ast.Call]:
    """Returns the jit spec call for a jit-decorated function (a synthetic
    empty call for bare `@jax.jit`), else None."""
    for deco in fn.decorator_list:
        if _is_jax_jit(deco):
            return ast.Call(func=deco, args=[], keywords=[])
        call = _jit_call_of(deco)
        if call is not None:
            return call
    return None


def _literal_spec(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, str))
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_literal_spec(e) for e in node.elts)
    return False


def _static_names(call: ast.Call, fn: ast.FunctionDef) -> Set[str]:
    """Parameter names made static by the spec (literal specs only)."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for s in ast.walk(kw.value):
                if isinstance(s, ast.Constant) and isinstance(s.value, str):
                    out.add(s.value)
        elif kw.arg == "static_argnums":
            for s in ast.walk(kw.value):
                if isinstance(s, ast.Constant) and isinstance(s.value, int) \
                        and 0 <= s.value < len(params):
                    out.add(params[s.value])
    return out


def _call_form_jit(node: ast.Call):
    """(spec_call, wrapped_name) for call-form jit wrapping — `jax.jit(fn,
    ...)` or `partial(jax.jit, ...)(fn)` — else None.  Decorator forms
    never match: a decorator expression has no outer application call."""
    if _is_jax_jit(node.func) and node.args and \
            isinstance(node.args[0], ast.Name):
        return node, node.args[0].id
    if isinstance(node.func, ast.Call) and \
            _jit_call_of(node.func) is not None and node.args and \
            isinstance(node.args[0], ast.Name):
        return node.func, node.args[0].id
    return None


class JaxHotPathChecker(Checker):
    family = "jax-hotpath"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        hot = sf.rel.startswith(HOT_PREFIXES)
        defs = {n.name: n for n in ast.walk(sf.tree)
                if isinstance(n, ast.FunctionDef)}
        for node in ast.walk(sf.tree):
            # JH001/JH002: sync calls, anywhere in hot modules
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                if hot and node.func.attr == "item" and not node.args:
                    findings.append(Finding(
                        "JH001", sf.rel, node.lineno, sf.scope_of(node),
                        "item", ".item() forces a host-device sync"))
                if node.func.attr == "block_until_ready":
                    findings.append(Finding(
                        "JH002", sf.rel, node.lineno, sf.scope_of(node),
                        "block_until_ready",
                        ".block_until_ready() barrier outside bench code"))
            # JH004: static spec must be literal — any jit call expression
            if isinstance(node, ast.Call):
                call = node if _is_jax_jit(node.func) else _jit_call_of(node)
                if call is not None:
                    for kw in call.keywords:
                        if kw.arg in ("static_argnums", "static_argnames") \
                                and not _literal_spec(kw.value):
                            findings.append(Finding(
                                "JH004", sf.rel, kw.value.lineno,
                                sf.scope_of(node), kw.arg,
                                f"non-literal {kw.arg} spec retraces "
                                "per call"))
            # JH005 on call-form specs: the wrapped fn resolves in-file
            if isinstance(node, ast.Call):
                cf = _call_form_jit(node)
                if cf is not None and cf[1] in defs:
                    findings.extend(self._check_donation(
                        sf, defs[cf[1]], cf[0], node))
            # per-jit-function rules
            if isinstance(node, ast.FunctionDef):
                spec = _is_jit_decorated(node)
                if spec is not None:
                    findings.extend(self._check_jit_fn(sf, node, spec))
        return findings

    def _check_donation(self, sf: SourceFile, fn: ast.FunctionDef,
                        spec: ast.Call, site: ast.AST) -> List[Finding]:
        """The JH005 scratch-donation check against an arbitrary spec call
        (decorator or call form) over `fn`."""
        static = _static_names(spec, fn)
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args +
                  fn.args.kwonlyargs}
        scratch = sorted(p for p in params - static
                         if p.startswith("init_"))
        if not scratch or any(kw.arg in ("donate_argnums",
                                         "donate_argnames")
                              for kw in spec.keywords):
            return []
        return [Finding(
            "JH005", sf.rel, site.lineno, sf.scope_of(site),
            f"{fn.name}:{','.join(scratch)}",
            f"jit spec over {fn.name} consumes scratch buffers "
            f"{scratch} without donation")]

    def _check_jit_fn(self, sf: SourceFile, fn: ast.FunctionDef,
                      spec: ast.Call) -> List[Finding]:
        findings: List[Finding] = []
        static = _static_names(spec, fn)
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args +
                  fn.args.kwonlyargs}
        traced = params - static

        def names_in(node: ast.AST) -> Set[str]:
            return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}

        for node in ast.walk(fn):
            # JH003: if/while whose test reads a traced parameter.  Only the
            # OUTER jit function's params are known-traced; nested scan-step
            # closures rebind their own names and are left to fixtures.
            if isinstance(node, (ast.If, ast.While)):
                hit = names_in(node.test) & traced
                if hit:
                    findings.append(Finding(
                        "JH003", sf.rel, node.lineno, sf.scope_of(node),
                        ",".join(sorted(hit)),
                        f"branch on traced value(s) {sorted(hit)} inside "
                        f"jit function {fn.name}"))
            # JH006: host conversion applied to a traced parameter
            if isinstance(node, ast.Call) and node.args and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in traced:
                f = node.func
                if isinstance(f, ast.Name) and f.id in _HOST_CONVERTERS:
                    conv = f.id
                elif isinstance(f, ast.Attribute) and \
                        f.attr in _NP_CONVERTERS and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in ("np", "numpy"):
                    conv = f"np.{f.attr}"
                else:
                    continue
                findings.append(Finding(
                    "JH006", sf.rel, node.lineno, sf.scope_of(node),
                    f"{conv}:{node.args[0].id}",
                    f"{conv}({node.args[0].id}) concretizes a traced value "
                    f"inside jit function {fn.name}"))

        # JH005: scratch-buffer params (init_* naming convention shared by
        # the scan kernels) without donation in the spec
        scratch = sorted(p for p in traced if p.startswith("init_"))
        if scratch:
            donated = any(kw.arg in ("donate_argnums", "donate_argnames")
                          for kw in spec.keywords)
            if not donated:
                findings.append(Finding(
                    "JH005", sf.rel, fn.lineno, sf.scope_of(fn),
                    ",".join(scratch),
                    f"kernel {fn.name} consumes scratch buffers "
                    f"{scratch} without donate_argnames"))
        return findings
