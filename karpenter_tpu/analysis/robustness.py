"""robustness checker family (RS*).

The supervision stack (operator/supervisor.py, ops/health.py,
utils/watchdog.py, utils/chaos.py) only works if faults actually reach
it and if its closed registries stay closed.  Three lexical rules:

  * RS001 — an ``except Exception``/bare ``except`` handler that swallows
    (no ``raise`` in the handler body) around a try body calling
    ``.reconcile()`` or ``.provision()``, anywhere outside the manager's
    `_supervised` funnel.  An inline swallow hides controller faults
    from the supervisor: no backoff, no circuit, no quarantine record —
    exactly the pre-supervision crash-loop this PR removed.
  * RS002 — a literal ``CHAOS.inject("<point>")`` whose point is not in
    `utils.chaos.POINTS`.  The registry is closed both ways: the chaos
    scenario schema validates against it, so an unregistered call site
    would be unreachable from any spec (and a typo would silently never
    fire).
  * RS003 — a literal ``run_with_deadline(..., "<phase>")`` whose phase
    is not in `utils.watchdog.PHASES`.  Same two-way contract: the
    `karpenter_watchdog_trips_total{phase}` label set and the docs
    enumerate the registry.
  * RS004 — a ``write_snapshot(...)`` call or a ``.create_fleet(`` /
    ``.terminate_instances(`` attribute call outside the fence-checked
    funnels (`state/snapshot.py`, `cloud/provider.py`,
    `cloud/batcher.py`).  HA fencing (utils/fencing.py) only holds if
    EVERY snapshot write and cloud mutation flows through a funnel that
    validates the fencing epoch — a new call site elsewhere is an
    unfenced write a deposed leader could still land.

`operator/manager.py` and `operator/supervisor.py` are exempt from RS001
— they ARE the supervision machinery (the manager's `_supervised` is the
one sanctioned except-Exception around a reconcile call).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Checker, Finding, SourceFile, rule

rule("RS001", "robustness",
     "controller fault swallowed outside the supervisor",
     "route the reconcile/provision call through the manager's "
     "supervised tick (operator/supervisor.py) instead of an inline "
     "except-Exception — supervision needs to see the failure to back "
     "off, open the circuit, and record the quarantine")
rule("RS002", "robustness",
     "CHAOS.inject point not in the registered POINTS set",
     "add the point to utils/chaos.py POINTS (and docs/robustness.md) "
     "before using it — unregistered points raise at inject time and "
     "can never be targeted by a chaos spec")
rule("RS003", "robustness",
     "run_with_deadline phase not in the registered PHASES set",
     "add the phase to utils/watchdog.py PHASES (and the "
     "karpenter_watchdog_trips_total docs row) before using it")
rule("RS004", "robustness",
     "snapshot write / cloud mutation outside the fence-checked funnel",
     "route the write through state/snapshot.py (SnapshotWriter or "
     "write_snapshot with the manager's fence) or the cloud provider's "
     "create/delete funnel — unfenced call sites let a deposed leader "
     "mutate shared state after a newer epoch took over")

_RS001_EXEMPT = frozenset({"karpenter_tpu/operator/manager.py",
                           "karpenter_tpu/operator/supervisor.py"})
_SUPERVISED_CALLS = frozenset({"reconcile", "provision"})
# the fence-checked funnels themselves: the only modules allowed to call
# the raw snapshot/cloud mutation seams (RS004 keeps them closed)
_RS004_EXEMPT = frozenset({"karpenter_tpu/state/snapshot.py",
                           "karpenter_tpu/cloud/provider.py",
                           "karpenter_tpu/cloud/batcher.py"})
_RS004_CLOUD_CALLS = frozenset({"create_fleet", "terminate_instances"})


def _points() -> frozenset:
    from ..utils.chaos import POINTS
    return POINTS


def _phases() -> frozenset:
    from ..utils.watchdog import PHASES
    return PHASES


def _broad_handler(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    names = []
    if isinstance(h.type, ast.Name):
        names = [h.type.id]
    elif isinstance(h.type, ast.Tuple):
        names = [e.id for e in h.type.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _swallows(h: ast.ExceptHandler) -> bool:
    return not any(isinstance(n, ast.Raise) for n in ast.walk(h))


def _supervised_call_in(body: List[ast.stmt]) -> Optional[str]:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SUPERVISED_CALLS:
                return node.func.attr
    return None


def _is_chaos_inject(call: ast.Call) -> bool:
    f = call.func
    return isinstance(f, ast.Attribute) and f.attr == "inject" and \
        isinstance(f.value, (ast.Name, ast.Attribute)) and \
        (f.value.id if isinstance(f.value, ast.Name)
         else f.value.attr) == "CHAOS"


def _rs004_escape(call: ast.Call) -> Optional[str]:
    """The mutation seam this call escapes through, or None.  Both the
    bare-name and module-qualified spellings of `write_snapshot` count;
    the cloud seams are method calls on whatever holds the substrate."""
    f = call.func
    if isinstance(f, ast.Name) and f.id == "write_snapshot":
        return "write_snapshot"
    if isinstance(f, ast.Attribute):
        if f.attr == "write_snapshot":
            return "write_snapshot"
        if f.attr in _RS004_CLOUD_CALLS:
            return f.attr
    return None


def _is_run_with_deadline(call: ast.Call) -> bool:
    f = call.func
    name = f.id if isinstance(f, ast.Name) else \
        f.attr if isinstance(f, ast.Attribute) else ""
    return name == "run_with_deadline"


def _literal(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class RobustnessChecker(Checker):
    family = "robustness"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        points, phases = _points(), _phases()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Try) and sf.rel not in _RS001_EXEMPT:
                called = _supervised_call_in(node.body)
                if called is not None:
                    for h in node.handlers:
                        if _broad_handler(h) and _swallows(h):
                            findings.append(Finding(
                                "RS001", sf.rel, h.lineno, sf.scope_of(h),
                                called,
                                f"except-Exception swallows a "
                                f".{called}() fault outside the "
                                f"supervisor — backoff/circuit/quarantine "
                                f"never see it"))
            elif isinstance(node, ast.Call):
                if sf.rel not in _RS004_EXEMPT:
                    seam = _rs004_escape(node)
                    if seam is not None:
                        findings.append(Finding(
                            "RS004", sf.rel, node.lineno,
                            sf.scope_of(node), seam,
                            f"{seam}() called outside the fence-checked "
                            f"funnel — a deposed leader could land this "
                            f"write with a stale fencing epoch"))
                if _is_chaos_inject(node) and node.args:
                    point = _literal(node.args[0])
                    if point is not None and point not in points:
                        findings.append(Finding(
                            "RS002", sf.rel, node.lineno, sf.scope_of(node),
                            point,
                            f"CHAOS.inject point {point!r} is not in "
                            f"utils.chaos.POINTS"))
                elif _is_run_with_deadline(node):
                    phase = _literal(node.args[2]) if len(node.args) >= 3 \
                        else next((_literal(kw.value) for kw in node.keywords
                                   if kw.arg == "phase"), None)
                    if phase is not None and phase not in phases:
                        findings.append(Finding(
                            "RS003", sf.rel, node.lineno, sf.scope_of(node),
                            phase,
                            f"run_with_deadline phase {phase!r} is not in "
                            f"utils.watchdog.PHASES"))
        return findings
