"""lock-discipline checker family (LK*).

Convention: a shared attribute is annotated where it is first assigned
(normally in `__init__`) with a trailing comment

    self._open: Dict = {}          # guarded-by: _lock
    self.nodes: Dict = {}          # guarded-by: caller(state_lock)

`guarded-by: <lock>` says every *write* to the attribute must be
lexically inside `with self.<lock>:` in the same class.  The
`caller(<lock>)` form documents an externally-held lock (the Cluster's
maps are mutated only under the Operator's `state_lock`, which the
ControllerManager's tick holds) — no lexical check is possible, but the
contract is recorded and the lock-order recorder still observes it at
test time.

Helper methods that are only ever called with the lock already held
(e.g. `Batcher._close`) are marked on their `def` line:

    def _close(self, key, bucket):  # graftlint: holds(_lock)

Rules:
  * LK001 — write to a guarded attribute outside `with self.<lock>:`.
  * LK002 — malformed annotation: the named lock attribute is never
    assigned in the class (typo-proofing the convention).

Writes are: assignment/augmented assignment to `self.X` (including
`self.X.field = ...` and `self.X[k] = ...`), `del self.X[...]`, and
mutating method calls (`self.X.append/add/pop/update/...`).  Reads are
deliberately out of scope — the codebase's read paths take snapshots
under the lock and the checker stays lexical, not alias-tracking.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Checker, Finding, SourceFile, rule

rule("LK001", "lock-discipline",
     "write to a guarded attribute outside its lock",
     "wrap the write in `with self.<lock>:`, or mark the enclosing helper "
     "`# graftlint: holds(<lock>)` if every caller already holds it")
rule("LK002", "lock-discipline",
     "guarded-by annotation names a lock the class never defines",
     "fix the lock name in the `# guarded-by:` comment (or assign "
     "`self.<lock>` in __init__)")

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*(caller\()?([A-Za-z_][\w.]*)\)?")
_HOLDS_RE = re.compile(r"#\s*graftlint:\s*holds\(([A-Za-z_][\w.]*)\)")

_MUTATORS = {"append", "add", "pop", "popitem", "discard", "remove",
             "clear", "update", "extend", "insert", "setdefault",
             "appendleft", "popleft", "__setitem__"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` → 'X' (depth-1 only)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _self_attr_root(node: ast.AST) -> Optional[str]:
    """Root attribute of a `self.X[...].y...` chain → 'X'."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        direct = _self_attr(node)
        if direct is not None:
            return direct
        node = node.value
    return None


class _ClassGuards:
    def __init__(self) -> None:
        self.guards: Dict[str, str] = {}          # attr -> lock name
        self.caller_guards: Dict[str, str] = {}   # attr -> external lock
        self.guard_lines: Dict[str, int] = {}
        self.lock_attrs: Set[str] = set()         # every self.X assigned


def _scan_class(sf: SourceFile, cls: ast.ClassDef) -> _ClassGuards:
    out = _ClassGuards()
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                out.lock_attrs.add(attr)
                m = _GUARD_RE.search(sf.line_text(node.lineno))
                if m:
                    if m.group(1):
                        out.caller_guards[attr] = m.group(2)
                    else:
                        out.guards[attr] = m.group(2)
                        out.guard_lines[attr] = node.lineno
    return out


def _with_locks(sf: SourceFile, node: ast.AST,
                stop: ast.FunctionDef) -> Set[str]:
    """Lock attribute names held by enclosing `with self.<lock>` blocks
    between `node` and the enclosing method `stop`."""
    held: Set[str] = set()
    parents = sf.parents()
    cur = parents.get(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    held.add(attr)
        cur = parents.get(cur)
    # the method itself may run entirely under the lock via `with` at its
    # top level even for `node is stop` descendants — handled above; also
    # honor a holds() marker on the def line or the line above it
    for lineno in (stop.lineno, stop.lineno - 1):
        m = _HOLDS_RE.search(sf.line_text(lineno))
        if m:
            held.add(m.group(1))
    return held


class LockDisciplineChecker(Checker):
    family = "lock-discipline"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        classes = {n.name: n for n in ast.walk(sf.tree)
                   if isinstance(n, ast.ClassDef)}
        for cls in classes.values():
            findings.extend(self._check_class(sf, cls, classes))
        return findings

    def _inherited_attrs(self, sf: SourceFile, cls: ast.ClassDef,
                         classes: Dict[str, ast.ClassDef]) -> Set[str]:
        """self.X assignments of same-file base classes (transitively) —
        locks like _Metric._lock are defined once in the base."""
        out: Set[str] = set()
        seen = {cls.name}
        frontier = [cls]
        while frontier:
            cur = frontier.pop()
            for base in cur.bases:
                name = base.id if isinstance(base, ast.Name) else None
                if name and name in classes and name not in seen:
                    seen.add(name)
                    out |= _scan_class(sf, classes[name]).lock_attrs
                    frontier.append(classes[name])
        return out

    def _check_class(self, sf: SourceFile, cls: ast.ClassDef,
                     classes: Dict[str, ast.ClassDef]) -> List[Finding]:
        guards = _scan_class(sf, cls)
        known_attrs = guards.lock_attrs | \
            self._inherited_attrs(sf, cls, classes)
        findings: List[Finding] = []
        for attr, lock in guards.guards.items():
            if lock not in known_attrs:
                findings.append(Finding(
                    "LK002", sf.rel, guards.guard_lines.get(attr, cls.lineno),
                    f"{cls.name}", attr,
                    f"{cls.name}.{attr} is guarded-by {lock!r} but the "
                    f"class never assigns self.{lock}"))
        if not guards.guards:
            return findings
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__":
                continue
            findings.extend(self._check_method(sf, cls, method, guards))
        return findings

    def _writes_in(self, method: ast.FunctionDef
                   ) -> List[Tuple[ast.AST, str, str]]:
        """(node, guarded-attr-candidate, kind) for every write site."""
        writes: List[Tuple[ast.AST, str, str]] = []
        for node in ast.walk(method):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    attr = _self_attr_root(tgt)
                    if attr is not None:
                        writes.append((node, attr, "assign"))
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    attr = _self_attr_root(tgt)
                    if attr is not None:
                        writes.append((node, attr, "del"))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                attr = _self_attr_root(node.func.value)
                if attr is not None:
                    writes.append((node, attr, node.func.attr))
        return writes

    def _check_method(self, sf: SourceFile, cls: ast.ClassDef,
                      method: ast.FunctionDef,
                      guards: _ClassGuards) -> List[Finding]:
        findings: List[Finding] = []
        for node, attr, kind in self._writes_in(method):
            lock = guards.guards.get(attr)
            if lock is None:
                continue
            held = _with_locks(sf, node, method)
            if lock not in held:
                findings.append(Finding(
                    "LK001", sf.rel, node.lineno,
                    f"{cls.name}.{method.name}", f"{attr}:{kind}",
                    f"write to {cls.name}.{attr} ({kind}) outside "
                    f"`with self.{lock}:`"))
        return findings
