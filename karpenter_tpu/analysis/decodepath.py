"""decode-path checker (JH007/JH008) — jax-hotpath family.

The DeviceDecode contract (`ops/decode.py`) is that plan assembly is
COLUMNAR: every artifact comes from gather/repeat/reduceat over slab
arrays, never a per-pod Python round.  That discipline rots the same way
the kernel disciplines do — one innocent `for pod in pods:` in a decode
assembler and the 1M-pod tick is back to seconds.  These rules hold
decode-annotated modules (a module carrying a standalone
`# graftlint: decode-path` marker line) to it:

  * JH007 — a Python loop over data rows: any `for`/`while`/comprehension
    whose iterable is not a literal `range(...)` call.  Per-NODE loops
    (bounded by cluster size, not pod count) are written as `range()`
    over node counts and stay clean; the residual-reconcile merge is the
    one grandfathered exception in tools/graftlint-baseline.json.
  * JH008 — host round-trips: `np.asarray(x.tolist())`-shaped calls
    anywhere, and `.tolist()` inside a loop body (a bulk `.tolist()` at
    the column boundary is the idiom; one per iteration is the rot).
"""

from __future__ import annotations

import ast
import re
from typing import List

from .core import Checker, Finding, SourceFile, rule

rule("JH007", "jax-hotpath",
     "per-pod Python loop in a decode-annotated module",
     "replace the row loop with column ops (gather/repeat/reduceat); "
     "per-node loops must iterate a literal range() over node counts — "
     "or baseline the finding when the loop is provably node-bounded "
     "(the residual-reconcile merge is)")
rule("JH008", "jax-hotpath",
     "host round-trip (.tolist() re-wrapped or inside a loop) in a "
     "decode-annotated module",
     "keep the data in one ndarray end to end; convert to Python lists "
     "once, at the final column boundary, never per iteration and never "
     "just to rebuild an array")

_MARKER_RE = re.compile(r"^\s*#\s*graftlint:\s*decode-path\s*$")
_ARRAY_WRAPPERS = {"asarray", "array"}


def _is_decode_module(sf: SourceFile) -> bool:
    return any(_MARKER_RE.match(line) for line in sf.lines)


def _target_names(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in node.elts:
            out.extend(_target_names(elt))
        return out
    return ["_"]


def _is_range_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "range")


def _is_tolist_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "tolist")


def _wraps_tolist(call: ast.Call) -> bool:
    """`np.asarray(x.tolist())` / `jnp.array(d["k"].tolist())` shapes —
    any array-constructor whose first argument is a `.tolist()` call."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _ARRAY_WRAPPERS):
        return False
    return bool(call.args) and _is_tolist_call(call.args[0])


class DecodePathChecker(Checker):
    family = "jax-hotpath"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        if not _is_decode_module(sf):
            return []
        out: List[Finding] = []
        parents = sf.parents()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.For) and not _is_range_call(node.iter):
                out.append(Finding(
                    "JH007", sf.rel, node.lineno, sf.scope_of(node),
                    ",".join(_target_names(node.target)),
                    "per-pod Python loop in decode-hot module — iterate "
                    "columns, not rows"))
            elif isinstance(node, ast.While):
                out.append(Finding(
                    "JH007", sf.rel, node.lineno, sf.scope_of(node),
                    "while",
                    "while loop in decode-hot module — decode assembly "
                    "must be straight-line column ops"))
            elif isinstance(node, ast.comprehension) and \
                    not _is_range_call(node.iter):
                out.append(Finding(
                    "JH007", sf.rel, node.iter.lineno, sf.scope_of(node.iter),
                    ",".join(_target_names(node.target)),
                    "per-pod comprehension in decode-hot module — iterate "
                    "columns, not rows"))
            elif isinstance(node, ast.Call):
                if _wraps_tolist(node):
                    out.append(Finding(
                        "JH008", sf.rel, node.lineno, sf.scope_of(node),
                        "asarray-of-tolist",
                        "array → list → array round-trip — keep the "
                        "ndarray"))
                elif _is_tolist_call(node) and \
                        self._in_loop_body(node, parents):
                    out.append(Finding(
                        "JH008", sf.rel, node.lineno, sf.scope_of(node),
                        "tolist-in-loop",
                        ".tolist() inside a loop body — hoist the bulk "
                        "conversion out of the loop"))
        return out

    @staticmethod
    def _in_loop_body(node: ast.AST, parents) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.While, ast.ListComp,
                                ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            cur = parents.get(cur)
        return False
