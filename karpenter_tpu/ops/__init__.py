from .tensorize import LaunchOption, Problem, build_options, tensorize, pad_to
from .ffd import NodeDecision, PackingResult, ffd_pack_kernel, solve_ffd, NO_ASSIGNMENT
from .classpack import class_pack_kernel, solve_classpack
