"""SolverHealth: one degradation ladder over the solver path zoo.

Before this module the fallbacks were piecewise and stateless: the
partitioned driver falls back to single-device on refusal
(parallel/driver.py), solve_ffd falls back from native when the C++ core
is unavailable, the LP guide falls back to greedy on a cold cache.  None
of them REMEMBER: a device that hangs every tick is retried every tick.

`SolverHealth` is the shared state machine both solve paths
(Provisioner.solve, DisruptionController.simulate) consult:

    sharded ──▶ jax ──▶ native ──▶ greedy

Repeated errors (or a single watchdog timeout — a hung device must not
get a second chance inside the same incident) demote a rung for a
backoff window that doubles per consecutive demotion; when the window
expires the next solve is a half-open probe — success promotes back
instantly, failure re-demotes for a longer window.  The greedy rung
(pure-NumPy FFD, ops/ffd.py backend="numpy") never demotes: it touches
no device, terminates by construction, and guarantees every tick still
produces *a* plan.

Every transition is logged, traced onto the active span, and counted in
karpenter_degradation_transitions_total{from,to,reason}.  The clock is
injectable so the ladder is deterministic under the sim's virtual clock.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..obs.incidents import publish_incident
from ..utils import metrics, tracing

log = logging.getLogger("karpenter_tpu.health")

# Ladder order, best rung first.  "sharded" = partitioned mesh solve,
# "jax" = the single-device kernels (classpack or scan FFD), "native" =
# the C++ packer, "greedy" = host NumPy FFD (guaranteed bottom).
RUNGS = ("sharded", "jax", "native", "greedy")
RUNG_INDEX = {r: i for i, r in enumerate(RUNGS)}

# The LP solver ladder (DeviceLP gate): the vmapped PDHG solver in
# ops/lpsolve.py sits above the host HiGHS path.  HiGHS is the bottom
# rung — exact, host-only, terminates — so it never demotes, exactly
# like "greedy" in the packing ladder.
LP_RUNGS = ("device_lp", "highs")

DEMOTE_AFTER_ERRORS = 2       # consecutive errors before demotion
DEFAULT_WINDOW_S = 60.0       # first demotion window
DEFAULT_WINDOW_MAX_S = 600.0  # doubling cap


@dataclass
class _RungState:
    failures: int = 0            # consecutive errors since last success
    demotions: int = 0           # consecutive demotions (window doubling)
    demoted_until: float = float("-inf")
    probing: bool = False        # a half-open probe is in flight
    total_failures: int = 0
    total_demotions: int = 0


class SolverHealth:
    """Shared ladder state.  Callers hold the state lock for the solve
    paths that consult this, so no internal locking is needed; the
    /debug/health snapshot reads plain attributes."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 demote_after: int = DEMOTE_AFTER_ERRORS,
                 window_s: float = DEFAULT_WINDOW_S,
                 window_max_s: float = DEFAULT_WINDOW_MAX_S,
                 rungs: tuple = RUNGS):
        self.clock = clock
        self.demote_after = max(1, int(demote_after))
        self.window_s = float(window_s)
        self.window_max_s = float(window_max_s)
        self.rungs = tuple(rungs)
        if len(self.rungs) < 2:
            raise ValueError("ladder needs at least two rungs")
        self.rung_index = {r: i for i, r in enumerate(self.rungs)}
        self._state: Dict[str, _RungState] = {r: _RungState()
                                              for r in self.rungs}
        # deterministic transition tally for reports: "from>to:reason" → n
        self.transitions: Dict[str, int] = {}

    # ------------------------------------------------------------------
    def active_rung(self, requested: Optional[str] = None) -> str:
        """Best non-demoted rung at or below `requested`.  An expired
        demotion window turns the rung into a half-open probe: it is
        offered exactly once; failure re-demotes, success promotes."""
        if requested is None:
            requested = "jax" if "jax" in self.rung_index else self.rungs[0]
        now = self.clock()
        for rung in self.rungs[self.rung_index[requested]:]:
            st = self._state[rung]
            if st.demoted_until <= now:
                if st.demotions and not st.probing:
                    st.probing = True
                    log.info("solver rung %s: half-open probe", rung)
                return rung
        return self.rungs[-1]  # unreachable: bottom rung never demotes

    def next_rung(self, rung: str) -> Optional[str]:
        i = self.rung_index[rung] + 1
        return self.rungs[i] if i < len(self.rungs) else None

    # ------------------------------------------------------------------
    def report_success(self, rung: str) -> None:
        st = self._state[rung]
        if st.probing or st.demotions:
            self._transition(rung, rung, "recovered")
        st.failures = 0
        st.demotions = 0
        st.probing = False
        st.demoted_until = float("-inf")
        self._export_rung()

    def report_failure(self, rung: str, reason: str = "error") -> None:
        """`reason` is "timeout" (watchdog trip — demote immediately) or
        "error" (demote after `demote_after` consecutive failures, or
        immediately when the failure hit a half-open probe)."""
        st = self._state[rung]
        st.failures += 1
        st.total_failures += 1
        if rung == self.rungs[-1]:
            return  # bottom rung: never demoted, failures only counted
        if reason == "timeout" or st.probing or \
                st.failures >= self.demote_after:
            st.probing = False
            st.failures = 0
            st.demotions += 1
            st.total_demotions += 1
            window = min(self.window_s * (2.0 ** (st.demotions - 1)),
                         self.window_max_s)
            st.demoted_until = self.clock() + window
            self._transition(rung, self.next_rung(rung) or rung, reason)
        self._export_rung()

    # ------------------------------------------------------------------
    def _transition(self, frm: str, to: str, reason: str) -> None:
        key = f"{frm}>{to}:{reason}"
        self.transitions[key] = self.transitions.get(key, 0) + 1
        metrics.degradation_transitions().inc(
            {"from": frm, "to": to, "reason": reason})
        if reason != "recovered":
            publish_incident("solver_demotion", {
                "from": frm, "to": to, "reason": reason,
                "transitions": dict(self.transitions)})
        tracing.annotate(degradation=key)
        if reason == "recovered":
            log.info("solver ladder: rung %s recovered", frm)
        else:
            log.warning("solver ladder: %s demoted to %s (%s), window %.0fs",
                        frm, to, reason,
                        self._state[frm].demoted_until - self.clock())

    def _export_rung(self) -> None:
        # lowest healthy rung index as a gauge (0 = best rung healthy)
        now = self.clock()
        for i, rung in enumerate(self.rungs):
            if self._state[rung].demoted_until <= now:
                metrics.degradation_rung().set(i)
                return

    # ---- warm restart (state/snapshot.py) ----------------------------
    def snapshot_state(self) -> Dict:
        """Round-trippable export of the whole ladder for the WarmRestart
        snapshot.  `demoted_until` values are absolute clock readings, so
        they only transfer between processes sharing a clock domain (the
        sim's virtual clock, or a wall-clock restart where stale windows
        simply read as expired)."""
        return {
            "rungs": {
                rung: {
                    "failures": st.failures,
                    "demotions": st.demotions,
                    "demoted_until": st.demoted_until,
                    "probing": st.probing,
                    "total_failures": st.total_failures,
                    "total_demotions": st.total_demotions,
                } for rung, st in self._state.items()
            },
            "transitions": dict(self.transitions),
        }

    def restore_state(self, data: Dict) -> None:
        for rung, st in data["rungs"].items():
            if rung not in self._state:
                continue
            cur = self._state[rung]
            cur.failures = int(st["failures"])
            cur.demotions = int(st["demotions"])
            cur.demoted_until = float(st["demoted_until"])
            cur.probing = bool(st["probing"])
            cur.total_failures = int(st["total_failures"])
            cur.total_demotions = int(st["total_demotions"])
        self.transitions = dict(data["transitions"])

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict:
        """Deterministic ladder state for /debug/health and tests."""
        now = self.clock()
        return {
            "rungs": {
                rung: {
                    "demoted": st.demoted_until > now,
                    "demoted_for_s": round(max(0.0, st.demoted_until - now), 3),
                    "consecutive_failures": st.failures,
                    "consecutive_demotions": st.demotions,
                    "probing": st.probing,
                    "total_failures": st.total_failures,
                    "total_demotions": st.total_demotions,
                } for rung in self.rungs for st in (self._state[rung],)
            },
            "transitions": dict(sorted(self.transitions.items())),
        }


def lp_ladder(clock: Callable[[], float] = time.monotonic,
              demote_after: int = DEMOTE_AFTER_ERRORS,
              window_s: float = DEFAULT_WINDOW_S,
              window_max_s: float = DEFAULT_WINDOW_MAX_S) -> SolverHealth:
    """The DeviceLP degradation ladder: device_lp ──▶ highs.

    Same state machine, demotion windows, half-open probes, metrics and
    `solver_demotion` incident funnel as the packing ladder — only the
    rung names differ.  Non-convergence of the PDHG solver (iteration
    cap, residual plateau, certificate failure) reports a failure on
    "device_lp"; after `demote_after` consecutive failures the guide
    answers from the HiGHS path until the window expires."""
    return SolverHealth(clock=clock, demote_after=demote_after,
                        window_s=window_s, window_max_s=window_max_s,
                        rungs=LP_RUNGS)
