"""Gang / topology-aware scheduling (the `GangScheduling` feature gate).

The solver places every pod independently; the tightly-coupled workloads
this repo is named for (multi-chip TPU slices, MPI gangs) need the
opposite: a *gang* of pods is useful only when every member runs, and
only when the members land close enough to each other to talk (one zone,
or one host).  This module supplies the missing semantics as a post-solve
audit over the dense packing — the kernels stay gang-oblivious and fast,
and the all-or-nothing / topology invariants are enforced where the plan
becomes visible, before any bind or launch:

* `audit_gangs` inspects a `PackingResult` and classifies every gang in
  the batch as admitted or rejected (`incomplete` — fewer members arrived
  than `gang_size` declares; `partial` — the solver left members
  unplaced; `straddle` — members placed across more than one topology
  domain).
* `enforce_gangs` strips every member of a rejected gang from the plan
  (`PackingResult.strip_pods`), so partial gangs never reach
  `claim_requests` or `bind_pod`, and records per-pod rejection info on
  `problem.gang_rejections` for `utils/provenance.explain_unschedulable`.
* `plan_preemption` builds the priority cascade: when a rejected gang
  outranks bound pods (strictly lower `gang_tier`), it computes the
  cheapest victim prefix — tier ascending, then disruption cost — whose
  eviction frees enough capacity in ONE topology domain.  The plan is
  capacity arithmetic, not a packing probe: the DisruptionController
  executes it like consolidation reschedules (victims unbind to pending)
  and the *real* solver admits the gang on a later round, so a bad plan
  costs churn, never correctness.
* `GangRegistry` is the durable ledger of gang admission state, carried
  through `state/snapshot.py` so a restart can prove no gang was ever
  half-admitted.

Everything here iterates in sorted order and touches no wall clock
(graftlint DT003): identical solves produce identical audits, plans and
registry states.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.resources import ResourceList
from .tensorize import GangInfo

# Rejection reasons — the bounded vocabulary used as the
# karpenter_gang_rejections_total label (graftlint OB003: never the gang
# name, which is user-controlled and unbounded).
INCOMPLETE = "incomplete"   # fewer members arrived than gang_size declares
PARTIAL = "partial"         # solver left some arrived members unplaced
STRADDLE = "straddle"       # all placed, but across >1 topology domain


@dataclass
class GangAudit:
    """One gang's verdict for one solve."""
    gang: GangInfo
    members: Tuple[int, ...]    # original pod indices in the batch, sorted
    placed: Tuple[int, ...]     # members the packing placed, sorted
    domains: Tuple[str, ...]    # distinct topology-domain tokens touched
    admitted: bool
    reason: str = ""            # INCOMPLETE / PARTIAL / STRADDLE when rejected
    message: str = ""           # human form, mirrored into FailedScheduling
    bound: int = 0              # members already bound outside this batch
    bound_domains: Tuple[str, ...] = ()  # domains those residents occupy


@dataclass
class PreemptionVictim:
    uid: str
    pod: str
    node: str
    tier: int
    cost: float


@dataclass
class PreemptionPlan:
    """Evict `victims` (in order) to free room for `gang` in `domain`."""
    gang: str
    tier: int
    topology: str
    domain: str
    victims: List[PreemptionVictim]
    total_cost: float


def gang_members(problem) -> Dict[int, List[int]]:
    """gang index → sorted original pod indices, from the class columns."""
    out: Dict[int, List[int]] = {}
    if problem.class_gang is None:
        return out
    for ci, g in enumerate(problem.class_gang.tolist()):
        if g < 0:
            continue
        out.setdefault(int(g), []).extend(
            int(i) for i in np.asarray(problem.class_members[ci], np.int64))
    for g in out:
        out[g].sort()
    return out


def _placements(result, existing_nodes, topology: str) -> Dict[int, str]:
    """pod index → topology-domain token for every pod the packing placed.

    zone granularity: new nodes take their launch option's zone, existing
    nodes their live zone — same zone == same domain either way.  hostname
    granularity: every node (new decision or existing slot) is its own
    domain, so a gang must fit on ONE machine."""
    dom: Dict[int, str] = {}
    for di, dec in enumerate(result.nodes):
        token = dec.option.zone if topology == "zone" else f"new:{di}"
        for i in dec.pod_indices:
            dom[int(i)] = token
    for i, slot in result.existing_assignments.items():
        node = existing_nodes[slot]
        dom[int(i)] = node.zone if topology == "zone" else f"node:{node.name}"
    return dom


def _residents(gang: GangInfo, cluster_nodes: Sequence) -> Dict[str, int]:
    """Domain token → count of the gang's already-bound members.

    A gang that lost part of itself after admission (spot reclaim killed
    a member's node) re-enters the batch with fewer pods than its size
    declares; the still-bound members count toward completeness and pin
    the topology domain the stragglers must rejoin."""
    out: Dict[str, int] = {}
    for n in sorted(cluster_nodes, key=lambda n: n.name):
        cnt = sum(1 for p in n.pods if p.gang_name == gang.name)
        if cnt:
            token = (n.zone if gang.topology == "zone" else n.name) or ""
            out[token] = out.get(token, 0) + cnt
    return out


def audit_gangs(problem, result, existing_nodes: Sequence,
                cluster_nodes: Sequence = ()) -> List[GangAudit]:
    """Classify every gang in the batch against one packing, gang order."""
    audits: List[GangAudit] = []
    by_gang = gang_members(problem)
    placements: Dict[str, Dict[int, str]] = {}
    for g in sorted(by_gang):
        gang = problem.gangs[g]
        members = by_gang[g]
        dom = placements.get(gang.topology)
        if dom is None:
            dom = placements[gang.topology] = _placements(
                result, existing_nodes, gang.topology)
        placed = tuple(i for i in members if i in dom)
        bound = _residents(gang, cluster_nodes)
        bound_n = sum(bound.values())
        bound_domains = tuple(sorted(bound))
        present = len(members) + bound_n
        domains = tuple(sorted({dom[i] for i in placed} | set(bound)))
        if present < gang.size:
            admitted, reason = False, INCOMPLETE
            message = (f"gang incomplete: {present}/{gang.size} "
                       "members present")
        elif len(placed) < len(members):
            admitted, reason = False, PARTIAL
            message = (f"gang partially placeable: "
                       f"{len(placed) + bound_n}/{present}")
        elif len(domains) > 1:
            admitted, reason = False, STRADDLE
            message = (f"gang straddles {len(domains)} {gang.topology} "
                       f"domains: {list(domains)[:4]}")
        else:
            admitted, reason, message = True, "", ""
        audits.append(GangAudit(gang=gang, members=tuple(members),
                                placed=placed, domains=domains,
                                admitted=admitted, reason=reason,
                                message=message, bound=bound_n,
                                bound_domains=bound_domains))
    return audits


def enforce_gangs(problem, result, existing_nodes: Sequence,
                  registry: Optional["GangRegistry"] = None,
                  cluster_nodes: Sequence = ()) -> List[GangAudit]:
    """All-or-nothing enforcement: audit, then strip every member of every
    rejected gang from `result` in place (they come back unschedulable) and
    record per-pod rejection info on `problem.gang_rejections` for the
    provenance walk.  Returns ALL audits; callers split admitted/rejected
    for metrics.  No partial gang bind can survive this call."""
    audits = audit_gangs(problem, result, existing_nodes,
                         cluster_nodes=cluster_nodes)
    rejected = [a for a in audits if not a.admitted]
    if rejected:
        rejections: Dict[int, Dict] = dict(
            getattr(problem, "gang_rejections", None) or {})
        strip: set = set()
        for a in rejected:
            placed_set = set(a.placed)
            unplaced = [i for i in a.members if i not in placed_set]
            # the "worst" member: first unplaced one — provenance replays
            # its catalog walk to name the first failing constraint
            worst = unplaced[0] if unplaced else -1
            info = {"gang": a.gang.name, "size": a.gang.size,
                    "tier": a.gang.tier, "topology": a.gang.topology,
                    "arrived": len(a.members) + a.bound,
                    "placed": len(a.placed),
                    "placed_members": list(a.placed),
                    "reason": a.reason, "message": a.message,
                    "worst": worst}
            for i in a.members:
                rejections[i] = info
            strip.update(a.members)
        result.strip_pods(strip, pods=problem.pods)
        problem.gang_rejections = rejections
    if registry is not None:
        for a in audits:
            registry.observe(a)
    return audits


def gang_demand(problem, members: Sequence[int]) -> ResourceList:
    """Summed resource requests of a gang's arrived members."""
    total = ResourceList()
    for i in members:
        total = total + problem.pods[i].requests
    return total


def victim_cost(pod) -> float:
    """Eviction cost for cascade ordering.  Mirrors
    `controllers/disruption.pod_disruption_cost` (ops must not import
    controllers); tests/test_gang.py pins the two formulas together."""
    return 1.0 + max(pod.priority, 0) / 1e4 + pod.deletion_cost / 1e3


def _first_fit(member_reqs: Sequence[ResourceList],
               free: Dict[str, ResourceList],
               order: Sequence[str]) -> bool:
    """Every member lands on SOME node at the current free capacities?
    First-fit over name-sorted nodes, members largest-first — the cheap
    stand-in for the real packing the solver will run next round."""
    avail = dict(free)
    for req in member_reqs:
        for name in order:
            if req.fits(avail[name]):
                avail[name] = avail[name] - req
                break
        else:
            return False
    return True


def plan_preemption(gang: GangInfo, member_requests: Sequence[ResourceList],
                    nodes: Sequence,
                    pin_domains: Sequence[str] = ()) -> Optional[PreemptionPlan]:
    """Pick the cheapest victim set whose eviction lets every gang member
    first-fit into ONE topology domain.

    Candidates are bound pods of strictly lower gang tier that are fair
    game for disruption (owned, not daemons, not do-not-disrupt), ordered
    by (tier asc, disruption cost asc, uid) — the priority cascade.  Per
    domain we take the minimal prefix of that order under which every
    member first-fits onto some node (per-node capacities, NOT an
    aggregate sum: a domain with plenty of total headroom but no single
    node large enough for a member must keep evicting, or the plan frees
    nothing the solver can use); the best domain is the one needing the
    fewest victims (ties: lower total cost, then domain name).  First-fit
    is a conservative stand-in for the real packing — the plan only frees
    capacity, the real solver re-admits the gang next round, and if
    fragmentation still blocks it the next plan evicts further down the
    cascade.  `pin_domains` restricts the search to the listed tokens —
    a gang with members still bound somewhere must free room in THAT
    domain, or the stragglers rejoin as a straddle."""
    reqs = sorted(member_requests,
                  key=lambda r: tuple(sorted(r.items())), reverse=True)
    domains: Dict[str, List] = {}
    pins = set(pin_domains)
    for n in nodes:
        if getattr(n, "marked_for_deletion", False):
            continue
        token = (n.zone if gang.topology == "zone" else n.name) or ""
        if pins and token not in pins:
            continue
        domains.setdefault(token, []).append(n)
    best: Optional[PreemptionPlan] = None
    best_key = None
    for token in sorted(domains):
        dnodes = sorted(domains[token], key=lambda n: n.name)
        order = [n.name for n in dnodes]
        free = {n.name: n.available() for n in dnodes}
        victims: List[Tuple[Tuple, PreemptionVictim, ResourceList]] = []
        for n in dnodes:
            for p in n.pods:
                if (p.gang_tier >= gang.tier or p.is_daemon
                        or p.do_not_disrupt or not p.owner_kind):
                    continue
                cost = victim_cost(p)
                victims.append(((p.gang_tier, cost, p.uid),
                                PreemptionVictim(uid=p.uid, pod=p.name,
                                                 node=n.name,
                                                 tier=p.gang_tier, cost=cost),
                                p.requests))
        victims.sort(key=lambda v: v[0])
        chosen: List[PreemptionVictim] = []
        feasible = _first_fit(reqs, free, order)
        for _, victim, req in victims:
            if feasible:
                break
            free[victim.node] = free[victim.node] + req
            chosen.append(victim)
            feasible = _first_fit(reqs, free, order)
        if not feasible or not chosen:
            # infeasible even with every victim gone, or feasible with
            # none — either way eviction buys this gang nothing here
            continue
        total_cost = sum(v.cost for v in chosen)
        key = (len(chosen), total_cost, token)
        if best_key is None or key < best_key:
            best_key = key
            best = PreemptionPlan(gang=gang.name, tier=gang.tier,
                                  topology=gang.topology, domain=token,
                                  victims=chosen, total_cost=total_cost)
    return best


@dataclass
class GangRecord:
    """Durable per-gang admission state (the registry's unit)."""
    name: str
    size: int = 0
    tier: int = 0
    topology: str = "zone"
    admitted: bool = False      # latest verdict: fully bound right now?
    admissions: int = 0
    rejections: int = 0
    last_reason: str = ""
    preempted: int = 0          # victims evicted on this gang's behalf

    def to_dict(self) -> Dict:
        return {"name": self.name, "size": self.size, "tier": self.tier,
                "topology": self.topology, "admitted": self.admitted,
                "admissions": self.admissions, "rejections": self.rejections,
                "last_reason": self.last_reason, "preempted": self.preempted}


class GangRegistry:
    """name → GangRecord: every gang the provisioner has ever audited.

    The snapshot section (`state/snapshot.py` "gang") serializes this, so
    a restarted operator knows which gangs were fully admitted at the
    checkpoint — the restart test proves a kill -9 can never surface a
    half-admitted gang, because admission itself is atomic (enforce_gangs
    strips rejected gangs before any bind)."""

    def __init__(self):
        self._gangs: Dict[str, GangRecord] = {}

    def __len__(self) -> int:
        return len(self._gangs)

    def get(self, name: str) -> Optional[GangRecord]:
        return self._gangs.get(name)

    def observe(self, audit: GangAudit) -> GangRecord:
        g = audit.gang
        rec = self._gangs.get(g.name)
        if rec is None:
            rec = self._gangs[g.name] = GangRecord(name=g.name)
        rec.size, rec.tier, rec.topology = g.size, g.tier, g.topology
        rec.admitted = audit.admitted
        if audit.admitted:
            rec.admissions += 1
            rec.last_reason = ""
        else:
            rec.rejections += 1
            rec.last_reason = audit.reason
        return rec

    def record_preemption(self, name: str, victims: int) -> None:
        rec = self._gangs.get(name)
        if rec is None:
            rec = self._gangs[name] = GangRecord(name=name)
        rec.preempted += victims

    def summary(self) -> Dict[str, Dict]:
        """Deterministic name-sorted view (debug endpoint + sim report)."""
        return {name: self._gangs[name].to_dict()
                for name in sorted(self._gangs)}

    # ---- snapshot section (state/snapshot.py "gang") ----
    def snapshot_state(self) -> Dict:
        return {"gangs": self.summary()}

    def restore_state(self, state: Dict) -> None:
        self._gangs.clear()
        for name in sorted(state.get("gangs", {})):
            d = state["gangs"][name]
            self._gangs[name] = GangRecord(
                name=name, size=int(d.get("size", 0)),
                tier=int(d.get("tier", 0)),
                topology=str(d.get("topology", "zone")),
                admitted=bool(d.get("admitted", False)),
                admissions=int(d.get("admissions", 0)),
                rejections=int(d.get("rejections", 0)),
                last_reason=str(d.get("last_reason", "")),
                preempted=int(d.get("preempted", 0)))
