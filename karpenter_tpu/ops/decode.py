"""Device-side decode: columnar plan assembly from the slot slab.

The classpack kernels already pick per-class node counts on device; the
expensive part of a 1M-pod solve was never the solve — it was decode,
the pod→node extraction, which `parallel/sharded._assemble_plan` walked
one pod at a time in Python (~4.1s at 1M pods, ROADMAP item 2).  This
module replaces that walk with column operations over a SLAB the kernel
now emits (`class_pack_assign_slab_kernel`):

    order        row ids stable-sorted by slot (unscheduled rows, then
                 padding, sort to the back under key=K)
    slot_counts  pods per slot — node run lengths after the sort
    slot_option  option column per slot (unchanged kernel output)

From those three arrays every plan artifact is a gather/repeat/reduceat:
node boundaries are the cumsum of the occupied slot counts, per-node
usage is one `np.add.reduceat`, the existing-fill dict is a single
`dict(zip(...))` over two columns, and the fleet launch cost is a
float64 cumsum that reproduces the legacy sequential accumulation bit
for bit.  The contract of both assemblers is EXACT equality with the
legacy decoders — same node order, same pod order inside a node, same
dict insertion order, same float — pinned by tests/test_decode.py and
the gate-ON sim goldens.

Every function here is decode-hot (`graftlint` JH007/JH008 hold the
whole module to the no-per-pod-Python discipline); the deliberate
per-existing-node exceptions are `range()` loops over node counts, and
the residual-reconcile merge is grandfathered in the baseline.

`DecodeHealth` is the single-rung analog of `ops/health.SolverHealth`:
a slab-assembly failure falls back to host assembly with a counted
outcome (`karpenter_decode_solves_total{outcome="fallback"}`) and
demotes the device path for a doubling backoff window, so one bad
decode never fails a tick and a persistently bad one stops being
retried every tick.  It is snapshot-registered (`state/snapshot.py`
section "decode") like every stateful piece of solver health.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..obs.incidents import publish_incident
from ..utils import metrics

log = logging.getLogger("karpenter_tpu.decode")

# graftlint: decode-path

# Below this many pods the single-device slab path is not worth the extra
# on-device sort: the legacy decode's host argsort on a few hundred rows
# is already microseconds, and small batches are the sim's steady state.
# (The partitioned driver has its own MIN_PODS floor and ignores this.)
DEVICE_DECODE_FLOOR = 512

DEMOTE_AFTER_ERRORS = 2       # consecutive failures before demotion
DEFAULT_WINDOW_S = 60.0       # first demotion window
DEFAULT_WINDOW_MAX_S = 600.0  # doubling cap


class DecodeHealth:
    """Single-rung breaker for the DeviceDecode path: device ⇄ host.

    Same mechanics as the SolverHealth ladder (ops/health.py) collapsed
    to one rung: repeated slab failures demote device decode for a
    backoff window that doubles per consecutive demotion; an expired
    window offers exactly one half-open probe — success promotes back,
    failure re-demotes for longer.  Host assembly is the greedy-rung
    analog: always available, never demoted.  Clock is injectable so the
    breaker is deterministic under the sim's virtual clock, and the
    state round-trips through the WarmRestart snapshot."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 demote_after: int = DEMOTE_AFTER_ERRORS,
                 window_s: float = DEFAULT_WINDOW_S,
                 window_max_s: float = DEFAULT_WINDOW_MAX_S):
        self.clock = clock
        self.demote_after = max(1, int(demote_after))
        self.window_s = float(window_s)
        self.window_max_s = float(window_max_s)
        self.failures = 0            # consecutive, since last success
        self.demotions = 0           # consecutive (window doubling)
        self.demoted_until = float("-inf")
        self.probing = False         # a half-open probe is in flight
        self.total_failures = 0
        self.total_demotions = 0
        # deterministic transition tally: "event:reason" → n
        self.transitions: Dict[str, int] = {}

    def allow(self) -> bool:
        """True when the device path may run.  An expired demotion window
        turns into a half-open probe: offered once; failure re-demotes."""
        now = self.clock()
        if self.demoted_until <= now:
            if self.demotions and not self.probing:
                self.probing = True
                log.info("device decode: half-open probe")
            return True
        return False

    def report_success(self) -> None:
        if self.probing or self.demotions:
            self._transition("recovered", "recovered")
        self.failures = 0
        self.demotions = 0
        self.probing = False
        self.demoted_until = float("-inf")
        metrics.decode_demoted().set(0)

    def report_failure(self, reason: str = "error") -> None:
        self.failures += 1
        self.total_failures += 1
        if self.probing or self.failures >= self.demote_after:
            self.probing = False
            self.failures = 0
            self.demotions += 1
            self.total_demotions += 1
            window = min(self.window_s * (2.0 ** (self.demotions - 1)),
                         self.window_max_s)
            self.demoted_until = self.clock() + window
            self._transition("demoted", reason)
            log.warning("device decode demoted to host assembly (%s), "
                        "window %.0fs", reason, window)
        metrics.decode_demoted().set(
            1 if self.demoted_until > self.clock() else 0)

    def _transition(self, event: str, reason: str) -> None:
        key = f"{event}:{reason}"
        self.transitions[key] = self.transitions.get(key, 0) + 1
        metrics.decode_transitions().inc({"event": event, "reason": reason})
        if event != "recovered":
            publish_incident("decode_demotion", {
                "reason": reason, "demotions": self.demotions,
                "transitions": dict(self.transitions)})
        if event == "recovered":
            log.info("device decode recovered")

    # ---- warm restart (state/snapshot.py section "decode") -----------
    def snapshot_state(self) -> Dict:
        """Round-trippable breaker state; `demoted_until` is an absolute
        clock reading, valid only within one clock domain (the sim's
        virtual clock, or a wall restart where stale windows read as
        expired — same contract as SolverHealth)."""
        return {
            "failures": self.failures,
            "demotions": self.demotions,
            "demoted_until": self.demoted_until,
            "probing": self.probing,
            "total_failures": self.total_failures,
            "total_demotions": self.total_demotions,
            "transitions": dict(self.transitions),
        }

    def restore_state(self, data: Dict) -> None:
        self.failures = int(data["failures"])
        self.demotions = int(data["demotions"])
        self.demoted_until = float(data["demoted_until"])
        self.probing = bool(data["probing"])
        self.total_failures = int(data["total_failures"])
        self.total_demotions = int(data["total_demotions"])
        self.transitions = dict(data["transitions"])


# shared default for direct solve_classpack callers; the operator wires a
# clock-injected instance through the Provisioner instead
DEFAULT_DECODE_HEALTH = DecodeHealth()


def slab_to_assignment(order_idx: np.ndarray, slot_counts: np.ndarray,
                       n_rows: int, K: int) -> np.ndarray:
    """Reconstruct the legacy per-row assignment vector from the slab —
    the host-fallback bridge when slab assembly fails after the kernel
    already ran (re-dispatching the kernel would double the device
    cost).  Exact inverse of the slab sort: rows order[:S] carry slots
    repeat(arange(K), slot_counts); everything else is unscheduled."""
    order_idx = np.asarray(order_idx, np.int64)
    slot_counts = np.asarray(slot_counts, np.int64)
    S = int(slot_counts.sum())
    out = np.full(n_rows, -1, np.int32)
    out[order_idx[:S]] = np.repeat(
        np.arange(K, dtype=np.int32), slot_counts)
    return out


def assemble_slab_single(problem, order_idx, slot_counts, slot_option,
                         pod_idx, class_of_row, E: int, K: int,
                         max_alternatives: int, n_rows: int):
    """Single-device slab → PackingResult, bit-identical to the legacy
    `solve_classpack` decode over the same kernel output.

    Parity notes (each pins a byte of the legacy output):
    - unschedulable: the key-K segment of `order` keeps original row
      order under the stable sort — same list as `pod_idx[~sched]`.
    - existing fills: the slab is slot-sorted but the legacy dict is
      ROW-ordered, so the existing segment is argsorted back to row
      order before the dict(zip(...)).
    - per-node usage: the same `np.add.reduceat` over float32 request
      rows the legacy decode runs (exact: integer-valued floats).
    """
    from .classpack import resolve_alternatives
    from .ffd import NodeDecision, PackingResult

    O = problem.num_options
    order_idx = np.asarray(order_idx, np.int64)
    slot_counts = np.asarray(slot_counts, np.int64)
    S = int(slot_counts.sum())
    take = order_idx[:S]
    unschedulable = pod_idx[order_idx[S:S + (n_rows - S)]].tolist()

    nE = int(slot_counts[:E].sum()) if E else 0
    if nE:
        ex_rows = take[:nE]
        eids = np.repeat(np.arange(E, dtype=np.int64), slot_counts[:E])
        ro = np.argsort(ex_rows, kind="stable")
        existing_assignments = dict(zip(pod_idx[ex_rows[ro]].tolist(),
                                        eids[ro].tolist()))
    else:
        existing_assignments = {}

    new_sorted = take[nE:]
    cnts = slot_counts[E:]
    occ = np.nonzero(cnts)[0]
    run = cnts[occ]
    node_slots = (occ + E).astype(np.int64)
    ends = np.cumsum(run)
    starts = ends - run
    ks = np.repeat(node_slots, run)
    cls_sorted = class_of_row[new_sorted]

    if len(starts):
        row_reqs = problem.class_requests[cls_sorted]
        node_used = np.add.reduceat(row_reqs, starts, axis=0).astype(np.int64)
    else:
        node_used = np.zeros((0, problem.class_requests.shape[1]), np.int64)

    Cn = problem.num_classes
    upq = np.unique(ks * (Cn + 1) + cls_sorted) if len(ks) else \
        np.zeros(0, np.int64)
    uslot, ucls = upq // (Cn + 1), upq % (Cn + 1)
    cls_starts = np.searchsorted(uslot, node_slots, side="left")
    cls_ends = np.searchsorted(uslot, node_slots, side="right")

    pod_sorted = pod_idx[new_sorted].tolist()
    node_oi = slot_option[node_slots].astype(np.int64)
    launch_mask = (node_oi >= 0) & (node_oi < O)
    total = float(problem.option_price[node_oi[launch_mask]].sum())
    oi_l = node_oi.tolist()
    starts_l, ends_l = starts.tolist(), ends.tolist()
    options_l = problem.options

    compat_bits = np.packbits(problem.class_compat, axis=1)
    ucls_l = ucls.tolist()
    cs_l, ce_l = cls_starts.tolist(), cls_ends.tolist()
    N = len(oi_l)
    jcb_list: List = [None] * N
    for i in range(N):
        if not (0 <= oi_l[i] < O):
            continue
        cls = ucls_l[cs_l[i]:ce_l[i]]
        jcb_list[i] = (compat_bits[cls[0]] if len(cls) == 1 else
                       np.bitwise_and.reduce(compat_bits[cls], axis=0))
    resolved = resolve_alternatives(problem, oi_l, jcb_list, node_used,
                                    max_alternatives)

    nodes = []
    for i in range(N):
        hit = resolved[i]
        if hit is None:
            continue
        nodes.append(NodeDecision(
            option=options_l[oi_l[i]],
            pod_indices=pod_sorted[starts_l[i]:ends_l[i]],
            used=hit[1],
            alternatives=hit[0],
        ))
    return PackingResult(nodes=nodes, unschedulable=unschedulable,
                         existing_assignments=existing_assignments,
                         total_price=total)


def assemble_slab_sharded(problem, pods_sorted, cls_sorted, node_slots,
                          run, unsched_pods, slot_option, O: int, K: int):
    """Sharded slab → (PackingResult, existing_used_add), bit-identical
    to `parallel/sharded._assemble_plan` over the concatenated shard
    rows.  The inputs are already globally slot-sorted: per-shard stable
    sorts concatenated shard-major equal one global stable sort because
    shard s's slot ids live in [s*K, (s+1)*K).

    Parity notes:
    - existing dict: node-major insertion in global slot order — one
      `np.repeat` of the node mask over run lengths reproduces it.
    - per-existing-node usage adds keep the legacy float32 `.sum(axis=0)`
      expression verbatim (a per-EXISTING-node loop, bounded by the
      cluster's node count, never pods).
    - total price: legacy accumulates `total += float(price[oi])`
      sequentially in float64; `np.cumsum` over float64 is the same left
      fold, so the last element is bit-equal.
    """
    from .classpack import resolve_alternatives
    from .ffd import NodeDecision, PackingResult

    unschedulable = unsched_pods.tolist()
    run = np.asarray(run, np.int64)
    node_slots = np.asarray(node_slots, np.int64)
    ends = np.cumsum(run)
    starts = ends - run
    node_shard = node_slots // K
    node_local = node_slots % K
    node_col = slot_option[node_shard, node_local].astype(np.int64)

    existing_assignments: Dict[int, int] = {}
    existing_used_add: Dict[int, np.ndarray] = {}
    reqs_f = problem.class_requests
    ex_mask = node_col >= O
    if ex_mask.any():
        row_ex = np.repeat(ex_mask, run)
        eid_rows = np.repeat(node_col - O, run)
        existing_assignments = dict(zip(pods_sorted[row_ex].tolist(),
                                        eid_rows[row_ex].tolist()))
        ex_idx = np.nonzero(ex_mask)[0]
        s_l, e_l = starts[ex_idx].tolist(), ends[ex_idx].tolist()
        eid_l = (node_col[ex_idx] - O).tolist()
        for j in range(len(eid_l)):
            add = reqs_f[cls_sorted[s_l[j]:e_l[j]]].sum(axis=0)
            existing_used_add[eid_l[j]] = \
                existing_used_add.get(eid_l[j], 0.0) + add

    new_idx = np.nonzero(~ex_mask)[0]
    oi_arr = node_col[new_idx]
    reqs = problem.class_requests.astype(np.int64)
    if len(starts):
        used_all = np.add.reduceat(reqs[cls_sorted], starts, axis=0)
        used_mat = used_all[new_idx]
    else:
        used_mat = np.zeros((0, reqs.shape[1]), np.int64)

    # per-node class sets from one global unique over (node, class) pairs
    # — feeds resolve_alternatives' content-digest memo (cls_keys), so the
    # joint-compat AND only runs for memo misses
    Cn = problem.num_classes
    node_of_row = np.repeat(np.arange(len(node_slots), dtype=np.int64), run)
    upq = (np.unique(node_of_row * (Cn + 1) + cls_sorted)
           if len(cls_sorted) else np.zeros(0, np.int64))
    unode, ucls = upq // (Cn + 1), upq % (Cn + 1)
    cs = np.searchsorted(unode, new_idx, side="left").tolist()
    ce = np.searchsorted(unode, new_idx, side="right").tolist()
    ucls_l = ucls.tolist()
    M = len(new_idx)
    cls_keys = [tuple(ucls_l[cs[j]:ce[j]]) for j in range(M)]

    oi_l = oi_arr.tolist()
    resolved = resolve_alternatives(problem, oi_l, None, used_mat,
                                    cls_keys=cls_keys)

    price_new = problem.option_price[oi_arr]
    total = (float(np.cumsum(price_new.astype(np.float64))[-1])
             if len(oi_arr) else 0.0)
    pods_l = pods_sorted.tolist()
    s_l, e_l = starts[new_idx].tolist(), ends[new_idx].tolist()
    nodes = []
    for j in range(M):
        alts, used_rl = resolved[j]
        nodes.append(NodeDecision(
            option=problem.options[oi_l[j]],
            pod_indices=pods_l[s_l[j]:e_l[j]],
            used=used_rl, alternatives=alts))
    return PackingResult(nodes=nodes, unschedulable=unschedulable,
                         existing_assignments=existing_assignments,
                         total_price=total), existing_used_add


def merge_residual_used(existing_used: Optional[np.ndarray],
                        used_add: Dict[int, np.ndarray],
                        E: int, R: int) -> np.ndarray:
    """True leftovers for the residual reconcile: charge the mesh pass's
    existing-node fills against each node's free space.  The per-eid loop
    is the deliberate residual-reconcile exception (bounded by cluster
    node count, grandfathered in tools/graftlint-baseline.json)."""
    used2 = (existing_used.astype(np.float64).copy()
             if existing_used is not None
             else np.zeros((E, R), np.float64))
    for eid in sorted(used_add):
        used2[eid] += used_add[eid]
    return used2
