"""Device-resident batched LP solver: restarted PDHG (PDLP-style).

The refinery's guide quality rests on a column-generation LP that
historically round-tripped to scipy/HiGHS on the host: a cold mix-cache
miss answered the tick greedy and waited a full background refine before
the guide landed (the stale-guide window).  This module closes that
window with a pure-JAX primal-dual hybrid gradient solver in the PDLP
mold — dense padded operands bucketed like the classpack kernel, one
jit'd `lax.while_loop` for the iterate loop, and a **batch axis** so the
restricted masters of many nodepools (or the per-candidate pricing LPs
ggbound.py used to solve serially) amortize one dispatch.

Problem form (everything the guide needs fits it):

    min  c·x    s.t.  A x = b,   G x ≤ h,   0 ≤ x ≤ u       (u may be +inf)

with the saddle-point iteration over L(x, y, λ) = c·x + y·(Ax−b) + λ·(Gx−h):

    x⁺ = clip(x − τ(c + Aᵀy + Gᵀλ), 0, u)        τ = η/ω
    y⁺ = y + σ(A(2x⁺−x) − b)                      σ = η·ω
    λ⁺ = max(0, λ + σ(G(2x⁺−x) − h))

η comes from a power-iteration bound on ‖[A;G]‖₂ after Ruiz row/column
equilibration; the primal weight ω rebalances on restarts from the
observed ‖Δ(y,λ)‖/‖Δx‖ ratio, exactly the PDLP recipe.  Every
`check_every` iterations the loop scores BOTH the current iterate and
the running epoch average against the unscaled KKT residuals (primal
infeasibility, dual infeasibility, duality gap — all relative), adopts
the better candidate, restarts the average on sufficient decay, and
freezes instances that converged so a batch reproduces each member's
solo trajectory.

Sign convention vs scipy: scipy's `res.eqlin.marginals` is ∂z/∂b = −y
and `res.ineqlin.marginals` is −λ, so `scipy_duals()` flips signs and
the existing dual-sign certificate in lpguide.py validates PDHG duals
verbatim.  This solver is deliberately approximate (first-order, f32):
callers that need a *bound* must repair duals into a certificate
(lpbound.dual_feasible_bound style) rather than trust the primal value;
`certified_upper_bound()` below does exactly that for the pricing LPs.

Padding is EXACT, not approximate: a padded variable has a zero column,
zero cost and u=0 (the projection pins it to 0); a padded row has zero
coefficients and zero rhs (its multiplier never moves).  The warm-start
cache keyed by caller digests is a stateful cache, so it has a
state/snapshot.py section and chaos × restart coverage like every other
one (ROADMAP hygiene).

Row equilibration happens HOST-SIDE in f64 before the f32 cast: each
eq/ineq row and its rhs are divided by the row's ∞-norm, and the
returned multipliers are divided by the same factor so callers see
duals in their original row units.  This is not an optimization knob —
the refinery masters mix millicore- and byte-scale capacity rows, and a
1e6-magnitude coefficient times a ~1e2 primal value carries ~1e1 of f32
round-off per dot product, which swamps the relative KKT measurement
entirely (the iterate converges but the residual floor sits near 1).
Normalized rows keep every product near the iterate's own magnitude, so
the f32 residuals measure the LP instead of the unit system.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import metrics, tracing
from .tensorize import pad_to

# Dim buckets for LP operands.  Masters are small (tens to low thousands
# of columns) next to the classpack pod axis, so the ladder starts low;
# past the last bucket pad_to falls back to the next power of two.
LP_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)

DEFAULT_EPS = 1e-4        # relative KKT tolerance (f32 solver)
DEFAULT_ITERS_CAP = 20000
DEFAULT_CHECK_EVERY = 32
_RESTART_DECAY = 0.36     # sufficient-decay restart threshold (PDLP β)
_RESTART_LEN = 512        # artificial restart: epoch length cap (iters)

STATUS_CONVERGED = "converged"
STATUS_CAP = "cap"

_WARM_MAX = 64
_WARM_LOCK = threading.Lock()
_WARM_CACHE: "OrderedDict[str, Dict]" = OrderedDict()


@dataclass
class LPSolution:
    """One instance's unpadded solve result (numpy, natural dims)."""
    x: np.ndarray           # primal (n,)
    y: np.ndarray           # eq multipliers, L-convention (me,)
    lam: np.ndarray         # ineq multipliers ≥ 0, L-convention (mi,)
    obj: float              # c·x
    status: str             # STATUS_CONVERGED | STATUS_CAP
    iterations: int
    restarts: int
    primal_res: float       # relative residuals at exit
    dual_res: float
    gap: float

    @property
    def converged(self) -> bool:
        return self.status == STATUS_CONVERGED

    def scipy_duals(self) -> Tuple[np.ndarray, np.ndarray]:
        """(eqlin.marginals, ineqlin.marginals) in scipy's sign
        convention: ∂z/∂b = −y, ∂z/∂h = −λ ≤ 0.  Feeds the lpguide
        dual-sign certificate unchanged."""
        return -self.y, -self.lam


# ---------------------------------------------------------------------------
# the jit'd kernel
# ---------------------------------------------------------------------------

def _kkt(A, b, G, h, c, u, u_fin, u_free, rhs_nrm, c_nrm, dc, de, di,
         x, y, lam):
    """Relative KKT score of a SCALED iterate, measured in the original
    (unscaled) space: primal/dual infeasibility and duality gap."""
    xo = dc * x
    yo = de * y
    lo = di * lam
    r_eq = jnp.einsum("bmn,bn->bm", A, xo) - b
    r_ub = jnp.maximum(jnp.einsum("bmn,bn->bm", G, xo) - h, 0.0)
    pres = jnp.maximum(jnp.max(jnp.abs(r_eq), axis=1),
                       jnp.max(r_ub, axis=1)) / (1.0 + rhs_nrm)
    rc = c + jnp.einsum("bmn,bm->bn", A, yo) + \
        jnp.einsum("bmn,bm->bn", G, lo)
    dres = jnp.max(jnp.maximum(-rc, 0.0) * u_free, axis=1) / (1.0 + c_nrm)
    pobj = jnp.sum(c * xo, axis=1)
    dobj = -jnp.sum(b * yo, axis=1) - jnp.sum(h * lo, axis=1) + \
        jnp.sum(jnp.minimum(rc, 0.0) * u_fin, axis=1)
    gap = jnp.abs(pobj - dobj) / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))
    score = jnp.maximum(jnp.maximum(pres, dres), gap)
    return score, pres, dres, gap


@partial(jax.jit, static_argnames=("iters_cap", "check_every"),
         donate_argnames=("init_x", "init_y", "init_lam"))
def _pdhg_kernel(A, b, G, h, c, u, init_x, init_y, init_lam, eps,
                 iters_cap: int, check_every: int):
    """Batched restarted PDHG.  Shapes: A (B,me,n), G (B,mi,n), b (B,me),
    h (B,mi), c/u/init_x (B,n), init_y (B,me), init_lam (B,mi), eps ().

    Converged instances freeze behind `done` masks — their iterates stop
    moving and their exit stats stop updating — so a vmapped batch
    reproduces each member's solo trajectory and the loop only runs
    until the stragglers finish or the cap lands."""
    f32 = jnp.float32
    A = A.astype(f32)
    G = G.astype(f32)
    b = b.astype(f32)
    h = h.astype(f32)
    c = c.astype(f32)
    u = u.astype(f32)
    B, me, n = A.shape
    mi = G.shape[1]
    tiny = f32(1e-12)

    u_free = jnp.isinf(u).astype(f32)          # vars with no upper bound
    u_fin = jnp.where(jnp.isinf(u), 0.0, u)    # finite bounds (0 for free)
    rhs_nrm = jnp.maximum(jnp.max(jnp.abs(b), axis=1, initial=0.0),
                          jnp.max(jnp.abs(h), axis=1, initial=0.0))
    c_nrm = jnp.max(jnp.abs(c), axis=1, initial=0.0)

    # --- Ruiz equilibration: D_r [A;G] D_c, scales kept for unscaling.
    def ruiz_step(_, carry):
        As, Gs, de, di, dc = carry
        re = jnp.max(jnp.abs(As), axis=2)
        ri = jnp.max(jnp.abs(Gs), axis=2)
        se = jnp.where(re > tiny, 1.0 / jnp.sqrt(jnp.maximum(re, tiny)), 1.0)
        si = jnp.where(ri > tiny, 1.0 / jnp.sqrt(jnp.maximum(ri, tiny)), 1.0)
        As = As * se[:, :, None]
        Gs = Gs * si[:, :, None]
        col = jnp.maximum(jnp.max(jnp.abs(As), axis=1, initial=0.0),
                          jnp.max(jnp.abs(Gs), axis=1, initial=0.0))
        sc = jnp.where(col > tiny, 1.0 / jnp.sqrt(jnp.maximum(col, tiny)),
                       1.0)
        As = As * sc[:, None, :]
        Gs = Gs * sc[:, None, :]
        return As, Gs, de * se, di * si, dc * sc

    As, Gs, de, di, dc = jax.lax.fori_loop(
        0, 8, ruiz_step,
        (A, G, jnp.ones((B, me), f32), jnp.ones((B, mi), f32),
         jnp.ones((B, n), f32)))
    # scaled data: row r of [A;G] was multiplied by d_r, so rhs scales the
    # same way; column j by d_c, so cost scales by d_c and bounds by 1/d_c.
    bs = b * de
    hs = h * di
    cs = c * dc
    us = u / jnp.maximum(dc, tiny)             # inf stays inf, 0 stays 0

    # --- ‖K‖₂ by power iteration on the scaled stacked operator.
    v0 = 1.0 + 0.5 * jnp.cos(jnp.arange(n, dtype=f32) * f32(1.618))
    v0 = jnp.broadcast_to(v0, (B, n))
    v0 = v0 / jnp.sqrt(jnp.sum(v0 * v0, axis=1, keepdims=True))

    def power_step(_, carry):
        v, _sig = carry
        we = jnp.einsum("bmn,bn->bm", As, v)
        wi = jnp.einsum("bmn,bn->bm", Gs, v)
        vn = jnp.einsum("bmn,bm->bn", As, we) + \
            jnp.einsum("bmn,bm->bn", Gs, wi)
        nrm = jnp.sqrt(jnp.sum(vn * vn, axis=1))
        sig = jnp.sqrt(jnp.maximum(nrm, tiny))   # v unit ⇒ ‖KᵀKv‖ → σ²
        return vn / jnp.maximum(nrm, tiny)[:, None], sig

    _, sigma = jax.lax.fori_loop(0, 24, power_step,
                                 (v0, jnp.ones((B,), f32)))
    sigma = jnp.maximum(sigma, f32(1e-6))
    eta = f32(0.9) / sigma

    nc = jnp.sqrt(jnp.sum(cs * cs, axis=1))
    nrhs = jnp.sqrt(jnp.sum(bs * bs, axis=1) + jnp.sum(hs * hs, axis=1))
    omega0 = jnp.where((nc > tiny) & (nrhs > tiny),
                       jnp.clip(nc / jnp.maximum(nrhs, tiny), 1e-2, 1e2),
                       1.0)

    x0 = jnp.clip(init_x.astype(f32) / jnp.maximum(dc, tiny), 0.0, us)
    y0 = init_y.astype(f32) / jnp.maximum(de, tiny)
    l0 = jnp.maximum(init_lam.astype(f32) / jnp.maximum(di, tiny), 0.0)
    zf = jnp.zeros((B,), f32)
    zi = jnp.zeros((B,), jnp.int32)

    carry0 = dict(
        x=x0, y=y0, lam=l0,
        xs=jnp.zeros_like(x0), ys=jnp.zeros_like(y0),
        ls=jnp.zeros_like(l0), elen=zi,
        xa=x0, ya=y0, la=l0, score_anc=jnp.full((B,), jnp.inf, f32),
        omega=omega0, done=jnp.zeros((B,), bool),
        iters=zi, restarts=zi, pres=zf, dres=zf, gap=zf,
        k=jnp.int32(0))

    restart_len = max(_RESTART_LEN // check_every, 2)

    def cond(cr):
        return jnp.logical_and(cr["k"] * check_every < iters_cap,
                               jnp.any(~cr["done"]))

    def body(cr):
        live = ~cr["done"]
        livec = live[:, None].astype(f32)
        tau = (eta / cr["omega"])[:, None]
        sig = (eta * cr["omega"])[:, None]

        def step(_, st):
            x, y, lam, xs, ys, ls = st
            kty = jnp.einsum("bmn,bm->bn", As, y) + \
                jnp.einsum("bmn,bm->bn", Gs, lam)
            xn = jnp.clip(x - tau * (cs + kty), 0.0, us)
            xb = 2.0 * xn - x
            yn = y + sig * (jnp.einsum("bmn,bn->bm", As, xb) - bs)
            ln = jnp.maximum(
                lam + sig * (jnp.einsum("bmn,bn->bm", Gs, xb) - hs), 0.0)
            xn = jnp.where(live[:, None], xn, x)
            yn = jnp.where(live[:, None], yn, y)
            ln = jnp.where(live[:, None], ln, lam)
            return xn, yn, ln, xs + livec * xn, ys + livec * yn, \
                ls + livec * ln

        x, y, lam, xs, ys, ls = jax.lax.fori_loop(
            0, check_every, step,
            (cr["x"], cr["y"], cr["lam"], cr["xs"], cr["ys"], cr["ls"]))
        elen = cr["elen"] + jnp.int32(check_every) * live

        # score current iterate and epoch average, adopt the better
        div = jnp.maximum(elen, 1).astype(f32)[:, None]
        score_c, pc_, dc_, gc_ = _kkt(A, b, G, h, c, u, u_fin, u_free,
                                      rhs_nrm, c_nrm, dc, de, di, x, y, lam)
        score_a, pa_, da_, ga_ = _kkt(A, b, G, h, c, u, u_fin, u_free,
                                      rhs_nrm, c_nrm, dc, de, di,
                                      xs / div, ys / div, ls / div)
        use_avg = score_a < score_c
        ua = use_avg[:, None]
        bx = jnp.where(ua, xs / div, x)
        by = jnp.where(ua, ys / div, y)
        bl = jnp.where(ua, ls / div, lam)
        bscore = jnp.minimum(score_a, score_c)
        bpres = jnp.where(use_avg, pa_, pc_)
        bdres = jnp.where(use_avg, da_, dc_)
        bgap = jnp.where(use_avg, ga_, gc_)

        newly = live & (bscore <= eps)
        suff = bscore <= f32(_RESTART_DECAY) * cr["score_anc"]
        long_epoch = elen >= jnp.int32(restart_len * check_every)
        adopt = live & (suff | long_epoch | newly)

        # PDLP primal-weight rebalance from the restart displacement
        dxn = jnp.sqrt(jnp.sum((bx - cr["xa"]) ** 2, axis=1))
        dyn = jnp.sqrt(jnp.sum((by - cr["ya"]) ** 2, axis=1) +
                       jnp.sum((bl - cr["la"]) ** 2, axis=1))
        ok = (dxn > tiny) & (dyn > tiny)
        om_new = jnp.clip(
            jnp.exp(0.5 * jnp.log(jnp.maximum(dyn, tiny) /
                                  jnp.maximum(dxn, tiny)) +
                    0.5 * jnp.log(cr["omega"])), 1e-3, 1e3)
        omega = jnp.where(adopt & ok & ~newly, om_new, cr["omega"])

        ad = adopt[:, None]
        return dict(
            x=jnp.where(ad, bx, x), y=jnp.where(ad, by, y),
            lam=jnp.where(ad, bl, lam),
            xs=jnp.where(ad, 0.0, xs), ys=jnp.where(ad, 0.0, ys),
            ls=jnp.where(ad, 0.0, ls),
            elen=jnp.where(adopt, 0, elen),
            xa=jnp.where(ad, bx, cr["xa"]),
            ya=jnp.where(ad, by, cr["ya"]),
            la=jnp.where(ad, bl, cr["la"]),
            score_anc=jnp.where(adopt, bscore, cr["score_anc"]),
            omega=omega, done=cr["done"] | newly,
            iters=cr["iters"] + jnp.int32(check_every) * live,
            restarts=cr["restarts"] + (adopt & ~newly),
            pres=jnp.where(live, bpres, cr["pres"]),
            dres=jnp.where(live, bdres, cr["dres"]),
            gap=jnp.where(live, bgap, cr["gap"]),
            k=cr["k"] + 1)

    out = jax.lax.while_loop(cond, body, carry0)
    return (dc * out["x"], de * out["y"], di * out["lam"], out["done"],
            out["iters"], out["restarts"], out["pres"], out["dres"],
            out["gap"])


# ---------------------------------------------------------------------------
# host wrapper: pad → stack → kernel → unpad
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class LPInstance:
    """One LP in natural dims; eq/ineq blocks optional, u entries may be
    +inf (the default when `upper` is None)."""
    c: np.ndarray
    A_eq: Optional[np.ndarray] = None
    b_eq: Optional[np.ndarray] = None
    A_ub: Optional[np.ndarray] = None
    b_ub: Optional[np.ndarray] = None
    upper: Optional[np.ndarray] = None
    warm_key: Optional[str] = None

    def dims(self) -> Tuple[int, int, int]:
        n = int(np.asarray(self.c).shape[0])
        me = 0 if self.A_eq is None else int(np.asarray(self.A_eq).shape[0])
        mi = 0 if self.A_ub is None else int(np.asarray(self.A_ub).shape[0])
        return n, me, mi


def _warm_get(key: Optional[str], dims: Tuple[int, int, int]):
    if key is None:
        return None
    with _WARM_LOCK:
        ent = _WARM_CACHE.get(key)
        if ent is None or ent["dims"] != tuple(dims):
            return None
        _WARM_CACHE.move_to_end(key)
        return ent


def _warm_put(key: Optional[str], dims: Tuple[int, int, int],
              x: np.ndarray, y: np.ndarray, lam: np.ndarray) -> None:
    if key is None:
        return
    with _WARM_LOCK:
        _WARM_CACHE[key] = {"dims": tuple(dims),
                            "x": np.asarray(x, np.float32).copy(),
                            "y": np.asarray(y, np.float32).copy(),
                            "lam": np.asarray(lam, np.float32).copy()}
        _WARM_CACHE.move_to_end(key)
        while len(_WARM_CACHE) > _WARM_MAX:
            _WARM_CACHE.popitem(last=False)


def warm_cache_len() -> int:
    with _WARM_LOCK:
        return len(_WARM_CACHE)


def snapshot_caches() -> dict:
    """Plain-data export of the warm-start cache for the WarmRestart
    snapshot (state/snapshot.py "lpsolve" section): keys are caller
    digests, values natural-dim float32 arrays — all picklable and
    clock-domain free (a warm start is only ever a hint)."""
    with _WARM_LOCK:
        return {"warm": {k: dict(v) for k, v in _WARM_CACHE.items()}}


def restore_caches(data: dict) -> None:
    with _WARM_LOCK:
        _WARM_CACHE.clear()
        for k, v in data.get("warm", {}).items():
            _WARM_CACHE[k] = {"dims": tuple(v["dims"]),
                              "x": np.asarray(v["x"], np.float32),
                              "y": np.asarray(v["y"], np.float32),
                              "lam": np.asarray(v["lam"], np.float32)}
        while len(_WARM_CACHE) > _WARM_MAX:
            _WARM_CACHE.popitem(last=False)


def reset_caches() -> None:
    with _WARM_LOCK:
        _WARM_CACHE.clear()


def solve_lp_batch(instances: Sequence[LPInstance],
                   eps: float = DEFAULT_EPS,
                   iters_cap: int = DEFAULT_ITERS_CAP,
                   check_every: int = DEFAULT_CHECK_EVERY,
                   buckets: Sequence[int] = LP_BUCKETS
                   ) -> List[LPSolution]:
    """Solve a batch of LPs in one padded device dispatch.

    All instances pad to one bucketed (n, me, mi) envelope — padding is
    exact (see module docstring), so heterogeneous natural dims batch
    fine.  Returns one LPSolution per instance, natural dims."""
    if not instances:
        return []
    B = len(instances)
    dims = [inst.dims() for inst in instances]
    nb = pad_to(max(d[0] for d in dims), buckets)
    meb = pad_to(max(max(d[1] for d in dims), 1), buckets)
    mib = pad_to(max(max(d[2] for d in dims), 1), buckets)

    A = np.zeros((B, meb, nb), np.float32)
    G = np.zeros((B, mib, nb), np.float32)
    b = np.zeros((B, meb), np.float32)
    h = np.zeros((B, mib), np.float32)
    c = np.zeros((B, nb), np.float32)
    u = np.zeros((B, nb), np.float32)          # padded vars pinned to 0
    ix = np.zeros((B, nb), np.float32)
    iy = np.zeros((B, meb), np.float32)
    il = np.zeros((B, mib), np.float32)

    # per-row ∞-norm scales (f64), kept to unscale duals on the way out
    se = np.ones((B, meb), np.float64)
    si = np.ones((B, mib), np.float64)

    for i, inst in enumerate(instances):
        n, me, mi = dims[i]
        c[i, :n] = np.asarray(inst.c, np.float32)
        u[i, :n] = np.inf if inst.upper is None else \
            np.asarray(inst.upper, np.float32)
        if me:
            Ae = np.asarray(inst.A_eq, np.float64)
            s = np.abs(Ae).max(axis=1)
            s = np.where(s > 0.0, s, 1.0)
            se[i, :me] = s
            A[i, :me, :n] = (Ae / s[:, None]).astype(np.float32)
            b[i, :me] = (np.asarray(inst.b_eq, np.float64) /
                         s).astype(np.float32)
        if mi:
            Gi = np.asarray(inst.A_ub, np.float64)
            s = np.abs(Gi).max(axis=1)
            s = np.where(s > 0.0, s, 1.0)
            si[i, :mi] = s
            G[i, :mi, :n] = (Gi / s[:, None]).astype(np.float32)
            h[i, :mi] = (np.asarray(inst.b_ub, np.float64) /
                         s).astype(np.float32)
        warm = _warm_get(inst.warm_key, dims[i])
        if warm is not None:
            # cached duals are in original row units; the kernel works in
            # row-normalized units (y' = s·y)
            ix[i, :n] = warm["x"]
            iy[i, :me] = warm["y"] * se[i, :me]
            il[i, :mi] = warm["lam"] * si[i, :mi]

    kw = dict(batch=B, shape=f"{nb}x{meb}x{mib}")
    sp = tracing.span("lp.batch", **kw) if B > 1 else \
        tracing.span("lp.solve", **kw)
    with sp:
        out = _pdhg_kernel(A, b, G, h, c, u, ix, iy, il,
                           np.float32(eps), iters_cap=int(iters_cap),
                           check_every=int(check_every))
        xs, ys, ls, done, iters, restarts, pres, dres, gap = \
            [np.asarray(o) for o in out]

    metrics.lp_batch_size().observe(B)
    sols: List[LPSolution] = []
    for i, inst in enumerate(instances):
        n, me, mi = dims[i]
        x = xs[i, :n].astype(np.float64)
        y = ys[i, :me].astype(np.float64) / se[i, :me]
        lam = ls[i, :mi].astype(np.float64) / si[i, :mi]
        ok = bool(done[i])
        status = STATUS_CONVERGED if ok else STATUS_CAP
        sol = LPSolution(
            x=x, y=y, lam=lam,
            obj=float(np.asarray(inst.c, np.float64) @ x),
            status=status, iterations=int(iters[i]),
            restarts=int(restarts[i]), primal_res=float(pres[i]),
            dual_res=float(dres[i]), gap=float(gap[i]))
        metrics.lp_solves().inc({"outcome": status})
        metrics.lp_iterations().observe(sol.iterations)
        metrics.lp_restarts().observe(sol.restarts)
        if ok:
            _warm_put(inst.warm_key, dims[i], x, y, lam)
        sols.append(sol)
    metrics.lp_residuals().set(float(pres.max()), {"kind": "primal"})
    metrics.lp_residuals().set(float(dres.max()), {"kind": "dual"})
    metrics.lp_residuals().set(float(gap.max()), {"kind": "gap"})
    return sols


def solve_lp(c, A_eq=None, b_eq=None, A_ub=None, b_ub=None, upper=None,
             warm_key: Optional[str] = None, eps: float = DEFAULT_EPS,
             iters_cap: int = DEFAULT_ITERS_CAP,
             check_every: int = DEFAULT_CHECK_EVERY,
             buckets: Sequence[int] = LP_BUCKETS) -> LPSolution:
    """Single-LP convenience wrapper over `solve_lp_batch` (B=1 batch, so
    single and batched solves share one kernel and one trajectory)."""
    return solve_lp_batch(
        [LPInstance(c=np.asarray(c, np.float32), A_eq=A_eq, b_eq=b_eq,
                    A_ub=A_ub, b_ub=b_ub, upper=upper, warm_key=warm_key)],
        eps=eps, iters_cap=iters_cap, check_every=check_every,
        buckets=buckets)[0]


def certified_upper_bound(d: np.ndarray, R: np.ndarray, a: np.ndarray,
                          ub: np.ndarray, lam: np.ndarray) -> float:
    """Certified upper bound on  max d·z  s.t.  R z ≤ a, 0 ≤ z ≤ ub,
    from ANY λ ≥ 0 (weak duality):  a·λ + Σ_j max(0, d_j − (Rᵀλ)_j)·ub_j.

    This is how ggbound consumes the batched solver: the PDHG *primal*
    value of a pricing LP may under-estimate the max (unsafe for Farley
    screening), but the dual-repaired bound is valid regardless of
    convergence — at worst it is loose and the screen is conservative."""
    lam = np.maximum(np.asarray(lam, np.float64), 0.0)
    slack = np.maximum(np.asarray(d, np.float64) -
                       np.asarray(R, np.float64).T @ lam, 0.0)
    return float(np.asarray(a, np.float64) @ lam +
                 slack @ np.asarray(ub, np.float64))
