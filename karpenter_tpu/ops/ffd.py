"""First-fit-decreasing packing as a jit-compiled lax.scan.

TPU re-expression of the reference scheduler's greedy loop
(/root/reference/designs/bin-packing.md:16-43: sort pods by resources
descending, place each on an existing node else open the best new node).
Instead of Go's per-pod × per-node × per-type nested loops, each scan step
evaluates feasibility against *all* open node slots and *all* launch options
as dense vector ops (VPU-friendly K×R / O×R comparisons), with
data-independent control flow (`jnp.where` masks, no branches) so XLA
compiles one fixed program.

The same kernel doubles as the consolidation simulator: pre-opened slots
(`init_option`/`init_used`) represent existing cluster nodes, so "would these
pods fit on the remaining nodes [+ one cheaper node]" is just a call with
different initial state (SURVEY.md §7.6).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api.resources import ResourceList
from ..utils import tracing
from .tensorize import LaunchOption, Problem, pad_to

NO_ASSIGNMENT = -1

# Cap on new-node scores (price × ceil(tail/m)): large-but-finite prices
# times a big tail overflow float32 to +inf, which argmin-over-all-inf
# resolves to index 0 — possibly an incompatible option — while `can_new`
# still says yes.  Clamping keeps overflowed candidates comparable (ties
# break to the lower, cheaper-sorted index) and MUST match the native
# kernel's clamp (csrc/ffd.cc) bit-for-bit for backend parity.
SCORE_CAP = 3.38e38  # just under float32 max (3.4028e38)


@partial(jax.jit, static_argnames=("max_nodes",))
def ffd_pack_kernel(requests: jax.Array,    # P×R, FFD-sorted
                    compat: jax.Array,      # P×O bool
                    valid: jax.Array,       # P bool (padding mask)
                    class_id: jax.Array,    # P int32 (rows of a class contiguous)
                    node_cap: jax.Array,    # P int32 max class pods per node
                    rem_in_class: jax.Array,  # P int32 class rows left (incl.)
                    alloc: jax.Array,       # O×R full-capacity allocatable
                    price: jax.Array,       # O
                    rank: jax.Array,        # O int32 pool-weight rank
                    init_option: jax.Array, # K int32, -1 == closed slot
                    init_used: jax.Array,   # K×R resources already used
                    max_nodes: int):
    """Returns (assignment P int32 slot-or--1, slot_option K, slot_used K×R,
    n_open).

    `node_cap` lowers hostname-granular topology constraints (hostname
    anti-affinity -> 1, hostname spread -> max_skew; ops/constraints.py):
    a K-vector counts pods of the *current* class per slot and resets when
    the scan crosses a class boundary — exact because FFD order keeps class
    rows contiguous."""
    K = max_nodes
    _IBIG = jnp.int32(2**30)

    def step(carry, x):
        slot_option, slot_used, slot_cls, prev_cid, n_open = carry
        req, comp, is_valid, cid, cap, tail = x
        slot_cls = jnp.where(cid == prev_cid, slot_cls, 0)
        opt = jnp.maximum(slot_option, 0)
        open_mask = slot_option >= 0
        slot_alloc = alloc[opt]                                   # K×R gather
        fits = (open_mask & comp[opt] & (slot_cls < cap)
                & jnp.all(slot_used + req <= slot_alloc, axis=-1))
        exist_k = jnp.argmax(fits)            # first-fit: lowest feasible slot
        any_fit = jnp.any(fits)
        # new node: highest-weight pool first (NodePool.spec.weight
        # precedence), then the option minimizing price × ceil(tail / m) —
        # the amortized cost of absorbing the class's unplaced rows, the
        # same tail-aware score the class-granular kernel uses.  A plain
        # per-pod cheapest rule degenerates on catalogs with cheap tiny
        # types (one pod per node at ~2× the blended optimum, review r5).
        # Ties break toward the lower index, which is pre-sorted by pool
        # rank then price (instance.go:395-412).
        new_ok = comp & jnp.all(req <= alloc, axis=-1) & jnp.isfinite(price)
        best_rank = jnp.min(jnp.where(new_ok, rank, _IBIG))
        new_ok_r = new_ok & (rank == best_rank)
        reqpos = req > 0
        safe_req = jnp.where(reqpos, req, 1.0)
        m = jnp.min(jnp.where(reqpos[None, :],
                              jnp.floor(alloc / safe_req[None, :]),
                              jnp.float32(2**30)), axis=-1)
        m = jnp.clip(m, 1.0, jnp.maximum(cap.astype(m.dtype), 1.0))
        score = jnp.minimum(price * jnp.ceil(
            jnp.maximum(tail, 1).astype(price.dtype) / m),
            jnp.asarray(SCORE_CAP, price.dtype))
        new_opt = jnp.argmin(jnp.where(new_ok_r, score, jnp.inf))
        can_new = jnp.any(new_ok) & (n_open < K)
        sched_exist = is_valid & any_fit
        sched_new = is_valid & ~any_fit & can_new
        placed = sched_exist | sched_new
        k = jnp.where(sched_exist, exist_k, n_open)
        k_safe = jnp.clip(k, 0, K - 1)
        slot_used = slot_used.at[k_safe].add(jnp.where(placed, req, 0.0))
        slot_cls = slot_cls.at[k_safe].add(placed.astype(jnp.int32))
        slot_option = slot_option.at[k_safe].set(
            jnp.where(sched_new, new_opt, slot_option[k_safe]))
        n_open = n_open + sched_new.astype(jnp.int32)
        carry = (slot_option, slot_used, slot_cls, cid, n_open)
        return carry, jnp.where(placed, k_safe, NO_ASSIGNMENT)

    n_open0 = jnp.sum(init_option >= 0).astype(jnp.int32)
    (slot_option, slot_used, _, _, n_open), assignment = jax.lax.scan(
        step, (init_option, init_used, jnp.zeros(K, jnp.int32),
               jnp.int32(-1), n_open0),
        (requests, compat, valid, class_id, node_cap, rem_in_class))
    return assignment, slot_option, slot_used, n_open


@dataclass
class NodeDecision:
    """One node to launch: the chosen option plus the pods packed onto it.
    The flexible `alternatives` list (instance types the packed pods are
    jointly compatible with, price-ordered) is what feeds CreateFleet-style
    flexible launches (/root/reference/pkg/providers/instance/instance.go:88-105)."""
    option: LaunchOption
    pod_indices: List[int]
    used: "ResourceList" = None   # canonical units (bytes/millicores)
    alternatives: List[LaunchOption] = field(default_factory=list)


@dataclass
class PackingResult:
    nodes: List[NodeDecision]
    unschedulable: List[int]            # original pod indices
    existing_assignments: Dict[int, int]  # pod index -> pre-opened slot id
    total_price: float

    @property
    def scheduled_count(self) -> int:
        return (sum(len(n.pod_indices) for n in self.nodes)
                + len(self.existing_assignments))

    def strip_pods(self, pod_indices, pods=None) -> None:
        """Remove pods from the plan in place: they leave their node
        decisions / existing slots and land in `unschedulable`.  Decisions
        left empty are dropped (their node is never launched) and
        `total_price` re-sums over the survivors.  This is how gang
        enforcement (ops/gang.py) takes a rejected gang out of the plan
        wholesale — no partial bind ever reaches claim_requests.  `pods`
        (the Problem's pod list) lets per-decision `used` shrink with the
        departures so downstream claim sizing stays honest."""
        drop = {int(i) for i in pod_indices}
        if not drop:
            return
        kept = []
        for dec in self.nodes:
            removed = [i for i in dec.pod_indices if int(i) in drop]
            if removed:
                dec.pod_indices = [i for i in dec.pod_indices
                                   if int(i) not in drop]
                if dec.used is not None and pods is not None:
                    for i in removed:
                        dec.used = dec.used - pods[i].requests
                    dec.used = dec.used.clamp_nonnegative()
            if dec.pod_indices:
                kept.append(dec)
        self.nodes = kept
        for i in [i for i in self.existing_assignments if int(i) in drop]:
            del self.existing_assignments[i]
        self.unschedulable = sorted(
            {int(i) for i in self.unschedulable} | drop)
        self.total_price = float(sum(d.option.price for d in self.nodes))


@dataclass
class SweepResult:
    """Aggregate verdicts for B masked sub-problems solved in one (or a few
    bucket-padded) device calls — the batched consolidation sweep's output.
    Row b answers the b-th probe exactly as a decode=False PackingResult
    would: could the probe's pods land on the unmasked columns, how many
    NEW nodes would launch, and at what launch cost."""
    total_price: np.ndarray     # B float32 — price of newly-launched nodes
    new_nodes: np.ndarray       # B int32  — nodes launched (existing excluded)
    unschedulable: np.ndarray   # B int32  — pods left unplaced
    device_calls: int = 1       # padded kernel invocations this sweep took

    def feasible_delete(self, b: int) -> bool:
        """The delete-probe contract: every pod lands on survivors alone."""
        return (int(self.unschedulable[b]) == 0
                and int(self.new_nodes[b]) == 0)


# below this many rows the native C++ packer beats a device kernel launch
NATIVE_CUTOVER_ROWS = 256


def ffd_pack_numpy(requests: np.ndarray,     # P×R float32, FFD-sorted
                   compat: np.ndarray,       # P×(O+E) bool
                   class_ids: np.ndarray,    # P int32
                   row_caps: np.ndarray,     # P int32
                   rem: np.ndarray,          # P int32
                   alloc: np.ndarray,        # (O+E)×R float32
                   price: np.ndarray,        # O+E float32, existing = inf
                   rank: np.ndarray,         # O+E int32
                   init_option: np.ndarray,  # K int32
                   init_used: np.ndarray,    # K×R float32
                   K: int):
    """Pure-NumPy mirror of `ffd_pack_kernel` on UNPADDED arrays — the
    degradation ladder's guaranteed-terminating greedy bottom rung
    (ops/health.py): no device, no compile, no C extension, one bounded
    Python loop.  Semantics (first-fit slot choice, tail-aware new-node
    score, float32 arithmetic and the SCORE_CAP clamp) track the scan
    step exactly so plans stay backend-comparable."""
    P, _ = requests.shape
    IBIG = np.int32(2**30)
    f32 = np.float32
    slot_option = init_option.astype(np.int32).copy()
    slot_used = init_used.astype(f32).copy()
    slot_cls = np.zeros(K, np.int32)
    prev_cid = None
    n_open = int((slot_option >= 0).sum())
    assignment = np.full(P, NO_ASSIGNMENT, np.int32)
    for i in range(P):
        req = requests[i]
        comp = compat[i]
        cid = int(class_ids[i])
        cap = int(row_caps[i])
        if cid != prev_cid:
            slot_cls[:] = 0
        prev_cid = cid
        opt = np.maximum(slot_option, 0)
        fits = ((slot_option >= 0) & comp[opt] & (slot_cls < cap)
                & np.all(slot_used + req <= alloc[opt], axis=-1))
        if fits.any():
            k = int(np.argmax(fits))
        else:
            new_ok = comp & np.all(req <= alloc, axis=-1) & np.isfinite(price)
            if not new_ok.any() or n_open >= K:
                continue  # row stays NO_ASSIGNMENT
            best_rank = np.min(np.where(new_ok, rank, IBIG))
            new_ok_r = new_ok & (rank == best_rank)
            reqpos = req > 0
            safe_req = np.where(reqpos, req, f32(1.0))
            m = np.min(np.where(reqpos[None, :],
                                np.floor(alloc / safe_req[None, :]),
                                f32(2**30)), axis=-1)
            m = np.clip(m, f32(1.0), f32(max(cap, 1)))
            score = np.minimum(
                price * np.ceil(f32(max(int(rem[i]), 1)) / m), f32(SCORE_CAP))
            k = n_open
            slot_option[k] = int(np.argmin(np.where(new_ok_r, score, np.inf)))
            n_open += 1
        slot_used[k] += req
        slot_cls[k] += 1
        assignment[i] = k
    return assignment, slot_option, slot_used, n_open


def rem_in_class(class_ids: np.ndarray) -> np.ndarray:
    """Per row: rows of the row's class still unplaced (itself included) —
    rows are class-contiguous, so this is count-from-the-back.  Feeds the
    tail-aware new-node score in BOTH packers (JAX scan and the native
    C++ core)."""
    P = len(class_ids)
    if P == 0:
        return np.zeros(0, np.int32)
    ends = np.nonzero(np.diff(class_ids, append=class_ids[-1] + 1))[0]
    out = np.empty(P, np.int64)
    start = 0
    for e in ends:
        out[start:e + 1] = np.arange(e + 1 - start, 0, -1)
        start = e + 1
    return out.astype(np.int32)


def solve_ffd(problem: Problem,
              max_nodes: Optional[int] = None,
              existing_alloc: Optional[np.ndarray] = None,   # E×R
              existing_used: Optional[np.ndarray] = None,    # E×R
              existing_compat: Optional[np.ndarray] = None,  # C×E bool
              max_alternatives: int = 60,
              backend: str = "auto") -> PackingResult:
    """Host wrapper: expand classes → pad → run kernel → decode decisions.

    Existing cluster nodes (for provisioning against live capacity and for
    consolidation simulation) enter as pre-opened slots with price already
    paid: their allocatable/used vectors are appended as zero-price virtual
    options.

    `backend`: "jax" (scan kernel), "native" (C++ packer — identical slot
    semantics, see karpenter_tpu/native), "numpy" (pure-host greedy mirror,
    the degradation ladder's bottom rung — always available, always
    terminates), or "auto" — native for small rows where kernel-launch
    latency dominates, accelerator otherwise.
    """
    if backend == "auto":
        total_rows = int(problem.class_counts.sum()) + \
            (0 if existing_alloc is None else len(existing_alloc))
        if total_rows <= NATIVE_CUTOVER_ROWS:
            from .. import native
            if native.available():
                backend = "native"
    if backend == "native":
        from .. import native
        tracing.annotate(backend="native", device_calls=0)
        return native.solve_ffd_native(
            problem, max_nodes=max_nodes, existing_alloc=existing_alloc,
            existing_used=existing_used, existing_compat=existing_compat,
            max_alternatives=max_alternatives)
    tracing.annotate(backend="jax", device_calls=1)
    E = 0 if existing_alloc is None else len(existing_alloc)
    ec = None
    if E:
        ec = existing_compat if existing_compat is not None else \
            np.ones((problem.num_classes, E), bool)
    requests, compat, pod_idx, class_ids = problem.expand(extra_compat=ec)
    caps = (problem.class_node_cap if problem.class_node_cap is not None
            else np.full(problem.num_classes, 2**30, np.int32))
    row_caps = caps[class_ids] if len(class_ids) else np.zeros(0, np.int32)
    P = len(requests)
    alloc = problem.option_alloc
    price = problem.option_price
    O = alloc.shape[0]
    R = alloc.shape[1]
    if E:
        # one virtual option per existing node, price 0 (sunk cost)
        alloc = np.concatenate([alloc, existing_alloc.astype(np.float32)], axis=0)
        price = np.concatenate([price, np.zeros(E, np.float32)])
    if alloc.shape[0] == 0:  # no options and no existing nodes
        return PackingResult(nodes=[], unschedulable=[int(i) for i in pod_idx],
                             existing_assignments={}, total_price=0.0)
    K = max_nodes if max_nodes is not None else 4096
    K = min(K, pad_to(P + E, (256, 1024, 4096)))
    K = max(K, E + 1)

    rank = np.zeros(alloc.shape[0], np.int32)
    rank[:O] = problem.option_rank
    new_price = price.copy()
    if E:
        new_price[O:] = np.inf  # existing nodes can't be "launched" again

    if backend == "numpy":
        tracing.annotate(backend="numpy", device_calls=0)
        init_option = np.full(K, -1, np.int32)
        init_used = np.zeros((K, R), np.float32)
        if E:
            init_option[:E] = np.arange(O, O + E, dtype=np.int32)
            init_used[:E] = existing_used.astype(np.float32) \
                if existing_used is not None else 0.0
        assignment, slot_option, slot_used, _ = ffd_pack_numpy(
            requests.astype(np.float32), compat,
            class_ids.astype(np.int32), row_caps,
            rem_in_class(class_ids), alloc.astype(np.float32),
            new_price.astype(np.float32), rank, init_option, init_used, K)
        return decode_assignment(problem, assignment, slot_option,
                                 slot_used, pod_idx, compat, E, O,
                                 max_alternatives)

    # pad both the pod axis and the option axis (columns) so catalog/ICE/
    # cluster-size changes reuse compiled programs instead of recompiling
    Ppad = pad_to(P)
    Opad = pad_to(alloc.shape[0], (512, 2048, 4096, 8192, 32768))
    req_p = np.zeros((Ppad, R), np.float32)
    req_p[:P] = requests
    comp_p = np.zeros((Ppad, Opad), bool)
    comp_p[:P, :alloc.shape[0]] = compat
    valid = np.zeros(Ppad, bool)
    valid[:P] = True
    cid_p = np.full(Ppad, -2, np.int32)   # padded rows: no real class
    cid_p[:P] = class_ids
    cap_p = np.full(Ppad, 2**30, np.int32)
    cap_p[:P] = row_caps
    rem_p = np.zeros(Ppad, np.int32)
    rem_p[:P] = rem_in_class(class_ids)
    alloc_p = np.zeros((Opad, R), np.float32)
    alloc_p[:alloc.shape[0]] = alloc
    price_p = np.full(Opad, np.inf, np.float32)
    price_p[:alloc.shape[0]] = new_price
    rank_p = np.full(Opad, 2**30, np.int32)
    rank_p[:alloc.shape[0]] = rank

    init_option = np.full(K, -1, np.int32)
    init_used = np.zeros((K, R), np.float32)
    if E:
        init_option[:E] = np.arange(O, O + E, dtype=np.int32)
        init_used[:E] = existing_used.astype(np.float32) if existing_used is not None else 0.0

    assignment, slot_option, slot_used, n_open = ffd_pack_kernel(
        jnp.asarray(req_p), jnp.asarray(comp_p), jnp.asarray(valid),
        jnp.asarray(cid_p), jnp.asarray(cap_p), jnp.asarray(rem_p),
        jnp.asarray(alloc_p), jnp.asarray(price_p), jnp.asarray(rank_p),
        jnp.asarray(init_option), jnp.asarray(init_used), K)
    assignment = np.asarray(assignment)[:P]
    slot_option = np.asarray(slot_option)
    slot_used = np.asarray(slot_used)
    return decode_assignment(problem, assignment, slot_option, slot_used,
                             pod_idx, compat, E, O, max_alternatives)


def decode_assignment(problem: Problem, assignment: np.ndarray,
                      slot_option: np.ndarray, slot_used: np.ndarray,
                      pod_idx: np.ndarray, compat: np.ndarray,
                      E: int, O: int, max_alternatives: int = 60
                      ) -> PackingResult:
    """Slot arrays → NodeDecisions (shared by the JAX kernel and the native
    C++ packer, which produce identical slot layouts)."""
    slot_pods: Dict[int, List[int]] = {}
    slot_rows: Dict[int, List[int]] = {}
    unschedulable: List[int] = []
    existing_assignments: Dict[int, int] = {}
    for row, k in enumerate(assignment):
        orig = int(pod_idx[row])
        if k == NO_ASSIGNMENT:
            unschedulable.append(orig)
        elif k < E:
            existing_assignments[orig] = int(k)
        else:
            slot_pods.setdefault(int(k), []).append(orig)
            slot_rows.setdefault(int(k), []).append(row)

    nodes: List[NodeDecision] = []
    total = 0.0
    for k, pods_on_node in sorted(slot_pods.items()):
        oi = int(slot_option[k])
        if oi < 0 or oi >= O:
            continue
        option = problem.options[oi]
        total += option.price
        # joint-compat alternatives for flexible launch — same pool only
        # (a NodeClaim belongs to exactly one NodePool)
        rows = slot_rows.get(k, [])
        joint = compat[rows][:, :O].all(axis=0) if rows else np.zeros(O, bool)
        used_vec = slot_used[k]
        cap_ok = (problem.option_alloc >= used_vec).all(axis=1)
        same_pool = np.asarray([o.pool == option.pool for o in problem.options])
        alt_ids = np.nonzero(joint & cap_ok & same_pool)[0][:max_alternatives]
        nodes.append(NodeDecision(
            option=option,
            pod_indices=pods_on_node,
            used=ResourceList.from_vector(used_vec, problem.axes, problem.scales),
            alternatives=[problem.options[a] for a in alt_ids],
        ))
    return PackingResult(nodes=nodes, unschedulable=unschedulable,
                         existing_assignments=existing_assignments,
                         total_price=total)
