"""Topology-constraint lowering: spread / pod-(anti-)affinity → dense solver inputs.

The reference enforces topology spread, pod affinity/anti-affinity and PV
topology inside its per-pod scheduling simulator (surface described in
/root/reference/website/content/en/docs/concepts/scheduling.md sections
"topology spread" and "pod affinity/anti-affinity"; relaxation of preferred
terms is karpenter-core's scheduler behavior).  A batched one-shot solve
can't replay per-pod decisions, so constraints are *lowered* ahead of
tensorization:

  * **zone / capacity-type domains** (labels every launch option and live
    node already carries) are lowered by REWRITING PODS: each member of a
    spread or anti-affinity group gets a concrete domain assignment as an
    extra requirement branch.  Option-compat and existing-node-compat then
    pick the constraint up through the ordinary Requirements path — no new
    kernel inputs.  Domain shares are water-filled against existing matching
    pods, which per-increment satisfies the K8s skew rule
    ((count_d + 1) - global_min <= max_skew) for any max_skew >= 1.
  * **hostname-granular** constraints become a per-class node cap enforced
    inside the packing kernels (self anti-affinity -> cap 1, hostname spread
    -> cap max_skew; computed in tensorize._node_cap), plus `hostname NotIn`
    masks against existing nodes already carrying group pods.
  * **soft constraints** (preferred node affinity, ScheduleAnyway spreads)
    are applied as hard requirements first and relaxed level by level when
    pods come back unschedulable — the batched analog of karpenter-core's
    one-preference-at-a-time relaxation loop.

Known approximations (documented, tested):
  * hostname spread against existing nodes is conservative: a node already
    carrying any group pod is excluded instead of tracking remaining skew.
  * required pod affinity between pods of the same batch co-locates the
    group into one deterministic zone (cheapest eligible) instead of
    searching all zones.
  * hostname-level *affinity* (all pods on one node) is not lowered; such
    pods schedule as if the term were zone-scoped.
  * required anti-affinity *between different pods of the same batch*
    (carrier's selector matches other batch pods, not itself) cannot be
    expressed as a mask ahead of the solve; violations are detected
    post-solve (`find_batch_anti_affinity_violations`) and the carrier is
    stranded to the next round, where the targets are existing pods and the
    ordinary NotIn lowering applies.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Set, Tuple

from ..api import labels as wk
from ..api.objects import Node, Pod, PodAffinityTerm, TopologySpreadConstraint
from ..api.requirements import IN, NOT_IN, Requirement, Requirements

# Relaxation levels (strictest first). MAX_LEVEL must stay the last index.
LEVEL_ALL_SOFT = 0        # every preferred term + ScheduleAnyway spreads hard
LEVEL_TOP_PREFERRED = 1   # only the highest-weight preferred term hard
LEVEL_REQUIRED_ONLY = 2   # required constraints only
MAX_LEVEL = LEVEL_REQUIRED_ONLY


def selector_matches(selector: Mapping[str, str], namespace: str, pod: Pod) -> bool:
    """Label-selector match within one namespace (K8s semantics: empty
    selector matches everything in the namespace)."""
    return (pod.namespace == namespace
            and all(pod.labels.get(k) == v for k, v in selector.items()))


@dataclass
class BoundPod:
    """Projection of a pod already running on a node — the 'existing
    matching pods' side of every topology computation."""
    pod: Pod
    zone: str
    capacity_type: str
    hostname: str


def bound_pods(nodes: Iterable[Node], exclude: Sequence[str] = ()) -> List[BoundPod]:
    out = []
    skip = set(exclude)
    for n in nodes:
        if n.name in skip:
            continue
        host = n.labels.get(wk.HOSTNAME, n.name)
        for p in n.pods:
            out.append(BoundPod(p, n.zone, n.capacity_type, host))
    return out


def greedy_spread(members: Sequence[int],
                  eligible: Mapping[int, Sequence[str]],
                  existing: Mapping[str, int]) -> Dict[int, Optional[str]]:
    """Assign each member pod a domain: most-constrained pods first, each to
    its *eligible* domain with the lowest current count — the per-increment
    form of the K8s skew rule ((count_d + 1) - eligible_min <= max_skew
    holds for any max_skew >= 1 because every pod lands on its own current
    minimum).  Deterministic: ties break on sorted domain name / member
    index.  Members with no eligible domain map to None."""
    counts: Dict[str, int] = dict(existing)
    out: Dict[int, Optional[str]] = {}
    for i in sorted(members, key=lambda i: (len(eligible[i]), i)):
        doms = eligible[i]
        if not doms:
            out[i] = None
            continue
        d = min(doms, key=lambda d: (counts.get(d, 0), d))
        counts[d] = counts.get(d, 0) + 1
        out[i] = d
    return out


# ---------------------------------------------------------------------------
# group detection
# ---------------------------------------------------------------------------

@dataclass
class _SpreadGroup:
    constraint: TopologySpreadConstraint
    namespace: str
    members: List[int] = field(default_factory=list)


@dataclass
class _AffinityGroup:
    term: PodAffinityTerm
    namespace: str
    members: List[int] = field(default_factory=list)


def _spread_key(ns: str, c: TopologySpreadConstraint) -> tuple:
    return (ns, c.topology_key, c.max_skew, c.when_unsatisfiable,
            tuple(sorted(c.label_selector.items())))


def _affinity_key(ns: str, a: PodAffinityTerm) -> tuple:
    return (ns, a.topology_key, a.anti, a.required,
            tuple(sorted(a.label_selector.items())))


def _self_group(term_selector: Mapping[str, str], namespace: str,
                members: Sequence[int], pods: Sequence[Pod]) -> bool:
    """Does the term's selector target the group's own pods?"""
    return any(selector_matches(term_selector, namespace, pods[i]) for i in members)


# ---------------------------------------------------------------------------
# the lowering pass
# ---------------------------------------------------------------------------

class _Rewrites:
    """Accumulates per-pod extra requirements; materializes copies lazily so
    unconstrained pods pass through untouched (and keep object identity)."""

    def __init__(self, pods: Sequence[Pod]):
        self.pods = list(pods)
        self.extra: Dict[int, Requirements] = {}
        self.impossible: Set[int] = set()
        # stripped soft constraints are tracked per kind: preferred terms
        # relax one level before ScheduleAnyway spreads (level contract)
        self.strip_preferred: Set[int] = set()
        self.strip_spread: Set[int] = set()

    def add(self, i: int, *reqs: Requirement):
        cur = self.extra.setdefault(i, Requirements())
        cur.add(*reqs)

    def mark_impossible(self, i: int):
        self.impossible.add(i)

    def result(self) -> List[Pod]:
        out = []
        for i, pod in enumerate(self.pods):
            extra = self.extra.get(i)
            strip_pref = i in self.strip_preferred
            strip_spread = i in self.strip_spread
            if i in self.impossible:
                # an empty In set matches nothing -> the pod surfaces as
                # unschedulable from the solver, like DoNotSchedule demands
                extra = (extra or Requirements()).union(
                    Requirements.of(Requirement.raw(wk.ZONE, False, set())))
            if extra is None and not (strip_pref or strip_spread):
                out.append(pod)
                continue
            p = copy.copy(pod)
            # the copy's constraint fields diverge below — drop the
            # inherited spec caches (ops/tensorize._class_key, pod_is_soft)
            p.__dict__.pop("_ckey", None)
            p.__dict__.pop("_cid", None)
            p.__dict__.pop("_soft", None)
            if strip_spread:
                p.topology_spread = [c for c in pod.topology_spread
                                     if c.when_unsatisfiable != "ScheduleAnyway"]
            if strip_pref:
                p.preferred_affinity_terms = []
                p.pod_affinities = [a for a in pod.pod_affinities if a.required]
            if extra:
                branches = pod.required_affinity_terms or [Requirements()]
                p.required_affinity_terms = [b.union(extra) for b in branches]
            out.append(p)
        return out


def _eligible_domains(pod: Pod, key: str, domains: Sequence[str]) -> List[str]:
    """Domains (zones / capacity types / …) the pod's own required
    constraints allow for label `key`."""
    out = []
    branches = pod.scheduling_requirements()
    for d in domains:
        for b in branches:
            r = b.get(key)
            if r is None or r.has(d):
                out.append(d)
                break
    return out


def eligible_zones(pod: Pod, zones: Sequence[str]) -> List[str]:
    return _eligible_domains(pod, wk.ZONE, zones)


def make_zone_feasibility(catalog: Sequence = (), nodes: Iterable[Node] = (),
                          exclude_nodes: Sequence[str] = ()):
    """Build a pod → {zones it can actually land in} predicate: zones with an
    available offering on a compatible instance type, or a compatible live
    node.  Without this, spread assignment only consults the pod's own zone
    requirement and can pin a type-pinned pod into a zone its instance type
    is never offered in (a false unschedulable the reference's per-pod
    simulator cannot produce)."""
    from ..api.taints import tolerates_all
    excl = set(exclude_nodes)
    node_list = [n for n in nodes
                 if n.name not in excl and not n.marked_for_deletion and n.zone]
    type_zones = []
    for it in catalog:
        avail = {o.zone for o in it.offerings if o.available}
        if avail:
            type_zones.append((it, avail))

    def feasible(pod: Pod) -> Set[str]:
        zones: Set[str] = set()
        branches = pod.scheduling_requirements()
        for it, avail in type_zones:
            if avail <= zones:
                continue
            if not pod.requests.fits(it.allocatable):
                continue
            for b in branches:
                allow = [k for k in b if k not in it.requirements]
                if b.compatible(it.requirements, allow_undefined=allow):
                    zones |= avail
                    break
        for n in node_list:
            if n.zone in zones:
                continue
            if not tolerates_all(pod.tolerations, n.taints):
                continue
            labels = dict(n.labels)
            labels.setdefault(wk.HOSTNAME, n.name)
            provided = Requirements.from_labels(labels)
            if any(b.compatible(provided) for b in branches):
                zones.add(n.zone)
        return zones

    return feasible


def _eligible_captypes(pod: Pod, captypes: Sequence[str]) -> List[str]:
    return _eligible_domains(pod, wk.CAPACITY_TYPE, captypes)


def lower_pods(pods: Sequence[Pod],
               nodes: Iterable[Node] = (),
               option_zones: Sequence[str] = (),
               option_captypes: Sequence[str] = (wk.CAPACITY_TYPE_ON_DEMAND,
                                                 wk.CAPACITY_TYPE_SPOT),
               zone_rank: Optional[Mapping[str, float]] = None,
               exclude_nodes: Sequence[str] = (),
               level: int = LEVEL_ALL_SOFT,
               zone_feasible=None) -> List[Pod]:
    """Lower zone/capacity-type topology constraints into pod requirement
    rewrites (see module docstring).  Returns a pod list of the same length
    and order; constrained pods are shallow copies with extra requirement
    branches, the rest pass through by identity."""
    existing = bound_pods(nodes, exclude=exclude_nodes)
    rw = _Rewrites(pods)

    spreads: Dict[tuple, _SpreadGroup] = {}
    host_spreads: Dict[tuple, _SpreadGroup] = {}
    affinities: Dict[tuple, _AffinityGroup] = {}
    for i, pod in enumerate(pods):
        for c in pod.topology_spread:
            if c.when_unsatisfiable == "ScheduleAnyway" and level >= LEVEL_REQUIRED_ONLY:
                rw.strip_spread.add(i)
                continue
            if c.topology_key in (wk.ZONE, wk.CAPACITY_TYPE):
                spreads.setdefault(_spread_key(pod.namespace, c),
                                   _SpreadGroup(c, pod.namespace)).members.append(i)
            elif c.topology_key == wk.HOSTNAME:
                host_spreads.setdefault(_spread_key(pod.namespace, c),
                                        _SpreadGroup(c, pod.namespace)).members.append(i)
        for a in pod.pod_affinities:
            if not a.required and level >= LEVEL_TOP_PREFERRED:
                rw.strip_preferred.add(i)
                continue
            affinities.setdefault(_affinity_key(pod.namespace, a),
                                  _AffinityGroup(a, pod.namespace)).members.append(i)
        if pod.preferred_affinity_terms and level < LEVEL_REQUIRED_ONLY:
            terms = sorted(pod.preferred_affinity_terms,
                           key=lambda wt: -wt[0])
            if level == LEVEL_TOP_PREFERRED:
                terms = terms[:1]
            for _, reqs in terms:
                rw.add(i, *reqs.values())
        elif pod.preferred_affinity_terms:
            rw.strip_preferred.add(i)

    # ---- zone/capacity-type spread: per-increment greedy assignment,
    # honoring each member's own eligibility (node selectors can differ
    # between members of one group) ----
    for g in spreads.values():
        c, ns = g.constraint, g.namespace
        if c.topology_key == wk.ZONE:
            elig = {}
            for i in g.members:
                zs = eligible_zones(pods[i], option_zones)
                if zone_feasible is not None:
                    # restrict to zones the pod can actually land in; fall
                    # back to the unfiltered set when nothing intersects so
                    # the worst case stays the old (relaxable) behavior
                    feas = zone_feasible(pods[i])
                    inter = [z for z in zs if z in feas]
                    if inter:
                        zs = inter
                elig[i] = zs
            dom_of = lambda bp: bp.zone
            key = wk.ZONE
        else:
            elig = {i: _eligible_captypes(pods[i], option_captypes)
                    for i in g.members}
            dom_of = lambda bp: bp.capacity_type
            key = wk.CAPACITY_TYPE
        all_domains = {d for ds in elig.values() for d in ds}
        counts: Dict[str, int] = {}
        for bp in existing:
            if selector_matches(c.label_selector, ns, bp.pod):
                d = dom_of(bp)
                if d in all_domains:
                    counts[d] = counts.get(d, 0) + 1
        for i, d in greedy_spread(g.members, elig, counts).items():
            if d is None:
                rw.mark_impossible(i)
            else:
                rw.add(i, Requirement(key, IN, [d]))

    # ---- hostname spread: new-node skew is the kernel node cap
    # (tensorize._node_cap); existing nodes already carrying a group pod
    # are excluded (conservative — see module docstring) ----
    for g in host_spreads.values():
        c, ns = g.constraint, g.namespace
        hosts = sorted({bp.hostname for bp in existing
                        if selector_matches(c.label_selector, ns, bp.pod)})
        if hosts:
            for i in g.members:
                rw.add(i, Requirement(wk.HOSTNAME, NOT_IN, hosts))

    # ---- pod (anti-)affinity over zone/hostname domains ----
    for g in affinities.values():
        a, ns = g.term, g.namespace
        sel = a.label_selector
        match_existing = [bp for bp in existing
                          if selector_matches(sel, ns, bp.pod)]
        self_ref = _self_group(sel, ns, g.members, pods)

        if a.anti:
            if a.topology_key == wk.HOSTNAME:
                hosts = sorted({bp.hostname for bp in match_existing})
                if hosts:
                    for i in g.members:
                        rw.add(i, Requirement(wk.HOSTNAME, NOT_IN, hosts))
                # self-exclusion among new pods = per-class node cap
                # (tensorize._node_cap); nothing more to do here
            elif a.topology_key == wk.ZONE:
                taken = sorted({bp.zone for bp in match_existing})
                if self_ref:
                    # one group pod per zone: assign distinct free zones
                    rep = pods[g.members[0]]
                    free = [z for z in eligible_zones(rep, option_zones)
                            if z not in taken]
                    free.sort(key=lambda z: (zone_rank or {}).get(z, 0.0))
                    for n_assigned, i in enumerate(sorted(g.members)):
                        if n_assigned < len(free):
                            rw.add(i, Requirement(wk.ZONE, IN, [free[n_assigned]]))
                        else:
                            rw.mark_impossible(i)
                elif taken:
                    for i in g.members:
                        rw.add(i, Requirement(wk.ZONE, NOT_IN, taken))
        else:
            # affinity: restrict to domains already hosting matching pods;
            # for an intra-batch group, co-locate into one eligible zone
            if a.topology_key == wk.HOSTNAME and match_existing:
                hosts = sorted({bp.hostname for bp in match_existing})
                for i in g.members:
                    rw.add(i, Requirement(wk.HOSTNAME, IN, hosts))
            elif a.topology_key == wk.ZONE or (
                    a.topology_key == wk.HOSTNAME and not match_existing):
                zones_with = sorted({bp.zone for bp in match_existing})
                if zones_with:
                    for i in g.members:
                        rw.add(i, Requirement(wk.ZONE, IN, zones_with))
                elif self_ref:
                    rep = pods[g.members[0]]
                    cand = eligible_zones(rep, option_zones)
                    if not cand:
                        for i in g.members:
                            rw.mark_impossible(i)
                        continue
                    chosen = min(cand, key=lambda z: ((zone_rank or {}).get(z, 0.0), z))
                    for i in g.members:
                        rw.add(i, Requirement(wk.ZONE, IN, [chosen]))
                elif a.required:
                    for i in g.members:
                        rw.mark_impossible(i)

    return rw.result()


def find_batch_topology_violations(problem, packing,
                                   existing_nodes: Sequence[Node] = ()
                                   ) -> Set[int]:
    """Detect topology constraints broken *within one batch* — the cases no
    pre-solve mask can express (module docstring, last approximation):

      * required anti-affinity whose selector matches a *different* pod
        placed on the same node (hostname) or zone;
      * hostname DoNotSchedule spread groups that span multiple pod classes
        (the kernel node cap is per class, so two classes of one group can
        co-locate beyond max_skew).

    Returns indices into `problem.pods` of pods to strand.  Carriers are
    processed in index order and only violate against *non-stranded* pods,
    so a mutually anti-affine pair strands exactly one member — the other
    binds, and the stranded one re-solves next round against bound targets,
    where the ordinary NotIn lowering applies (guaranteed convergence)."""
    pods = problem.pods
    # placement: pod index -> (node key, zone)
    place: Dict[int, Tuple[object, str]] = {}
    for di, nd in enumerate(packing.nodes):
        for i in nd.pod_indices:
            place[i] = (("new", di), nd.option.zone)
    nodes = list(existing_nodes)
    for i, slot in packing.existing_assignments.items():
        zone = nodes[slot].zone if slot < len(nodes) else ""
        place[i] = (("existing", slot), zone)

    by_node: Dict[object, List[int]] = {}
    by_zone: Dict[str, List[int]] = {}
    for i, (nk, z) in place.items():
        by_node.setdefault(nk, []).append(i)
        if z:
            by_zone.setdefault(z, []).append(i)

    out: Set[int] = set()
    for i in sorted(place):
        nk, z = place[i]
        pod = pods[i]
        for a in pod.pod_affinities:
            if not (a.anti and a.required):
                continue
            if a.topology_key == wk.HOSTNAME:
                neighbors = by_node.get(nk, ())
            elif a.topology_key == wk.ZONE:
                neighbors = by_zone.get(z, ()) if z else ()
            else:
                continue
            if any(j != i and j not in out and pods[j].uid != pod.uid
                   and selector_matches(a.label_selector, pod.namespace, pods[j])
                   for j in neighbors):
                out.add(i)
                break

    # hostname spread across classes: per (group, node) the kept count may
    # not exceed max_skew; strand the excess (highest indices first so the
    # earliest pods keep their placement deterministically)
    group_node: Dict[tuple, Dict[object, List[int]]] = {}
    for i in sorted(place):
        if i in out:
            continue
        pod = pods[i]
        for c in pod.topology_spread:
            if c.topology_key != wk.HOSTNAME or c.when_unsatisfiable != "DoNotSchedule":
                continue
            key = _spread_key(pod.namespace, c)
            group_node.setdefault(key, {}).setdefault(place[i][0], []).append(i)
    for key, per_node in group_node.items():
        max_skew = key[2]
        for nk, members in per_node.items():
            if len(members) > max_skew:
                out.update(members[max_skew:])
    return out


def pod_is_soft(pod: Pod) -> bool:
    """Whether relaxation levels can change this pod's lowering. Spec-derived
    and cached (dropped alongside the class key when _Rewrites copies a pod),
    so 50k-pod batches pay the attribute walk once, at admission."""
    d = pod.__dict__
    s = d.get("_soft")
    if s is None:
        s = d["_soft"] = bool(
            pod.preferred_affinity_terms
            or any(c.when_unsatisfiable == "ScheduleAnyway"
                   for c in pod.topology_spread)
            or any(not a.required for a in pod.pod_affinities))
    return s


def has_soft_constraints(pods: Sequence[Pod]) -> bool:
    """Whether relaxing to a higher level could change the outcome."""
    return any(pod_is_soft(p) for p in pods)
