"""Asynchronous LP-guide refinery: column generation off the tick.

The flagship guided path hits its latency headline only when the mix
cache is warm — a cold guided solve pays the 0.3–2s colgen LP
synchronously inside the provisioning tick (round-5 verdict), against a
~1s batch window.  CvxCluster's pattern (PAPERS.md) is the fix: decouple
the expensive optimality refinement from the latency-critical
feasibility path and amortize the solver across rounds.

`GuideRefinery` is that decoupling: `solve_guided` hands a mix-cache
miss here as a (key, job) pair and answers the tick immediately — with
the freshest *stale* mix whose catalog fingerprint still matches
(bounded staleness window) or, failing that, the greedy plan.  A worker
thread runs the job (ops/lpguide._refine_job: mask → dedup →
warm-started colgen → rounding), lands the refined mix in the
content-keyed cache so the NEXT solve of the same signature is a warm
hit, and prices the greedy alternative; when the refined mix beats it by
more than `upgrade_threshold`, a one-shot upgrade hint is raised that
the controller manager turns into an early re-solve of still-pending
pods (operator/manager.py).

Degradation contract: every failure mode — worker crash, queue
overflow, job exception — leaves the provisioning path exactly where it
would be with no refinery at all: greedy solves that still bind every
pod.  Exceptions are counted (karpenter_lpguide_refinery_errors) and
swallowed; the tick never sees them.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional

from ..analysis.lockorder import named_lock
from ..utils import metrics, tracing

log = logging.getLogger("karpenter_tpu.refinery")


class GuideRefinery:
    """Bounded, deduplicating background refinement queue.

    `clock` feeds the staleness window; `monotonic` feeds the drain
    deadline — both injectable (the virtual-clock simulator injects its
    clock for each so the refinery participates fully in virtual time;
    tests inject fake clocks).  Refine-latency metrics always use
    perf_counter.  `start=False` leaves the worker unstarted — jobs
    accumulate until `start()` — which tests use to observe the
    cold/stale tick behavior deterministically.
    """

    def __init__(self, max_queue: int = 64, stale_ttl: float = 300.0,
                 upgrade_threshold: float = 0.03,
                 clock: Callable[[], float] = time.monotonic,
                 monotonic: Callable[[], float] = time.monotonic,
                 start: bool = True, device_lp: bool = False,
                 lp_health=None):
        self.stale_ttl = stale_ttl
        self.upgrade_threshold = upgrade_threshold
        self.clock = clock
        self.monotonic = monotonic
        # DeviceLP wiring (operator/operator.py): with device_lp on and
        # the lp_health ladder healthy, solve_guided refines a miss
        # synchronously on the PDHG solver instead of enqueueing here —
        # this queue then only carries the HiGHS-rung fallback refines.
        self.device_lp = bool(device_lp)
        self.lp_health = lp_health
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._lock = named_lock("refinery.inflight")
        self._inflight: set = set()     # guarded-by: _lock
        self._stop = threading.Event()
        self._upgrade = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._work, daemon=True,
                                        name="lpguide-refinery")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------------
    def submit(self, key, job: Callable[[], Optional[dict]]) -> bool:
        """Enqueue one refine job, deduplicated on the exact problem
        signature: re-solves of an unchanged pending set (tick loops,
        retries) while a refinement is queued or running are no-ops.
        A full queue drops the job (counted) — the caller already has
        its greedy/stale answer, so dropping only delays refinement."""
        with self._lock:
            if key in self._inflight:
                return False
            self._inflight.add(key)
        # capture the submitting tick's span so the daemon's refine span
        # joins the provisioning trace it was spawned from
        ctx = tracing.TRACER.capture()
        try:
            self._q.put_nowait((key, job, ctx))
        except queue.Full:
            with self._lock:
                self._inflight.discard(key)
            metrics.refinery_errors().inc({"reason": "queue_full"})
            return False
        metrics.refinery_queue_depth().set(len(self._inflight))
        return True

    def _work(self) -> None:
        while not self._stop.is_set():
            try:
                key, job, ctx = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            t0 = time.perf_counter()
            res = None
            try:
                with tracing.TRACER.attach(ctx), \
                        tracing.span("refinery.refine") as sp:
                    res = job()
                    if res:
                        sp.annotate(z_lp=res.get("z_lp"),
                                    greedy_total=res.get("greedy_total"))
            except Exception:
                metrics.refinery_errors().inc({"reason": "exception"})
                log.exception("refine job failed; tick stays on greedy")
            finally:
                with self._lock:
                    self._inflight.discard(key)
                metrics.refinery_queue_depth().set(len(self._inflight))
                metrics.refinery_refine_duration().observe(
                    time.perf_counter() - t0)
                self._q.task_done()
            if res and res.get("greedy_total", 0.0) > 0:
                saving = 1.0 - res["z_lp"] / res["greedy_total"]
                if saving > self.upgrade_threshold:
                    metrics.refinery_cost_delta().inc(
                        by=res["greedy_total"] - res["z_lp"])
                    self._upgrade.set()

    # ------------------------------------------------------------------
    def take_upgrade(self) -> bool:
        """One-shot: True exactly once per refined-mix-beats-greedy
        event.  The manager consumes this to re-solve still-pending pods
        ahead of the batch window."""
        if self._upgrade.is_set():
            self._upgrade.clear()
            return True
        return False

    def pending(self) -> int:
        with self._lock:
            return len(self._inflight)

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted job finished (tests/bench); True
        if the queue drained within the timeout.  The deadline runs on the
        injected `monotonic` clock so a virtual-time harness bounds the
        wait in virtual seconds; the 5ms poll is a thread yield to the
        worker, not a timing source."""
        deadline = self.monotonic() + timeout
        while self.monotonic() < deadline:
            if self.pending() == 0:
                return True
            time.sleep(0.005)
        return self.pending() == 0
