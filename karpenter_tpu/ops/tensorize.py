"""Tensorization: pods + catalog + nodepools → dense arrays.

This layer replaces the reference's per-pod set algebra
(`scheduling.Requirements.Compatible` at
/root/reference/pkg/cloudprovider/cloudprovider.go:260-265 and the
per-(pod,instance-type) inner loop of the FFD scheduler described in
/root/reference/designs/bin-packing.md:16-43) with a one-shot lowering:

  * pods are deduplicated into **equivalence classes** (identical requests +
    constraints) — the host does set algebra once per (class × launch option)
    instead of once per (pod × node × type) inside the scheduling loop;
  * the catalog is flattened into **launch options** — one column per
    (nodepool × instance-type × zone × capacity-type) available offering,
    the exact action space of the reference's CreateFleet override list
    (/root/reference/pkg/providers/instance/instance.go:327-367);
  * the result is a `Problem` of dense arrays (requests C×R / P×R, compat
    C×O / P×O, allocatable O×R, price O) that the jit-compiled kernels in
    karpenter_tpu.ops.{ffd,sinkhorn} consume with static shapes.

Shape discipline: `pad_to` buckets P and O up to fixed sizes so recompiles
are bounded (SURVEY.md §7 hard part iv).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as wk
from ..api.objects import Node, NodePool, Pod
from ..api.requirements import IN, Requirement, Requirements
from ..api.resources import DEFAULT_AXES, DEFAULT_SCALES, PODS, ResourceList
from ..api.taints import tolerates_all
from ..catalog.instancetype import InstanceType, Offering


@dataclass(frozen=True)
class LaunchOption:
    """One solver column: a concrete way to buy a node."""
    pool: str
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    type_index: int       # into the catalog list
    pool_index: int
    weight_rank: int = 0  # 0 == highest-weight pool (pool precedence)


@dataclass
class Problem:
    """Dense scheduling problem. All arrays are numpy on the host; kernels
    move them to device once per solve."""
    axes: Tuple[str, ...]
    # per pod-class
    class_requests: np.ndarray      # C×R float32
    class_counts: np.ndarray        # C int32
    class_compat: np.ndarray        # C×O bool
    class_members: List[List[int]]  # class -> original pod indices
    # per launch option (column)
    options: List[LaunchOption]
    option_alloc: np.ndarray        # O×R float32
    option_price: np.ndarray        # O float32
    option_rank: np.ndarray = None  # O int32 pool-weight rank (0 = preferred)
    # per-class max pods per node (hostname spread / anti-affinity lowering;
    # _CAP_BIG == unconstrained)
    class_node_cap: np.ndarray = None  # C int32
    option_zone: np.ndarray = None  # O int32
    option_captype: np.ndarray = None  # O int32 (0=on-demand, 1=spot)
    zones: List[str] = field(default_factory=list)
    pods: List[Pod] = field(default_factory=list)

    @property
    def num_classes(self) -> int:
        return self.class_requests.shape[0]

    @property
    def class_reps(self) -> List[Pod]:
        """One representative pod per equivalence class."""
        return [self.pods[m[0]] for m in self.class_members]

    def class_order(self) -> np.ndarray:
        """FFD order over classes (largest first) under a scale-free size key
        (per-axis mean allocatable). The single source of ordering truth for
        expand(), the class-granular solver, and the test oracles."""
        norm = (self.option_alloc.mean(axis=0) if self.num_options
                else np.ones(len(self.axes), np.float32))
        norm = np.where(norm > 0, norm, 1.0)
        size = (self.class_requests / norm).sum(axis=1)
        return np.argsort(-size, kind="stable")

    @property
    def num_options(self) -> int:
        return self.option_alloc.shape[0]

    # ---- per-pod expansion (for pod-granular kernels) ----
    def expand(self, sort_desc: bool = True, extra_compat: Optional[np.ndarray] = None):
        """Expand classes to per-pod rows, FFD-sorted (largest first, as the
        reference sorts pods by resources descending,
        /root/reference/designs/bin-packing.md:16-20). Returns
        (requests P×R, compat P×(O[+E]), pod_index P, class_id P). The sort
        is stable on class rank, so rows of one class stay contiguous — the
        pod-granular kernel's per-class node-cap counter relies on that.
        `extra_compat` (C×E, e.g. per-existing-node feasibility) is expanded
        and appended as extra columns in the same row order."""
        class_ids = np.repeat(np.arange(self.num_classes), self.class_counts)
        requests = self.class_requests[class_ids]
        compat = self.class_compat[class_ids]
        if extra_compat is not None:
            compat = np.concatenate([compat, extra_compat[class_ids]], axis=1)
        pod_idx = np.concatenate([np.asarray(m, dtype=np.int32) for m in self.class_members]) \
            if self.class_members else np.zeros(0, np.int32)
        if sort_desc and len(requests):
            class_rank = np.empty(self.num_classes, np.int64)
            class_rank[self.class_order()] = np.arange(self.num_classes)
            order = np.argsort(class_rank[class_ids], kind="stable")
            requests, compat = requests[order], compat[order]
            pod_idx, class_ids = pod_idx[order], class_ids[order]
        return requests.astype(np.float32), compat, pod_idx, class_ids.astype(np.int32)


def _class_key(pod: Pod) -> tuple:
    return (
        tuple(sorted(pod.requests.nonzero().items())),
        tuple(sorted(pod.node_selector.items())),
        tuple(repr(t) for t in pod.required_affinity_terms),
        tuple((w, repr(t)) for w, t in pod.preferred_affinity_terms),
        tuple(sorted(pod.volume_zones)),
        tuple(sorted((t.key, t.operator, t.value, t.effect) for t in pod.tolerations)),
        tuple((c.topology_key, c.max_skew, c.when_unsatisfiable,
               tuple(sorted(c.label_selector.items()))) for c in pod.topology_spread),
        tuple((a.topology_key, a.anti, a.required,
               tuple(sorted(a.label_selector.items()))) for a in pod.pod_affinities),
        tuple(sorted(pod.labels.items())),
        pod.namespace,
    )


_CAP_BIG = 2**30


def _node_cap(pod: Pod) -> int:
    """Max pods of this class one node may hold — the kernel-enforced
    lowering of hostname-granular constraints (ops/constraints.py docstring):
    hostname topology spread -> max_skew; required self anti-affinity over
    hostname -> 1."""
    cap = _CAP_BIG
    for c in pod.topology_spread:
        if c.topology_key == wk.HOSTNAME:
            cap = min(cap, max(1, int(c.max_skew)))
    for a in pod.pod_affinities:
        if (a.anti and a.required and a.topology_key == wk.HOSTNAME
                and all(pod.labels.get(k) == v
                        for k, v in a.label_selector.items())):
            cap = 1
    return cap


def build_options(catalog: Sequence[InstanceType],
                  nodepools: Sequence[NodePool]) -> List[LaunchOption]:
    """Flatten (nodepool × type × zone × capacity-type) available offerings,
    dropping options the nodepool's own requirements exclude.  Higher-weight
    NodePools rank first (weight precedence, reference NodePool.spec.weight)."""
    ranks = {w: i for i, w in
             enumerate(sorted({p.weight for p in nodepools}, reverse=True))}
    out: List[LaunchOption] = []
    for pi, pool in enumerate(nodepools):
        pool_reqs = pool.requirements()
        for ti, it in enumerate(catalog):
            # keys the type doesn't define (nodepool, template labels) are
            # provided by the pool itself at node creation — only type-defined
            # keys can conflict (AllowUndefinedWellKnownLabels semantics)
            allow = [k for k in pool_reqs if k not in it.requirements]
            if not pool_reqs.compatible(it.requirements, allow_undefined=allow):
                continue
            zone_req = pool_reqs.get(wk.ZONE)
            cap_req = pool_reqs.get(wk.CAPACITY_TYPE)
            for o in it.offerings:
                if not o.available:
                    continue
                if zone_req is not None and not zone_req.has(o.zone):
                    continue
                if cap_req is not None and not cap_req.has(o.capacity_type):
                    continue
                out.append(LaunchOption(pool.name, it.name, o.zone,
                                        o.capacity_type, o.price, ti, pi,
                                        weight_rank=ranks[pool.weight]))
    # pool precedence first, then deterministic price ordering with name
    # tie-break (/root/reference/pkg/providers/instance/instance.go:395-412)
    out.sort(key=lambda lo: (lo.weight_rank, lo.price, lo.instance_type,
                             lo.zone, lo.capacity_type, lo.pool))
    return out


def _option_requirements(option: LaunchOption, it: InstanceType,
                         pool: NodePool) -> Requirements:
    """The label surface a node launched from this option will have."""
    reqs = Requirements(it.requirements)
    reqs = reqs.union(Requirements.of(
        Requirement(wk.ZONE, IN, [option.zone]),
        Requirement(wk.CAPACITY_TYPE, IN, [option.capacity_type]),
        Requirement(wk.NODEPOOL, IN, [option.pool]),
    ))
    return reqs.union(Requirements.from_labels(pool.template.labels))


def tensorize(pods: Sequence[Pod], catalog: Sequence[InstanceType],
              nodepools: Sequence[NodePool],
              axes: Tuple[str, ...] = DEFAULT_AXES) -> Problem:
    """Lower a scheduling round to dense arrays."""
    pools = {p.name: p for p in nodepools}
    options = build_options(catalog, nodepools)
    O, R = len(options), len(axes)

    option_alloc = np.zeros((O, R), np.float32)
    option_price = np.zeros(O, np.float32)
    zones = sorted({o.zone for o in options})
    zone_ids = {z: i for i, z in enumerate(zones)}
    option_zone = np.zeros(O, np.int32)
    option_captype = np.zeros(O, np.int32)
    option_rank = np.zeros(O, np.int32)
    option_reqs: List[Requirements] = []
    option_taints = []
    for j, opt in enumerate(options):
        option_rank[j] = opt.weight_rank
        it = catalog[opt.type_index]
        pool = pools[opt.pool]
        option_alloc[j] = it.allocatable.to_vector(axes, DEFAULT_SCALES)
        option_price[j] = opt.price
        option_zone[j] = zone_ids[opt.zone]
        option_captype[j] = 1 if opt.capacity_type == wk.CAPACITY_TYPE_SPOT else 0
        option_reqs.append(_option_requirements(opt, it, pool))
        option_taints.append(pool.template.taints)

    # pod equivalence classes
    classes: Dict[tuple, int] = {}
    members: List[List[int]] = []
    reps: List[Pod] = []
    for i, pod in enumerate(pods):
        k = _class_key(pod)
        ci = classes.get(k)
        if ci is None:
            ci = classes[k] = len(members)
            members.append([])
            reps.append(pod)
        members[ci].append(i)

    C = len(reps)
    class_requests = np.zeros((C, R), np.float32)
    class_compat = np.zeros((C, O), bool)
    # compat rows depend only on the class's constraint shape (branches +
    # tolerations), not its resources — many classes share one shape, so the
    # O(C×O) Python loop collapses to O(distinct-shapes × O)
    compat_memo: dict = {}
    for ci, rep in enumerate(reps):
        req = ResourceList(rep.requests)
        req[PODS] = req.get(PODS, 0) + 1  # every pod consumes one pod slot
        class_requests[ci] = req.to_vector(axes, DEFAULT_SCALES, round_up=True)
        branches = rep.scheduling_requirements()
        sig = (tuple(tuple(sorted((k, repr(r)) for k, r in b.items()))
                     for b in branches),
               tuple(sorted((t.key, t.operator, t.value, t.effect)
                            for t in rep.tolerations)))
        row = compat_memo.get(sig)
        if row is None:
            row = np.zeros(O, bool)
            for j in range(O):
                if not tolerates_all(rep.tolerations, option_taints[j]):
                    continue
                # Fail closed on keys the option can't provide: a pod
                # requiring a user label schedules only if some NodePool
                # template carries it (reference scheduling.md label rules);
                # complemented ops (NotIn/DoesNotExist) tolerate absence via
                # Requirements.compatible.
                provided = option_reqs[j]
                if any(b.compatible(provided) for b in branches):
                    row[j] = True
            compat_memo[sig] = row
        class_compat[ci] = row

    return Problem(
        axes=axes,
        class_requests=class_requests,
        class_counts=np.asarray([len(m) for m in members], np.int32),
        class_compat=class_compat,
        class_members=members,
        class_node_cap=np.asarray([_node_cap(rep) for rep in reps], np.int32),
        options=options,
        option_alloc=option_alloc,
        option_price=option_price,
        option_rank=option_rank,
        option_zone=option_zone,
        option_captype=option_captype,
        zones=zones,
        pods=list(pods),
    )


def pad_to(n: int, buckets: Sequence[int] = (256, 1024, 4096, 16384, 65536)) -> int:
    """Bucketed padding to bound jit recompiles (SURVEY.md §7 hard part iv)."""
    for b in buckets:
        if n <= b:
            return b
    return int(2 ** math.ceil(math.log2(max(n, 1))))
