"""Tensorization: pods + catalog + nodepools → dense arrays.

This layer replaces the reference's per-pod set algebra
(`scheduling.Requirements.Compatible` at
/root/reference/pkg/cloudprovider/cloudprovider.go:260-265 and the
per-(pod,instance-type) inner loop of the FFD scheduler described in
/root/reference/designs/bin-packing.md:16-43) with a one-shot lowering:

  * pods are deduplicated into **equivalence classes** (identical requests +
    constraints) — the host does set algebra once per (class × launch option)
    instead of once per (pod × node × type) inside the scheduling loop;
  * the catalog is flattened into **launch options** — one column per
    (nodepool × instance-type × zone × capacity-type) available offering,
    the exact action space of the reference's CreateFleet override list
    (/root/reference/pkg/providers/instance/instance.go:327-367);
  * the result is a `Problem` of dense arrays (requests C×R / P×R, compat
    C×O / P×O, allocatable O×R, price O) that the jit-compiled kernels in
    karpenter_tpu.ops.{ffd,classpack,lpbound} consume with static shapes.

Shape discipline: `pad_to` buckets P and O up to fixed sizes so recompiles
are bounded (SURVEY.md §7 hard part iv).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as wk
from ..api.objects import Node, NodePool, Pod
from ..api.requirements import IN, Requirement, Requirements
from ..api.resources import DEFAULT_AXES, DEFAULT_SCALES, PODS, ResourceList
from ..api.taints import tolerates_all
from ..catalog.instancetype import InstanceType, Offering


@dataclass(frozen=True)
class LaunchOption:
    """One solver column: a concrete way to buy a node."""
    pool: str
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    type_index: int       # into the catalog list
    pool_index: int
    weight_rank: int = 0  # 0 == highest-weight pool (pool precedence)


@dataclass(frozen=True)
class GangInfo:
    """One all-or-nothing gang observed in a batch (ops/gang.py): every
    member binds in one solve within one topology domain, or none do."""
    name: str
    size: int                 # declared member count (arrived may be less)
    tier: int                 # preemption tier (higher evicts lower)
    topology: str = "zone"    # domain granularity: "zone" | "hostname"


@dataclass
class Problem:
    """Dense scheduling problem. All arrays are numpy on the host; kernels
    move them to device once per solve."""
    axes: Tuple[str, ...]
    # per pod-class
    class_requests: np.ndarray      # C×R float32
    class_counts: np.ndarray        # C int32
    class_compat: np.ndarray        # C×O bool
    class_members: Sequence  # class -> original pod index vectors (int64
                             # ndarrays from tensorize; plain lists OK too)
    # per launch option (column)
    options: List[LaunchOption]
    option_alloc: np.ndarray        # O×R float32
    option_price: np.ndarray        # O float32
    option_rank: np.ndarray = None  # O int32 pool-weight rank (0 = preferred)
    # per-class max pods per node (hostname spread / anti-affinity lowering;
    # _CAP_BIG == unconstrained)
    class_node_cap: np.ndarray = None  # C int32
    option_zone: np.ndarray = None  # O int32 (index into zones)
    option_captype: np.ndarray = None  # O int32 (index into the sorted
    # capacity-type vocabulary; on-demand=0, spot=1 in the standard catalog)
    zones: List[str] = field(default_factory=list)
    pods: List[Pod] = field(default_factory=list)
    # gang columns (GangScheduling): class → index into `gangs` (-1 = not
    # in a gang).  Gang members may span several classes (heterogeneous
    # specs); `None` class_gang means "no gang pods in this batch" and
    # every consumer short-circuits.
    class_gang: np.ndarray = None   # C int32, -1 == non-gang
    gangs: List[GangInfo] = field(default_factory=list)
    # per-axis quantity scales the dense arrays were lowered with (byte axes
    # divide to MiB so int32 kernel math can't overflow); decode must invert
    # with THESE, not DEFAULT_SCALES — extra axes may carry their own scale
    scales: Mapping[str, float] = field(default_factory=lambda: DEFAULT_SCALES)

    @property
    def num_classes(self) -> int:
        return self.class_requests.shape[0]

    @property
    def class_reps(self) -> List[Pod]:
        """One representative pod per equivalence class."""
        return [self.pods[m[0]] for m in self.class_members]

    def class_order(self) -> np.ndarray:
        """FFD order over classes (largest first) under a scale-free size key:
        the class's BOTTLENECK dimension (max over axes of request /
        mean-allocatable) — the standard vector-packing size measure, which
        benches 1-2% cheaper than the sum-of-dims key on mixed shapes and
        ties on homogeneous ones. The single source of ordering truth for
        expand(), the class-granular solver, and the test oracles."""
        norm = (self.option_alloc.mean(axis=0) if self.num_options
                else np.ones(len(self.axes), np.float32))
        norm = np.where(norm > 0, norm, 1.0)
        size = (self.class_requests / norm).max(axis=1)
        order = np.argsort(-size, kind="stable")
        if self.class_gang is not None:
            # gang members pack adjacently (at the rank of the gang's
            # largest class) so one scan sees the whole gang together —
            # the no-gang path above is byte-identical to the pre-gang key
            gang_slot: Dict[int, int] = {}
            groups: List[List[int]] = []
            for ci in order.tolist():
                g = int(self.class_gang[ci])
                if g < 0:
                    groups.append([ci])
                elif g in gang_slot:
                    groups[gang_slot[g]].append(ci)
                else:
                    gang_slot[g] = len(groups)
                    groups.append([ci])
            order = np.asarray([ci for grp in groups for ci in grp],
                               order.dtype)
        return order

    @property
    def num_options(self) -> int:
        return self.option_alloc.shape[0]

    def members_arrays(self) -> List[np.ndarray]:
        """class_members as int64 arrays, converted once per Problem —
        decode concatenates them every solve."""
        arrs = self.__dict__.get("_members_arr")
        if arrs is None:
            arrs = self.__dict__["_members_arr"] = [
                np.asarray(m, np.int64) for m in self.class_members]
        return arrs

    # ---- per-pod expansion (for pod-granular kernels) ----
    def expand(self, sort_desc: bool = True, extra_compat: Optional[np.ndarray] = None):
        """Expand classes to per-pod rows, FFD-sorted (largest first, as the
        reference sorts pods by resources descending,
        /root/reference/designs/bin-packing.md:16-20). Returns
        (requests P×R, compat P×(O[+E]), pod_index P, class_id P). The sort
        is stable on class rank, so rows of one class stay contiguous — the
        pod-granular kernel's per-class node-cap counter relies on that.
        `extra_compat` (C×E, e.g. per-existing-node feasibility) is expanded
        and appended as extra columns in the same row order."""
        class_ids = np.repeat(np.arange(self.num_classes), self.class_counts)
        requests = self.class_requests[class_ids]
        compat = self.class_compat[class_ids]
        if extra_compat is not None:
            compat = np.concatenate([compat, extra_compat[class_ids]], axis=1)
        pod_idx = np.concatenate([np.asarray(m, dtype=np.int32) for m in self.class_members]) \
            if self.class_members else np.zeros(0, np.int32)
        if sort_desc and len(requests):
            class_rank = np.empty(self.num_classes, np.int64)
            class_rank[self.class_order()] = np.arange(self.num_classes)
            order = np.argsort(class_rank[class_ids], kind="stable")
            requests, compat = requests[order], compat[order]
            pod_idx, class_ids = pod_idx[order], class_ids[order]
        return requests.astype(np.float32), compat, pod_idx, class_ids.astype(np.int32)


def _class_key(pod: Pod) -> tuple:
    """Equivalence-class key over the pod's scheduling-relevant spec.

    Cached on the pod (the spec is immutable once created — the one code
    path that derives modified pods, ops/constraints._Rewrites, copies and
    drops the cache), so re-solves over the same pending set — relaxation
    levels, consolidation simulations, successive rounds — skip the key
    build entirely. Empty constraint fields short-circuit to (): at 50k
    pods the per-pod cost is what bounds tensorize latency."""
    d = pod.__dict__
    k = d.get("_ckey")
    if k is not None:
        return k
    req = d["requests"]
    ns = d["node_selector"]
    rat = d["required_affinity_terms"]
    pat = d["preferred_affinity_terms"]
    vz = d["volume_zones"]
    tol = d["tolerations"]
    ts = d["topology_spread"]
    pa = d["pod_affinities"]
    lab = d["labels"]
    k = (
        tuple(sorted([i for i in req.items() if i[1]])) if req else (),
        tuple(sorted(ns.items())) if ns else (),
        tuple([repr(t) for t in rat]) if rat else (),
        tuple([(w, repr(t)) for w, t in pat]) if pat else (),
        tuple(sorted(vz)) if vz else (),
        tuple(sorted([(t.key, t.operator, t.value, t.effect)
                      for t in tol])) if tol else (),
        tuple([(c.topology_key, c.max_skew, c.when_unsatisfiable,
                tuple(sorted(c.label_selector.items())))
               for c in ts]) if ts else (),
        tuple([(a.topology_key, a.anti, a.required,
                tuple(sorted(a.label_selector.items())))
               for a in pa]) if pa else (),
        tuple(sorted(lab.items())) if lab else (),
        d["namespace"],
        # gang members must never merge into non-gang classes (and gangs
        # must not merge with each other): the gang spec is part of the
        # scheduling-relevant identity.  Non-gang pods keep () so every
        # pre-gang key is unchanged in content.
        ((d["gang_name"], d["gang_size"], d["gang_tier"],
          d["gang_topology"]) if d["gang_name"] else ()),
    )
    d["_ckey"] = k
    return k


# class keys interned to small ints so the 50k-pod grouping loop can run in
# numpy (np.unique over an int vector) instead of 50k Python dict round
# trips.  Pod labels are part of the key, so distinct keys are unbounded in
# a long-lived controller (per-pod-unique label values churn daily): the
# table resets when it exceeds _CLASS_IDS_MAX, and a generation token on
# the per-pod cache invalidates stale ids.  Resets happen ONLY between
# tensorize calls (see tensorize) — a mid-call reset would let two distinct
# keys share an id and silently merge classes.
_CLASS_IDS: Dict[tuple, int] = {}
_CLASS_GEN = [0]
_CLASS_IDS_MAX = 1 << 17


def _class_id(pod: Pod) -> int:
    d = pod.__dict__
    tok = d.get("_cid")
    if tok is not None and tok[0] == _CLASS_GEN[0]:
        return tok[1]
    k = _class_key(pod)
    cid = _CLASS_IDS.get(k)
    if cid is None:
        cid = _CLASS_IDS[k] = len(_CLASS_IDS)
    d["_cid"] = (_CLASS_GEN[0], cid)
    return cid


_CAP_BIG = 2**30


def _node_cap(pod: Pod) -> int:
    """Max pods of this class one node may hold — the kernel-enforced
    lowering of hostname-granular constraints (ops/constraints.py docstring):
    hostname topology spread -> max_skew; required self anti-affinity over
    hostname -> 1."""
    cap = _CAP_BIG
    for c in pod.topology_spread:
        if c.topology_key == wk.HOSTNAME:
            cap = min(cap, max(1, int(c.max_skew)))
    for a in pod.pod_affinities:
        if (a.anti and a.required and a.topology_key == wk.HOSTNAME
                and all(pod.labels.get(k) == v
                        for k, v in a.label_selector.items())):
            cap = 1
    return cap


def build_options(catalog: Sequence[InstanceType],
                  nodepools: Sequence[NodePool]) -> List[LaunchOption]:
    """Flatten (nodepool × type × zone × capacity-type) available offerings,
    dropping options the nodepool's own requirements exclude.  Higher-weight
    NodePools rank first (weight precedence, reference NodePool.spec.weight)."""
    ranks = {w: i for i, w in
             enumerate(sorted({p.weight for p in nodepools}, reverse=True))}
    out: List[LaunchOption] = []
    for pi, pool in enumerate(nodepools):
        pool_reqs = pool.requirements()
        for ti, it in enumerate(catalog):
            # keys the type doesn't define (nodepool, template labels) are
            # provided by the pool itself at node creation — only type-defined
            # keys can conflict (AllowUndefinedWellKnownLabels semantics)
            allow = [k for k in pool_reqs if k not in it.requirements]
            if not pool_reqs.compatible(it.requirements, allow_undefined=allow):
                continue
            zone_req = pool_reqs.get(wk.ZONE)
            cap_req = pool_reqs.get(wk.CAPACITY_TYPE)
            for o in it.offerings:
                if not o.available:
                    continue
                if zone_req is not None and not zone_req.has(o.zone):
                    continue
                if cap_req is not None and not cap_req.has(o.capacity_type):
                    continue
                out.append(LaunchOption(pool.name, it.name, o.zone,
                                        o.capacity_type, o.price, ti, pi,
                                        weight_rank=ranks[pool.weight]))
    # pool precedence first, then deterministic price ordering with name
    # tie-break (/root/reference/pkg/providers/instance/instance.go:395-412)
    out.sort(key=lambda lo: (lo.weight_rank, lo.price, lo.instance_type,
                             lo.zone, lo.capacity_type, lo.pool))
    return out


class _CatalogSide:
    """Everything tensorize derives from (catalog × nodepools) alone, cached
    across solves (VERDICT r1 #4: encode option labels as tables once per
    catalog seq; the catalog changes only on ICE/pricing seq bumps).

    The compat decomposition: an option's label surface is its (type × pool)
    *group* surface — type requirements ∪ pool labels ∪ the nodepool pin —
    plus two per-option pins (zone, capacity-type). Pod requirement branches
    are therefore evaluated once per GROUP with the zone/captype keys
    stripped, and the stripped keys are applied as integer-table lookups
    over all O options at once. Exact because build_options only emits
    offerings whose zone/captype survive the pool's own constraints, so the
    per-option effective zone/captype sets are the singletons {o.zone} /
    {o.capacity_type}."""

    __slots__ = ("scales", "catalog", "nodepools", "options", "option_alloc",
                 "option_price", "option_zone", "option_captype",
                 "option_rank", "option_pool", "option_group", "zones",
                 "captypes", "groups", "pool_taints", "rest_mask_memo",
                 "compat_memo", "axes")

    def __init__(self, catalog: Sequence[InstanceType],
                 nodepools: Sequence[NodePool], axes: Tuple[str, ...],
                 scales: Optional[Mapping[str, float]] = None,
                 node_classes: Optional[Mapping[str, object]] = None):
        # strong refs keep the fingerprint's id()s stable for the cache's life
        self.catalog = list(catalog)
        self.nodepools = list(nodepools)
        self.axes = axes
        self.scales = DEFAULT_SCALES if scales is None else scales
        node_classes = node_classes or {}
        options = build_options(catalog, nodepools)
        self.options = options
        O, R = len(options), len(axes)
        self.option_alloc = np.zeros((O, R), np.float32)
        self.option_price = np.zeros(O, np.float32)
        self.zones = sorted({o.zone for o in options})
        zone_ids = {z: i for i, z in enumerate(self.zones)}
        self.captypes = sorted({o.capacity_type for o in options})
        cap_ids = {c: i for i, c in enumerate(self.captypes)}
        self.option_zone = np.zeros(O, np.int32)
        self.option_captype = np.zeros(O, np.int32)
        self.option_rank = np.zeros(O, np.int32)
        self.option_pool = np.zeros(O, np.int32)
        self.option_group = np.zeros(O, np.int32)
        self.pool_taints = [p.template.taints for p in nodepools]
        group_ids: Dict[tuple, int] = {}
        self.groups: List[Requirements] = []
        # per-(type, pool-kubelet) allocatable: a NodePool's kubelet config
        # (maxPods, podsPerCore, reserved/eviction overrides) reshapes pod
        # density and overhead for ITS options only — the reference rebuilds
        # its InstanceType list per kubelet hash
        # (/root/reference/pkg/providers/instancetype/instancetype.go:114-124)
        from ..catalog.instancetype import (apply_kubelet, apply_storage,
                                            root_volume_gib)
        kubelet_keys = [p.template.kubelet.key() for p in nodepools]
        ncs = node_classes or {}
        storage_gib = [root_volume_gib(ncs.get(p.template.node_class_ref))
                       for p in nodepools]
        alloc_by_type: Dict[tuple, list] = {}
        for j, opt in enumerate(options):
            it = catalog[opt.type_index]
            kk = kubelet_keys[opt.pool_index]
            sg = storage_gib[opt.pool_index]
            vec = alloc_by_type.get((opt.type_index, kk, sg))
            if vec is None:
                eff = apply_storage(it, sg)
                if kk is not None:
                    eff = apply_kubelet(
                        eff, nodepools[opt.pool_index].template.kubelet)
                vec = alloc_by_type[(opt.type_index, kk, sg)] = \
                    eff.allocatable.to_vector(axes, self.scales)
            self.option_alloc[j] = vec
            self.option_price[j] = opt.price
            self.option_zone[j] = zone_ids[opt.zone]
            self.option_captype[j] = cap_ids[opt.capacity_type]
            self.option_rank[j] = opt.weight_rank
            self.option_pool[j] = opt.pool_index
            gk = (opt.type_index, opt.pool_index)
            gi = group_ids.get(gk)
            if gi is None:
                gi = group_ids[gk] = len(self.groups)
                pool = nodepools[opt.pool_index]
                reqs = Requirements(it.requirements)
                reqs = reqs.union(Requirements.of(
                    Requirement(wk.NODEPOOL, IN, [opt.pool])))
                reqs = reqs.union(Requirements.from_labels(pool.template.labels))
                reqs.pop(wk.ZONE, None)          # vectorized per option
                reqs.pop(wk.CAPACITY_TYPE, None)
                self.groups.append(reqs)
            self.option_group[j] = gi
        # per-(branch-rest signature) group masks / per-(full constraint
        # signature) compat rows, shared by every batch against this catalog
        self.rest_mask_memo: Dict[tuple, np.ndarray] = {}
        self.compat_memo: Dict[tuple, np.ndarray] = {}

    # -- vectorized pod-constraint → option-mask lowering -----------------
    def compat_row(self, rep: Pod) -> np.ndarray:
        branches = rep.scheduling_requirements()
        sig = (tuple(tuple(sorted((k, repr(r)) for k, r in b.items()))
                     for b in branches),
               tuple(sorted((t.key, t.operator, t.value, t.effect)
                            for t in rep.tolerations)))
        row = self.compat_memo.get(sig)
        if row is not None:
            return row
        O = len(self.options)
        row = np.zeros(O, bool)
        for bi, branch in enumerate(branches):
            zone_req = branch.get(wk.ZONE)
            cap_req = branch.get(wk.CAPACITY_TYPE)
            rest_sig = sig[0][bi]
            gmask = self.rest_mask_memo.get(rest_sig)
            if gmask is None:
                rest = Requirements({k: r for k, r in branch.items()
                                     if k not in (wk.ZONE, wk.CAPACITY_TYPE)})
                # Fail closed on keys the group can't provide: a pod
                # requiring a user label schedules only if some NodePool
                # template carries it (reference scheduling.md label rules);
                # complemented ops (NotIn/DoesNotExist) tolerate absence via
                # Requirements.compatible.
                gmask = np.fromiter(
                    (rest.compatible(g) for g in self.groups),
                    bool, count=len(self.groups))
                self.rest_mask_memo[rest_sig] = gmask
            bmask = gmask[self.option_group]
            if zone_req is not None:
                zvec = np.fromiter((zone_req.has(z) for z in self.zones),
                                   bool, count=len(self.zones))
                bmask = bmask & zvec[self.option_zone]
            if cap_req is not None:
                cvec = np.fromiter((cap_req.has(c) for c in self.captypes),
                                   bool, count=len(self.captypes))
                bmask = bmask & cvec[self.option_captype]
            row |= bmask
        if rep.tolerations or any(self.pool_taints):
            tvec = np.fromiter(
                (tolerates_all(rep.tolerations, ts) for ts in self.pool_taints),
                bool, count=len(self.pool_taints))
            row = row & tvec[self.option_pool]
        self.compat_memo[sig] = row
        return row


# LRU of catalog sides. Keyed on instance-type identity PLUS the mutable
# content the tensorizer consumes (offering price/availability, allocatable
# resources, requirements, pool spec), so in-place mutations — ICE masking
# in tests, capacity/requirement edits, pool edits — can't serve stale
# tensors. The content hashes cost ~µs/type; repeated-solve hits come from
# upper layers memoizing their catalog lists.
_CATSIDE_CACHE: Dict[tuple, _CatalogSide] = {}
_CATSIDE_MAX = 8
import threading as _threading
_CATSIDE_LOCK = _threading.Lock()


def _catside_fingerprint(catalog: Sequence[InstanceType],
                         nodepools: Sequence[NodePool],
                         axes: Tuple[str, ...],
                         scales: Optional[Mapping[str, float]] = None,
                         node_classes: Optional[Mapping[str, object]] = None) -> tuple:
    # requirements are keyed by an int hash over EVERY Requirement field
    # (not Requirement.__hash__, which omits min_values) — full content
    # tuples would triple the cost of this hot-path fingerprint, and a
    # spurious miss from dict-order variation only costs a rebuild
    cat_sig = tuple((id(it),
                     tuple((o.zone, o.capacity_type, o.price, o.available)
                           for o in it.offerings),
                     tuple(sorted(it.allocatable.items())),
                     hash(tuple((k, r.complement, tuple(r.values),
                                 r.greater_than, r.less_than, r.min_values)
                                for k, r in it.requirements.items())))
                    for it in catalog)
    pool_sig = tuple(
        (p.name, p.weight,
         tuple(sorted(p.template.labels.items())),
         tuple(repr(t) for t in p.template.taints),
         tuple(sorted((k, repr(r)) for k, r in p.template.requirements.items())),
         p.template.kubelet.key())
        for p in nodepools)
    scale_sig = (None if scales is None else
                 tuple(sorted((k, float(v)) for k, v in scales.items())))
    # only the nodeclass content the columns consume: per-pool root volume
    from ..catalog.instancetype import root_volume_gib
    ncs = node_classes or {}
    storage_sig = tuple(root_volume_gib(ncs.get(p.template.node_class_ref))
                        for p in nodepools)
    return (cat_sig, pool_sig, axes, scale_sig, storage_sig)


def catalog_side(catalog: Sequence[InstanceType],
                 nodepools: Sequence[NodePool],
                 axes: Tuple[str, ...] = DEFAULT_AXES,
                 scales: Optional[Mapping[str, float]] = None,
                 node_classes: Optional[Mapping[str, object]] = None) -> _CatalogSide:
    key = _catside_fingerprint(catalog, nodepools, axes, scales, node_classes)
    side = _CATSIDE_CACHE.get(key)
    if side is None:
        side = _CatalogSide(catalog, nodepools, axes, scales, node_classes)
    with _CATSIDE_LOCK:
        # atomic size-capped LRU re-insert (concurrent misses would
        # otherwise overshoot the cap)
        _CATSIDE_CACHE.pop(key, None)
        while len(_CATSIDE_CACHE) >= _CATSIDE_MAX:
            _CATSIDE_CACHE.pop(next(iter(_CATSIDE_CACHE)), None)
        _CATSIDE_CACHE[key] = side
    return side


def tensorize(pods: Sequence[Pod], catalog: Sequence[InstanceType],
              nodepools: Sequence[NodePool],
              axes: Tuple[str, ...] = DEFAULT_AXES,
              node_classes: Optional[Mapping[str, object]] = None) -> Problem:
    """Lower a scheduling round to dense arrays."""
    # pod equivalence classes, grouped in numpy over interned class ids —
    # one attribute read per pod instead of a dict-build round trip; class
    # order stays first-appearance (the old dict semantics) so tie-breaks
    # and decode order are unchanged
    n = len(pods)
    if len(_CLASS_IDS) >= _CLASS_IDS_MAX:   # bound the intern table; never
        _CLASS_IDS.clear()                  # resets mid-call (id collisions
        _CLASS_GEN[0] += 1                  # would merge distinct classes)
    if n:
        ids = np.fromiter((_class_id(p) for p in pods), np.int64, count=n)
        uniq, first, inverse = np.unique(ids, return_index=True,
                                         return_inverse=True)
        appear = np.argsort(first, kind="stable")
        rank = np.empty(len(uniq), np.int64)
        rank[appear] = np.arange(len(uniq))
        ci_of_pod = rank[inverse]
        reps = [pods[first[o]] for o in appear]
        by_class = np.argsort(ci_of_pod, kind="stable")
        counts = np.bincount(ci_of_pod, minlength=len(uniq))
        members = np.split(by_class, np.cumsum(counts)[:-1])
    else:  # np.split of an empty vector would yield ONE empty group
        reps, members = [], []
        counts = np.zeros(0, np.int64)

    # requested resources outside the configured axes become extra axes, so
    # the packer accounts for them exactly instead of silently ignoring
    # them (the reference compares EVERY requested resource,
    # /root/reference/pkg/cloudprovider/cloudprovider.go:264 resources.Fits
    # — a pod asking for example.com/fpga must land only on types
    # advertising it, or go unschedulable). Scanning class reps, not pods:
    # identical requests are part of the class key.
    extra = sorted({k for rep in reps for k, v in rep.requests.items()
                    if v and k not in axes})
    scales = DEFAULT_SCALES
    if extra:
        axes = tuple(axes) + tuple(extra)
        # extra axes with byte-sized magnitudes must scale down or they
        # overflow the kernels' int32 lowering (2^31 ≈ 2GiB): hugepages-*
        # are bytes by the k8s spec and get the MEMORY convention (MiB);
        # anything else scales by the SMALLEST power of two that brings its
        # max observed quantity under 2^30 — count-valued resources with
        # large node capacity keep (most of) their granularity instead of
        # being flattened 2^20x (request ceil(1/2^20)=1 would collapse a
        # node's capacity to alloc/2^20 and over-provision wildly)
        scales = dict(DEFAULT_SCALES)
        for k in extra:
            if k.startswith("hugepages-"):
                scales[k] = float(2**20)
                continue
            big = max((float(rep.requests.get(k, 0)) for rep in reps),
                      default=0.0)
            big = max(big, max((float(it.allocatable.get(k, 0))
                                for it in catalog), default=0.0))
            if big >= 2.0**30:
                scales[k] = 2.0 ** math.ceil(math.log2(big) - 30)

    side = catalog_side(catalog, nodepools, axes, scales, node_classes)
    O, R = len(side.options), len(axes)

    C = len(reps)
    class_requests = np.zeros((C, R), np.float32)
    class_compat = np.zeros((C, O), bool)
    for ci, rep in enumerate(reps):
        req = ResourceList(rep.requests)
        req[PODS] = req.get(PODS, 0) + 1  # every pod consumes one pod slot
        class_requests[ci] = req.to_vector(axes, scales, round_up=True)
        class_compat[ci] = side.compat_row(rep)

    # gang columns: class → gang index in first-appearance order (the same
    # deterministic order classes themselves use).  The gang spec rides on
    # the class key, so one gang's heterogeneous members land in distinct
    # classes that all point at one GangInfo row.
    class_gang = None
    gangs: List[GangInfo] = []
    if any(rep.gang_name for rep in reps):
        class_gang = np.full(C, -1, np.int32)
        gang_of: Dict[str, int] = {}
        for ci, rep in enumerate(reps):
            if not rep.gang_name:
                continue
            gi = gang_of.get(rep.gang_name)
            if gi is None:
                gi = gang_of[rep.gang_name] = len(gangs)
                gangs.append(GangInfo(name=rep.gang_name,
                                      size=int(rep.gang_size),
                                      tier=int(rep.gang_tier),
                                      topology=rep.gang_topology or "zone"))
            class_gang[ci] = gi

    return Problem(
        axes=axes,
        class_requests=class_requests,
        class_counts=counts.astype(np.int32),
        class_compat=class_compat,
        class_members=members,
        class_node_cap=np.asarray([_node_cap(rep) for rep in reps], np.int32),
        options=side.options,
        option_alloc=side.option_alloc,
        option_price=side.option_price,
        option_rank=side.option_rank,
        option_zone=side.option_zone,
        option_captype=side.option_captype,
        zones=side.zones,
        pods=list(pods),
        scales=scales,
        class_gang=class_gang,
        gangs=gangs,
    )


def arena_fingerprint(candidates: Sequence, nodes: Sequence[Node],
                      catalog_key: tuple) -> tuple:
    """Cluster-state fingerprint for `SimulationArena` reuse: everything the
    arena's tensors consume — candidate identity/order/price/pod multisets,
    every live node's column inputs (allocatable, labels, taints, zone,
    bound pods), and the catalog side's content key.  Pod identity is
    (id, name): pod specs are immutable once admitted (see `_class_key`'s
    cache), so object identity covers spec content, and the cluster holds
    strong refs for the pods' cluster lifetime so ids can't be recycled
    while they still matter.  PDBs are deliberately NOT part of the key:
    evictability is recomputed on the host every tick, never baked into
    the arena's arrays."""
    node_sig = tuple(
        (n.name, n.zone, float(n.price), n.marked_for_deletion,
         tuple(sorted(n.allocatable.items())),
         tuple(sorted(n.labels.items())),
         tuple(repr(t) for t in n.taints),
         tuple((id(p), p.name) for p in n.pods))
        for n in nodes)
    cand_sig = tuple((c.name, float(c.price),
                      tuple((id(p), p.name) for p in c.reschedulable))
                     for c in candidates)
    return (cand_sig, node_sig, catalog_key)


@dataclass
class _ArenaSide:
    """One tensorized face of the arena: the lowered+tensorized problem over
    the union of all candidate pods, every live node as a pre-opened column,
    and the per-candidate bookkeeping the sweeps mask with."""
    problem: Problem
    node_list: List[Node]
    alloc: np.ndarray           # E×R float32
    used: np.ndarray            # E×R float32
    compat: np.ndarray          # C×E bool
    cand_counts: np.ndarray     # N×C int32 — candidate i's pod class counts
    cand_cols: np.ndarray       # N int64 — candidate i's column index (-1: none)


class SimulationArena:
    """One tensorization of the cluster serving a WHOLE consolidation sweep.

    The sequential path re-runs `lower_pods` + `tensorize` +
    `tensorize_nodes` per probe (log₂N prefix probes + up to 2N single-node
    screens per tick).  The arena does that lowering ONCE over the union of
    all candidate pods and ALL live nodes, then expresses each probe as
    pure masking: a per-probe class-count vector (which candidates' pods to
    reschedule), a per-probe existing-column mask (which candidate nodes
    are gone), and a per-probe price cap (the strictly-cheaper replacement
    rule) — exactly the batch axes `solve_classpack_sweep` consumes, so a
    whole prefix family or single-node screen is 1-2 device calls.

    Two faces, matching the sequential simulate's two catalog shapes:
    `delete` (empty catalog — pods must fit on survivors alone) and
    `replace` (full catalog, price-masked per candidate).  Both are built
    lazily: a tick that finds a multi-node delete never pays for the
    replace face.

    Exactness: delete-face verdicts match the sequential per-probe oracle
    bit-for-bit on topology-free pods — same class arrays (zero-count
    classes are exact scan no-ops), same survivor columns (sequential
    probes keep non-probed candidates as survivors, so columns cover ALL
    live nodes and probes mask their own), same FFD order (catalog-free
    norm).  Two documented approximations remain: (1) constraint lowering
    runs once with every candidate excluded, where the sequential path
    excludes only the probed subset — spread/affinity rewrites can differ;
    (2) the replace face FFD-orders classes under the FULL catalog's norm
    while the sequential screen tensorizes a price-filtered catalog.  Both
    are safe by construction: the sweep only *screens*, and every chosen
    action is re-validated by the sequential fully-decoded `simulate`
    (decode-audit included) before execution."""

    def __init__(self, candidates: Sequence, cluster, catalog,
                 nodepools: Sequence[NodePool], node_classes=None):
        self.candidates = list(candidates)
        self._cluster = cluster
        self._catalog = list(catalog)
        self._nodepools = list(nodepools)
        self._node_classes = node_classes
        self._names = [c.name for c in self.candidates]
        self.prices = np.asarray([c.price for c in self.candidates],
                                 np.float32)
        pods = []
        self._slices: List[Tuple[int, int]] = []
        for c in self.candidates:
            s = len(pods)
            pods.extend(c.reschedulable)
            self._slices.append((s, len(pods)))
        self._pods = pods
        self._delete: Optional[_ArenaSide] = None
        self._replace: Optional[_ArenaSide] = None
        # staleness guard (the lazy-face hazard): faces tensorized from an
        # earlier cluster state must never serve a sweep after ANY cluster
        # mutation — a bind between sweeps changes used rows, a taint edit
        # changes compat.  The cluster's mutation_epoch is bumped by every
        # mutator, so comparing it is an O(1) validity check.
        self._built_epoch = getattr(cluster, "mutation_epoch", None)

    def _check_stale(self):
        epoch = getattr(self._cluster, "mutation_epoch", None)
        if epoch != self._built_epoch:
            self._delete = None
            self._replace = None
            self._built_epoch = epoch

    # ---- face construction ------------------------------------------------
    def _build_side(self, catalog) -> _ArenaSide:
        from .constraints import (LEVEL_REQUIRED_ONLY, lower_pods,
                                  make_zone_feasibility)
        nodes = list(self._cluster.nodes.values())
        excl = self._names
        excl_set = set(excl)
        zones = sorted({o.zone for it in catalog for o in it.offerings
                        if o.available}
                       | {n.zone for n in nodes
                          if n.name not in excl_set and n.zone})
        lowered = lower_pods(self._pods, nodes=nodes, option_zones=zones,
                             exclude_nodes=excl, level=LEVEL_REQUIRED_ONLY,
                             zone_feasible=make_zone_feasibility(
                                 catalog, nodes, exclude_nodes=excl))
        problem = tensorize(lowered, catalog, self._nodepools,
                            node_classes=self._node_classes)
        # ALL live nodes as columns — each probe masks its own subset, the
        # rest act as survivors exactly as in the sequential per-probe
        # tensorize_nodes(exclude=subset).  A warm ClusterArena serves the
        # same arrays bit-identically from its slab; gather() returning
        # None (extra axes, untracked node) falls back to the full path.
        cluster_arena = getattr(self._cluster, "arena", None)
        gathered = None
        if cluster_arena is not None:
            gathered = cluster_arena.gather(
                problem.class_reps, problem.axes, exclude=(),
                scales=problem.scales)
        if gathered is None:
            gathered = self._cluster.tensorize_nodes(
                problem.class_reps, problem.axes, exclude=(),
                scales=problem.scales)
        node_list, alloc, used, compat = gathered
        col_of = {n.name: i for i, n in enumerate(node_list)}
        C = problem.num_classes
        cid = np.zeros(len(lowered), np.int64)
        for ci, m in enumerate(problem.class_members):
            cid[np.asarray(m, np.int64)] = ci
        counts = np.zeros((len(self.candidates), C), np.int32)
        for i, (s, e) in enumerate(self._slices):
            if e > s:
                counts[i] = np.bincount(cid[s:e], minlength=C)
        cols = np.asarray([col_of.get(name, -1) for name in self._names],
                          np.int64)
        return _ArenaSide(problem, node_list, alloc, used, compat,
                          counts, cols)

    @property
    def delete_side(self) -> _ArenaSide:
        self._check_stale()
        if self._delete is None:
            self._delete = self._build_side([])
        return self._delete

    @property
    def replace_side(self) -> _ArenaSide:
        self._check_stale()
        if self._replace is None:
            self._replace = self._build_side(self._catalog)
        return self._replace

    # ---- the two sweeps ---------------------------------------------------
    def _sweep(self, side: _ArenaSide, counts_b: np.ndarray,
               mask: Optional[np.ndarray], caps: Optional[np.ndarray],
               max_nodes: int = 8192):
        from .classpack import solve_classpack_sweep
        E = len(side.node_list)
        return solve_classpack_sweep(
            side.problem, counts_b,
            existing_alloc=side.alloc if E else None,
            existing_used=side.used if E else None,
            existing_compat=side.compat if E else None,
            exist_mask_b=mask if E else None,
            price_cap_b=caps,
            max_nodes=max_nodes)

    def sweep_prefixes(self):
        """All N candidate prefixes as one batched delete probe: row k-1
        answers `simulate(cands[:k], allow_new=False, decode=False)` —
        feasible ⇔ unschedulable == 0 and new_nodes == 0."""
        return self.sweep_prefix_subset(range(1, len(self.candidates) + 1))

    def sweep_prefix_subset(self, ks):
        """Delete probes for the given prefix lengths only (1-based): row r
        answers `simulate(cands[:ks[r]], allow_new=False, decode=False)`.

        The consolidation search asks this for the mids its binary search
        can actually reach (~log₂N prefixes per round) instead of all N —
        the batched kernel's cost is near-linear in rows on hosts without
        wide SIMD over the batch axis, so probing the reachable frontier
        is what keeps the sweep ahead of the sequential baseline."""
        side = self.delete_side
        ks = [int(k) for k in ks]
        C = side.problem.num_classes
        if ks:
            cum = np.cumsum(side.cand_counts, axis=0, dtype=np.int32)
            counts_b = np.stack([cum[k - 1] for k in ks])
        else:
            counts_b = np.zeros((0, C), np.int32)
        E = len(side.node_list)
        mask = np.ones((len(ks), E), bool)
        for r, k in enumerate(ks):
            for j in side.cand_cols[:k]:
                if j >= 0:
                    mask[r, j] = False      # prefix k loses its candidates
        # the delete face has NO launch options — no slot beyond the E
        # pre-opened columns can ever open, so the slot array stops at the
        # E bucket instead of the pods+nodes bucket (the vmapped scan pays
        # B×K per step; at 500 nodes this is the difference between a
        # 512-slot and an 8192-slot program)
        return self._sweep(side, counts_b, mask, None,
                           max_nodes=pad_to(E + 1, (256, 512, 1024, 2048,
                                                    4096, 8192)))

    def sweep_singles(self):
        """All N single-candidate replacement screens in one batched call:
        row i answers `simulate([c_i], allow_new=True,
        max_total_price=c_i.price, decode=False)` with the price cap
        applied as an option mask instead of a catalog rebuild."""
        side = self.replace_side
        N = len(self.candidates)
        E = len(side.node_list)
        mask = np.ones((N, E), bool)
        for i, j in enumerate(side.cand_cols):
            if j >= 0:
                mask[i, j] = False
        return self._sweep(side, side.cand_counts, mask, self.prices)


def pad_to(n: int, buckets: Sequence[int] = (256, 1024, 4096, 16384, 32768,
                                             53248, 65536)) -> int:
    """Bucketed padding to bound jit recompiles (SURVEY.md §7 hard part iv).

    The 32k/52k steps exist because padded size is TRANSFER: the decode
    ships one int16 per padded pod row, and on tunneled dev TPUs every
    byte of result payload is latency — a 50k batch padded to 64k would
    pay a quarter more fetch for nothing."""
    for b in buckets:
        if n <= b:
            return b
    return int(2 ** math.ceil(math.log2(max(n, 1))))
