"""Gilmore-Gomory configuration-LP lower bound (offline certification).

The strongest tractable bound family for the packing problem: a
set-covering LP over *node configurations* (integral fills of one node)
with exact MILP pricing per launch option, warm-started from an actual
packing plan.  Farley's bound makes every iteration's value a certified
lower bound — convergence is not required for validity:

    LB = z_master / max_j (pricing_value_j / price_j)

Compute cost is minutes on bench-scale instances (hundreds of pricing
MILPs), so this runs OFFLINE — `class_lp_bound` (ops/lpbound.py) remains
the bench's in-line certificate.

Measured on the bench's 10k-mixed instance (docs/design-relaxation.md):
the configuration LP converges to ~645.6 vs the plain class-LP's 642.91
(+0.4%), while the greedy plan costs 704.12 — establishing that the
residual certified gap is λ-integrality (how many nodes of each
configuration), which no LP in this family can close, not a weakness
specific to the class-granular relaxation.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np

from . import lpbound


def gg_bound(problem, iters: int = 20, time_limit_s: float = 600.0,
             pricing_time_limit_s: float = 2.0,
             warm_plan=None, log=None,
             device: bool = False) -> Tuple[float, dict]:
    """Certified lower bound via column generation with Farley's rule.

    Returns (bound, info).  The bound is always valid: it starts at the
    exact class-LP optimum and only improves when an iteration's Farley
    value (or the converged master) exceeds it.  `warm_plan` may be a
    PackingResult whose node fills seed the column pool.

    With `device=True` the per-option fractional pricing screens — the
    bulk of the serial HiGHS calls — run as ONE vmapped PDHG batch
    (ops/lpsolve.py), and the screen threshold uses the dual-certified
    upper bound, which is valid for Farley regardless of PDHG
    convergence (see `lpsolve.certified_upper_bound`).
    """
    best, _state, info = _colgen(problem, iters, time_limit_s,
                                 pricing_time_limit_s, warm_plan, log,
                                 device=device)
    return best, info


def integral_bracket(problem, iters: int = 20, time_limit_s: float = 600.0,
                     pricing_time_limit_s: float = 2.0,
                     master_time_limit_s: float = 120.0,
                     warm_plan=None, log=None,
                     device: bool = False) -> Tuple[float, float, dict]:
    """Bracket the EXACT integral packing optimum: (lb, ub, info).

    lb is the certified configuration-LP/Farley bound from column
    generation; ub is the cost of a genuine integral packing — the
    restricted master re-solved as a MILP (integer node counts per
    generated configuration, coverage ≥ demand).  The true integral
    optimum lies in [lb, ub], so ub/lb bounds how loose the LP
    certificate can possibly be, and plan_cost/ub lower-bounds how much
    of a plan's measured overhead is real packer waste rather than bound
    slack.  This settles the question the bench's x-ratios alone cannot
    (docs/performance.md): which side of the gap owns the residual.

    Runs OFFLINE (minutes): column generation plus one MILP over the
    generated column pool.  Singleton columns keep the MILP feasible
    regardless of convergence, so (lb, ub) is always a valid bracket.
    """
    best, state, info = _colgen(problem, iters, time_limit_s,
                                pricing_time_limit_s, warm_plan, log,
                                device=device)
    if state is None:
        return best, float("inf"), info
    ub, lam = _integral_master(state, master_time_limit_s)
    info["integral_ub"] = ub
    if lam is not None:
        info["integral_columns_used"] = int((lam > 0.5).sum())
    return best, ub, info


def _integral_master(state, time_limit_s: float):
    """Solve the restricted master with integer multiplicities.  Every
    column is an integral single-node fill, so any feasible λ IS a
    concrete fleet whose cost upper-bounds the integral optimum."""
    from scipy import sparse
    from scipy.optimize import Bounds, LinearConstraint, milp
    import numpy as np
    cols, cnt = state["cols"], state["cnt"]
    cost = np.array([c for c, _ in cols])
    A = sparse.csr_matrix(np.stack([a for _, a in cols], axis=1))
    res = milp(cost,
               constraints=[LinearConstraint(A, cnt, np.inf)],
               integrality=np.ones(len(cols)),
               bounds=Bounds(0, np.inf),
               options={"time_limit": float(time_limit_s)})
    if res.x is None:  # pragma: no cover — singletons keep this feasible
        return float("inf"), None
    return float(res.fun), np.round(res.x)


def _device_screen(jobs, duals, req, alloc):
    """Batched PDHG pre-screen: one vmapped solve over every option's
    fractional pricing LP, then a dual-certified upper bound per option.

    The certified bound (weak duality from the harvested λ ≥ 0) OVER-
    estimates the pricing optimum even when PDHG did not converge, which
    is exactly the direction both the screen and Farley's `worst`
    quotient need — a loose bound only makes the screen conservative,
    never invalid.  Returns {option j: certified ub}."""
    from . import lpsolve
    insts = [lpsolve.LPInstance(c=-duals[idx], A_ub=req[idx].T,
                                b_ub=alloc[j], upper=ub,
                                warm_key=f"gg:pricing:{j}")
             for j, idx, ub in jobs]
    sols = lpsolve.solve_lp_batch(insts)
    return {j: lpsolve.certified_upper_bound(duals[idx], req[idx].T,
                                             alloc[j], ub, sol.lam)
            for (j, idx, ub), sol in zip(jobs, sols)}


def _colgen(problem, iters, time_limit_s, pricing_time_limit_s,
            warm_plan, log, device=False):
    """Shared column-generation core.  Returns (best_lb, state, info)
    where state carries the generated column pool for the integral
    master (None when scipy is absent or the instance is empty)."""
    try:
        from scipy import sparse
        from scipy.optimize import Bounds, LinearConstraint, linprog, milp
    except ImportError:  # pragma: no cover
        return lpbound.dual_feasible_bound(problem), None, {"method": "dual"}

    base = lpbound.class_lp_bound(problem)
    if base is None:
        base = lpbound.dual_feasible_bound(problem)
    info = {"method": "gg", "base_lp": base, "iters": 0, "converged": False,
            "pricing_screen": "device" if device else "highs"}
    if problem.num_options == 0 or problem.num_classes == 0:
        return 0.0, None, info

    fit = lpbound._fit_compat(problem)
    feas = fit.any(axis=1)
    req = problem.class_requests[feas].astype(np.float64)
    cnt = problem.class_counts[feas].astype(np.float64)
    compat = fit[feas]
    alloc, price, compat = lpbound._dedup_options(
        problem.option_alloc.astype(np.float64),
        problem.option_price.astype(np.float64), compat)
    C, R = req.shape
    O = alloc.shape[0]
    if C == 0 or O == 0:
        return 0.0, None, info

    reqpos = req > 0
    safe_req = np.where(reqpos, req, 1.0)
    m = np.where(reqpos[:, None, :],
                 alloc[None, :, :] // safe_req[:, None, :], np.inf).min(axis=2)
    m = np.where(compat, m, 0)

    cols: list = []
    colset: set = set()

    def add_col(j: int, a: np.ndarray) -> bool:
        key = (j, a.tobytes())
        if key in colset:
            return False
        colset.add(key)
        cols.append((float(price[j]), a.astype(np.float64)))
        return True

    # singleton columns guarantee master feasibility
    for c in range(C):
        j = int(np.argmin(np.where(m[c] > 0, price, np.inf)))
        if m[c, j] > 0:
            a = np.zeros(C)
            a[c] = min(m[c, j], cnt[c])
            add_col(j, a)

    if warm_plan is not None:
        _seed_from_plan(problem, warm_plan, feas, fit, add_col)

    def solve_master():
        cost = np.array([c for c, _ in cols])
        A = sparse.csr_matrix(np.stack([a for _, a in cols], axis=1))
        res = linprog(cost, A_ub=-A, b_ub=-cnt, bounds=(0, None),
                      method="highs")
        if not res.success:  # pragma: no cover
            return None, None
        return res.fun, -res.ineqlin.marginals

    best = float(base)
    t0 = time.perf_counter()
    # dual-threshold slack: the pricing step ignores classes whose dual is
    # ≤ 1e-9 (and options with no such class at all), so each pricing value
    # can under-estimate the true pricing optimum by at most
    # 1e-9 · Σ_c min(m, cnt) pods' worth of omitted dual mass.  Farley
    # divides by the WORST pricing ratio, so every ratio must be an
    # over-estimate: add this worst-case omitted contribution to every
    # pricing value (advisor r4; the correction is ~1e-5 on bench scales,
    # documented tolerance rather than a silent epsilon).
    eps_omit = 1e-9 * float(np.minimum(np.where(m > 0, m, 0),
                                       cnt[:, None]).sum(axis=0).max())
    for it in range(iters):
        z, duals = solve_master()
        if z is None:
            break
        worst = eps_omit / float(price.min())   # covers fully-skipped options
        added = 0
        farley_valid = True   # every option's pricing ratio accounted for
        proven = True         # every option priced out or MILP-optimal
        jobs = []
        for j in range(O):
            mask = compat[:, j] & (m[:, j] > 0) & (duals > 1e-9)
            if mask.any():
                idx = np.nonzero(mask)[0]
                jobs.append((j, idx, np.minimum(m[idx, j], cnt[idx])))
        # one vmapped PDHG dispatch replaces the serial HiGHS screens
        dev_ub = _device_screen(jobs, duals, req, alloc) if device else None
        for j, idx, ub in jobs:
            A_p = sparse.csr_matrix(req[idx].T)
            # fractional pricing bound filters options that cannot violate
            if dev_ub is not None:
                lp_ub = dev_ub[j]   # certified even if PDHG hit its cap
            else:
                lp = linprog(-duals[idx], A_ub=A_p, b_ub=alloc[j],
                             bounds=np.stack([np.zeros(len(idx)), ub],
                                             axis=1),
                             method="highs")
                if not lp.success:
                    # Farley needs EVERY option's ratio; an unpriced option
                    # invalidates this iteration's bound (not the run)
                    farley_valid = False
                    proven = False
                    continue
                lp_ub = -lp.fun
            if lp_ub <= price[j] * (1 + 1e-9):
                continue     # proven non-violating by the relaxation
            res = milp(-duals[idx],
                       constraints=[LinearConstraint(A_p, -np.inf, alloc[j])],
                       integrality=np.ones(len(idx)), bounds=Bounds(0, ub),
                       options={"time_limit": float(pricing_time_limit_s)})
            if res.status != 0 or res.x is None:
                # the screen bound safely over-estimates the pricing
                # optimum — Farley stays valid, but the master is NOT
                # proven optimal
                worst = max(worst, (lp_ub + eps_omit) / price[j])
                proven = False
                continue
            val = -res.fun
            worst = max(worst, (val + eps_omit) / price[j])
            if val > price[j] * (1 + 1e-7):
                a = np.zeros(C)
                a[idx] = np.round(res.x)
                added += add_col(j, a)
        # denominator floor covers options skipped by the screens: the
        # fractional screen admits true ratios up to 1+1e-9+eps/price, and
        # the MILP path only adds columns above the 1e-7 add-threshold, so
        # tolerance-scale improving columns can survive even at
        # "convergence" — both the Farley quotient AND the converged master
        # value must be discounted by this floor (review r5)
        floor = 1.0 + 1e-7 + eps_omit / float(price.min())
        if farley_valid:
            best = max(best, z / max(worst, floor))   # Farley
        info["iters"] = it + 1
        if log:
            log(f"gg iter {it}: master={z:.2f} worst={worst:.4f} "
                f"best_lb={best:.2f} cols={len(cols)}")
        if added == 0:
            if proven:
                # converged restricted master ≈ GG LP up to screen
                # tolerances; z/floor is the certified value
                best = max(best, z / floor)
                info["converged"] = True
            break
        if time.perf_counter() - t0 > time_limit_s:
            break
    info["columns"] = len(cols)
    state = {"cols": cols, "cnt": cnt, "price": price, "req": req,
             "compat": compat, "alloc": alloc}
    return float(best), state, info


def _seed_from_plan(problem, plan, feas, fit, add_col) -> None:
    """Seed columns from a PackingResult's actual node fills."""
    cid_map = -np.ones(problem.num_classes, np.int64)
    cid_map[np.nonzero(feas)[0]] = np.arange(int(feas.sum()))
    keys: dict = {}
    dedup_of = {}
    comp = fit[feas]
    for j in range(problem.num_options):
        k = (problem.option_alloc[j].astype(np.float64).tobytes(),
             float(problem.option_price[j]), comp[:, j].tobytes())
        if k not in keys:
            keys[k] = len(keys)
        dedup_of[j] = keys[k]
    class_of_pod = {}
    for ci, mem in enumerate(problem.class_members):
        for p in np.asarray(mem):
            class_of_pod[int(p)] = ci
    opt_index = {id(o): j for j, o in enumerate(problem.options)}
    C = int(feas.sum())
    for nd in plan.nodes:
        a = np.zeros(C)
        ok = True
        for p in nd.pod_indices:
            ci = class_of_pod.get(p)
            cc = cid_map[ci] if ci is not None else -1
            if cc < 0:
                ok = False
                break
            a[cc] += 1
        j = opt_index.get(id(nd.option))
        if ok and j is not None:
            add_col(dedup_of[j], a)
