"""LP-guided option mix: close the packer's option-choice gap.

Round-4's open question — is the measured ~9% cost-vs-bound residual on
mixed shapes bound looseness or packer waste? — was settled by
benchmarks/optimality_probe.py and ops/ggbound.py `integral_bracket`:
on the bench's 10k-mixed instance the integral optimum lies in
[642.91, 654.52] while the greedy plan costs 704.12, and the plan's
nodes are ~100% full on their bottleneck resource.  The waste is
**option-mix**, not fragmentation: each class independently buys the
type cheapest for itself, stranding the non-bottleneck resource that a
complementary class (cpu-heavy with mem-heavy) could have used.  The
reference's FFD has the same blind spot by construction
(/root/reference/designs/bin-packing.md:16-43 packs pod-at-a-time with
a per-pod type preference).

The fix: solve the class-granular LP

    min  Σ_j price_j · n_j
    s.t. Σ_c req[c,r]·x[c,j] ≤ alloc[j,r]·n_j   ∀ j,r
         Σ_j x[c,j] = cnt_c                      ∀ c,  x, n ≥ 0

EXACTLY, but fast: restricted to a small per-class support of candidate
options, then priced against the full catalog by LP reduced costs and
re-solved until no violating pair remains — textbook column generation
whose terminal solution is optimal for the FULL LP.  The support starts
at each class's cheapest sole-tenancy options, so one or two pricing
rounds settle it; the restricted LPs are ~10³ variables and solve in
tens of milliseconds (first-order methods were tried first and stall at
1.03-1.04× — see docs/design-lpguide.md).

The guide then *shapes* the existing scan kernel instead of replacing
it: each class's LP allocation is floored into **bulk rows** pinned to
their option's dedup group (one-hot group compat) plus one **remainder
row** with the class's full compat.  The unchanged first-fit kernel
packs bulk rows into the LP's option mix and lets remainders fill the
cross-option partial tails — integrality lands exactly where the greedy
was already good, and the option mix lands where the LP is provably
better.  Decode, audits, and caps are the same code path as every other
solve.  The mix is content-cached: a provisioner re-solving an
unchanged pending set (tick loops, capacity retries, bench iterations)
pays the LP once.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Tuple

import numpy as np

from .tensorize import Problem

_BIG = np.int32(2**30)

# content-keyed mix cache: (classes ⊕ catalog fingerprint) → guided rows.
# Same discipline as classpack's catalog/pod-side caches: check-then-insert
# under one lock, bounded size.
_MIX_CACHE: dict = {}
_MIX_CACHE_MAX = 16
_MIX_LOCK = threading.Lock()


def _feasible_mask(problem: Problem) -> np.ndarray:
    """class_compat ∧ fits-one-node ∧ launchable ∧ best-pool-rank — the
    same preselection the pack kernel applies, so the LP optimizes over
    exactly the kernel's action space."""
    req = problem.class_requests.astype(np.float64)
    alloc = problem.option_alloc.astype(np.float64)
    reqpos = req > 0
    safe = np.where(reqpos, req, 1.0)
    m = np.where(reqpos[:, None, :], alloc[None, :, :] // safe[:, None, :],
                 np.inf).min(axis=2)
    ok = problem.class_compat & (m >= 1.0) & \
        np.isfinite(problem.option_price)
    rank = (problem.option_rank if problem.option_rank is not None
            else np.zeros(problem.num_options, np.int32))
    best = np.min(np.where(ok, rank[None, :], _BIG), axis=1)
    return ok & (rank[None, :] == best[:, None])


def _dedup_with_inverse(alloc: np.ndarray, price: np.ndarray,
                        compat: np.ndarray):
    """Collapse options identical in (alloc, price, compat column); returns
    (alloc', price', compat', group_of: O→O' inverse map).  Zone/subnet
    copies of one offering are LP-indistinguishable, and their identical
    compat columns mean a group mask is exactly the member mask."""
    O = alloc.shape[0]
    keys: dict = {}
    group_of = np.empty(O, np.int64)
    keep = []
    for j in range(O):
        k = (alloc[j].tobytes(), float(price[j]), compat[:, j].tobytes())
        g = keys.get(k)
        if g is None:
            g = keys[k] = len(keep)
            keep.append(j)
        group_of[j] = g
    keep = np.asarray(keep, np.int64)
    return alloc[keep], price[keep], compat[:, keep], group_of


def exact_lp_mix(req: np.ndarray, cnt: np.ndarray, compat: np.ndarray,
                 alloc: np.ndarray, price: np.ndarray,
                 pricing_rounds: int = 3, add_per_round: int = 16,
                 tol: float = 1e-6):
    """Class-LP optimum by option-granular column generation.  Returns
    (x C×O, objective, info) or (None, None, info) when scipy is
    unavailable or the LP fails.

    Seeding is the part that makes this fast: for a small family of
    resource weightings w (each axis alone, the uniform mix, pairwise
    mixes, and the bottleneck max), every class contributes its cheapest
    option under cost_w = price_j·Σ_r w_r·req_cr/alloc_jr.  That yields
    a few dozen ratio-diverse options whose restricted LP — ALL
    compatible (class, option) pairs for seeded options — lands on the
    full-LP optimum immediately on every bench shape measured (the
    ratio-matched option family the LP blends is exactly what the
    weighting sweep enumerates).  Safety net for adversarial shapes:
    price the excluded options with the master's duals, admit the worst
    `add_per_round`, and stop as soon as the objective stops improving —
    duals of these degenerate masters routinely flag options that cannot
    actually improve the optimum, so improvement (not rc-cleanliness) is
    the stopping criterion.  Certified bounds stay lpbound's job."""
    try:
        from scipy import sparse
        from scipy.optimize import linprog
    except ImportError:  # pragma: no cover — scipy is baked into the image
        return None, None, {"method": "none"}

    C, R = req.shape
    O = alloc.shape[0]
    reqf = req.astype(np.float64)
    allocf = alloc.astype(np.float64)
    pricef = price.astype(np.float64)
    inv_alloc = np.where(allocf > 0, 1.0 / np.maximum(allocf, 1e-12), 0.0)

    # ---- multi-weight seeding ----
    weights = [np.eye(R)[r] for r in range(R)]
    weights.append(np.ones(R) / R)
    for a in range(R):
        for b in range(a + 1, R):
            w = np.zeros(R)
            w[a] = w[b] = 0.5
            weights.append(w)
    S = np.zeros(O, bool)
    for w in weights:
        cost_w = pricef[None, :] * (reqf @ (inv_alloc * w[None, :]).T)
        cost_w = np.where(compat, cost_w, np.inf)
        S[np.unique(np.argmin(cost_w, axis=1))] = True
    ppm = np.where(compat, pricef[None, :] *
                   np.max(reqf[:, None, :] * inv_alloc[None, :, :], axis=2),
                   np.inf)
    S[np.unique(np.argmin(ppm, axis=1))] = True

    info = {"method": "colgen-lp", "rounds": 0, "proven": False}
    x_full = None
    z = None
    for rnd in range(pricing_rounds):
        supp = compat & S[None, :]
        pc, pj = np.nonzero(supp)
        P = len(pc)
        nvars = P + O
        rows, cols, vals = [], [], []
        for r in range(R):
            nz = reqf[pc, r] != 0
            rows.append(pj[nz] * R + r)
            cols.append(np.nonzero(nz)[0])
            vals.append(reqf[pc[nz], r])
        rows.append(np.repeat(np.arange(O), R) * R + np.tile(np.arange(R), O))
        cols.append(np.repeat(np.arange(O) + P, R))
        vals.append(-allocf.reshape(-1))
        A_ub = sparse.csr_matrix(
            (np.concatenate(vals),
             (np.concatenate(rows), np.concatenate(cols))),
            shape=(O * R, nvars))
        A_eq = sparse.csr_matrix((np.ones(P), (pc, np.arange(P))),
                                 shape=(C, nvars))
        c_obj = np.concatenate([np.zeros(P), pricef])
        res = linprog(c_obj, A_ub=A_ub, b_ub=np.zeros(O * R),
                      A_eq=A_eq, b_eq=cnt.astype(np.float64),
                      bounds=(0, None), method="highs")
        if not res.success:
            return None, None, info
        info["rounds"] = rnd + 1
        z_new = float(res.fun)
        if z is not None and z_new > z - max(tol, tol * abs(z)):
            # pricing admitted options but the optimum didn't move —
            # dual-degeneracy noise, not real columns; keep the last x
            info["proven"] = True
            break
        z = z_new
        x_full = np.zeros((C, O))
        x_full[pc, pj] = res.x[:P]
        # option pricing under the master's duals: capacity rows (≤,
        # duals μ ≤ 0 in scipy's sign) coeff req[c,r]; demand rows (=,
        # dual y) coeff 1 ⇒ rc(c,j) = −y_c − Σ_r μ_jr·req[c,r]
        y = res.eqlin.marginals
        mu = res.ineqlin.marginals.reshape(O, R)
        rc = -y[:, None] - np.einsum("cr,jr->cj", reqf, mu)
        optmin = np.where(compat & ~S[None, :], rc, np.inf).min(axis=0)
        worst = np.argsort(optmin)[:add_per_round]
        worst = worst[optmin[worst] < -max(tol, tol * abs(z))]
        if len(worst) == 0:
            info["proven"] = True
            break
        S[worst] = True
    info["objective"] = z
    info["options_used"] = int(S.sum())
    return x_full, z, info


def _stripe_group(amounts: np.ndarray, ng: int, req: np.ndarray,
                  alloc: np.ndarray):
    """Distribute amounts[c] pods of each class across ng identical nodes
    WITHOUT exceeding any node's alloc.

    Least-loaded placement: classes go biggest-pod-first; each round a
    class puts one pod on each of the `remaining` least-loaded nodes
    that still fit it (load = bottleneck utilization).  Unlike
    ring-rotation striping — whose window-overlap variance demoted ~12%
    of pods on the bench's big blended group — this keeps fills balanced
    by construction, so only true integrality friction (a class whose
    pods no node can take anymore) demotes to the remainder.
    Returns (fills ng×C int64, demoted C int64)."""
    Cg = len(amounts)
    R = len(alloc)
    fills = np.zeros((ng, Cg), np.int64)
    used = np.zeros((ng, R), np.int64)
    inv_alloc = 1.0 / np.maximum(alloc.astype(np.float64), 1)
    demoted = np.zeros(Cg, np.int64)
    order = np.argsort(-np.max(req * inv_alloc[None, :], axis=1))
    for c in order:
        rem = int(amounts[c])
        rc = req[c]
        while rem > 0:
            fits = (used + rc[None, :] <= alloc[None, :]).all(axis=1)
            n_fit = int(fits.sum())
            if n_fit == 0:
                demoted[c] += rem
                break
            take = min(rem, n_fit)
            if take < n_fit:
                load = np.max(used * inv_alloc[None, :], axis=1)
                load[~fits] = np.inf
                target = np.argpartition(load, take - 1)[:take]
            else:
                target = np.nonzero(fits)[0]
            fills[target, c] += 1
            used[target] += rc
            rem -= take
    return fills, demoted


def solve_guided(problem: Problem, max_alternatives: int = 60,
                 max_nodes: int = 8192, ng_slack: float = 1.0):
    """LP-guided solve: stripe the LP mix into concrete node fills, then
    run the pack kernel on what the LP cannot see.

    1. `exact_lp_mix` gives x[c,g] (pods of class c on option group g)
       and the implied node counts n_g.
    2. The floor of each x[c,g] is STRIPED across ceil(n_g) nodes —
       integral per-node fills that reproduce the LP's blend (sequential
       first-fit cannot: its prefix rule concentrates every class on the
       earliest nodes and measured +19-30% cost).
    3. Everything integrality leaves over — fractional parts, striping
       repairs, hostname-capped classes the pooled LP cannot reason
       about — is a small remainder solved by the ordinary scan kernel
       against the striped nodes' leftover free space (existing columns)
       plus fresh launches.

    Returns a PackingResult indistinguishable from the greedy path's, or
    None when the guide does not apply (degenerate instance, scipy
    missing).  The mix is content-cached on (classes ⊕ catalog).
    """
    from .classpack import resolve_alternatives, solve_classpack
    from .ffd import NodeDecision, PackingResult

    C0, R = problem.class_requests.shape
    O0 = problem.num_options
    if C0 < 2 or O0 == 0:
        return None
    caps = (problem.class_node_cap if problem.class_node_cap is not None
            else np.full(C0, _BIG, np.int32))

    # key over the RAW inputs — the feasibility mask is a deterministic
    # (and, at 50k scale, ~150ms) function of them, so a cache hit skips
    # recomputing it (it rides in the cached tuple).  max_nodes is part
    # of the key: a gate rejection under a tight launch cap must not
    # disable the guide for the same pending set solved with a roomier
    # budget (review r5).
    rank = (problem.option_rank if problem.option_rank is not None
            else np.zeros(O0, np.int32))
    key = hashlib.blake2b(
        problem.class_requests.tobytes() + problem.class_counts.tobytes()
        + np.packbits(problem.class_compat).tobytes() + caps.tobytes()
        + problem.option_alloc.tobytes() + problem.option_price.tobytes()
        + np.ascontiguousarray(rank).tobytes() + str(max_nodes).encode(),
        digest_size=16).digest()
    hit = _MIX_CACHE.get(key)
    if hit is None:
        ok = _feasible_mask(problem)
        if ok.any(axis=1).sum() < 2:
            return None
        d_alloc, d_price, d_compat, group_of = _dedup_with_inverse(
            problem.option_alloc.astype(np.float64),
            problem.option_price.astype(np.float64), ok)
        # hostname-capped classes are excluded from the mix: the pooled LP
        # cannot honor per-node caps, so those classes go to the kernel
        uncapped = caps >= _BIG
        cnt_lp = np.where(uncapped, problem.class_counts, 0)
        x, z, info = exact_lp_mix(problem.class_requests, cnt_lp,
                                  d_compat, d_alloc, d_price)
        if x is None:
            return None
        # largest-remainder rounding per class: integer y[c,g] with
        # Σ_g y = cnt_c exactly — no fractional leftovers ever reach the
        # (greedy-priced) remainder solve; the striper recomputes node
        # counts from the rounded loads so the slight overfill vs the
        # fractional optimum stays inside each group's ceil slack
        y = np.floor(x)
        frac = x - y
        short = np.round(cnt_lp - y.sum(axis=1)).astype(np.int64)
        for c in np.nonzero(short > 0)[0]:
            top = np.argsort(-frac[c])[:short[c]]
            y[c, top] += 1
        loadg = np.einsum("cj,cr->jr", y,
                          problem.class_requests.astype(np.float64))
        n_g = np.max(loadg / np.maximum(d_alloc, 1e-12), axis=1)
        hit = [y, n_g, group_of, float(z), ok, False]
        with _MIX_LOCK:
            while len(_MIX_CACHE) >= _MIX_CACHE_MAX:
                _MIX_CACHE.pop(next(iter(_MIX_CACHE)), None)
            _MIX_CACHE[key] = hit
    x, n_g, group_of, z_lp, ok, rejected = hit
    if rejected:
        return None
    # per-round launch-cap contract (review r5): the striper creates
    # nodes directly, so it must honor max_nodes like the kernel's K cap
    # does — when the LP fleet alone would blow the budget, the greedy
    # path owns the cap semantics (pack what fits, leave the rest
    # unschedulable for the next round)
    if int(np.ceil(n_g - 1e-9).sum()) > max_nodes:
        return None

    members_arr = problem.members_arrays()
    reqs_int = problem.class_requests.astype(np.int64)
    consumed = np.zeros(C0, np.int64)
    ptr = np.zeros(C0, np.int64)

    # ---- stripe each LP-used group into integral node fills ----
    # assembled fully vectorized: per class one np.repeat gives each pod's
    # node id; one global stable argsort + boundary split then yields the
    # per-node pod lists (the same pattern the kernel decode uses) — no
    # per-(class, node) Python loop at 50k-pod scale
    all_node_ids: list = []
    all_pod_ids: list = []
    all_cls_ids: list = []
    node_oi_parts: list = []
    node_used_parts: list = []
    node_base = 0
    for g in np.nonzero(n_g > 1e-6)[0]:
        members = np.nonzero(group_of == g)[0]
        if not len(members):
            continue
        oi = int(members[0])
        cls = np.nonzero(x[:, g] >= 1.0)[0]
        amounts = np.floor(x[cls, g]).astype(np.int64)
        amounts = np.minimum(amounts,
                             problem.class_counts[cls] - consumed[cls])
        keep = amounts > 0
        cls, amounts = cls[keep], amounts[keep]
        if not len(cls):
            continue
        ng = int(np.ceil(n_g[g] * ng_slack - 1e-9))
        fills, demoted = _stripe_group(
            amounts, ng, reqs_int[cls],
            problem.option_alloc[oi].astype(np.int64))
        placed = amounts - demoted
        consumed[cls] += placed
        nodes_of_group = np.arange(ng)
        for k, c in enumerate(cls):
            n_pl = int(placed[k])
            if n_pl == 0:
                continue
            node_ids = np.repeat(nodes_of_group, fills[:, k]) + node_base
            all_node_ids.append(node_ids)
            all_pod_ids.append(members_arr[c][ptr[c]:ptr[c] + n_pl])
            all_cls_ids.append(np.full(n_pl, c, np.int64))
            ptr[c] += n_pl
        node_oi_parts.append(np.full(ng, oi, np.int64))
        node_used_parts.append(fills @ reqs_int[cls])
        node_base += ng

    if not all_node_ids:
        return None
    node_ids = np.concatenate(all_node_ids)
    pod_ids = np.concatenate(all_pod_ids)
    cls_ids = np.concatenate(all_cls_ids)
    order = np.argsort(node_ids, kind="stable")
    node_ids, pod_ids, cls_ids = (node_ids[order], pod_ids[order],
                                  cls_ids[order])
    starts = np.nonzero(np.diff(node_ids, prepend=np.int64(-1)))[0]
    ends = np.append(starts[1:], len(node_ids))
    occupied = node_ids[starts]                 # node id per non-empty node
    all_oi = np.concatenate(node_oi_parts) if node_oi_parts else \
        np.zeros(0, np.int64)
    all_used = np.concatenate(node_used_parts) if node_used_parts else \
        np.zeros((0, R), np.int64)
    bulk_oi = all_oi[occupied].tolist()
    bulk_pods = [pod_ids[s:e].tolist() for s, e in zip(starts, ends)]
    # duplicates are fine downstream (joint compat ANDs idempotently), so
    # skip the ~per-node np.unique
    bulk_cls = [cls_ids[s:e].tolist() for s, e in zip(starts, ends)]

    if not bulk_oi:
        return None

    # ---- cross-group tuck: demoted pods into ANY bulk node with room ----
    # Striping strands slivers per node (≈1-2% of bulk capacity) while
    # demoting the pods that no longer fit their OWN group; across groups
    # those slivers add up to whole node-equivalents.  One host-side
    # least-loaded pass over the entire fleet (compat-checked against each
    # node's option) re-places most demotions for free — measured 12%→
    # remainder drop to a few % on 50k-burst — and lets the remainder
    # solve run WITHOUT existing columns, keeping the fresh kernel's
    # compiled shapes.  Hostname-capped classes stay out (their per-node
    # caps need the kernel).
    rem = problem.class_counts.astype(np.int64) - consumed
    alloc_int = problem.option_alloc.astype(np.int64)
    used_mat = all_used[occupied].astype(np.int64)
    node_oi_arr = np.asarray(bulk_oi, np.int64)
    free_mat = alloc_int[node_oi_arr] - used_mat
    inv_node_alloc = 1.0 / np.maximum(alloc_int[node_oi_arr], 1)
    tuck_order = np.argsort(
        -(reqs_int / np.maximum(alloc_int.mean(axis=0), 1)).max(axis=1))
    for c in tuck_order:
        if rem[c] <= 0:
            continue
        rc = reqs_int[c]
        # RAW compat, not the rank-restricted mask: pool-weight precedence
        # governs what to LAUNCH, never what already-bought capacity may
        # host (same rule as the kernel's existing columns; review r5)
        node_ok = problem.class_compat[c][node_oi_arr]
        # hostname-capped classes tuck too: striped bulk nodes host none
        # of their pods, so a fresh per-node counter enforces the cap
        # exactly (review r5: skipping them forced fresh launches for
        # pods the fleet's slivers could legally hold)
        placed_c = np.zeros(len(node_oi_arr), np.int64)
        cap_c = int(caps[c])
        while rem[c] > 0:
            fits = node_ok & (free_mat >= rc[None, :]).all(axis=1) & \
                (placed_c < cap_c)
            n_fit = int(fits.sum())
            if n_fit == 0:
                break
            take = min(int(rem[c]), n_fit)
            if take < n_fit:
                load = np.max(used_mat * inv_node_alloc, axis=1)
                load[~fits] = np.inf
                sel = np.argpartition(load, take - 1)[:take]
            else:
                sel = np.nonzero(fits)[0]
            mem = members_arr[c]
            for i in sel:
                bulk_pods[i].append(int(mem[ptr[c]]))
                ptr[c] += 1
                if c not in bulk_cls[i]:
                    bulk_cls[i].append(int(c))
            used_mat[sel] += rc
            free_mat[sel] -= rc
            placed_c[sel] += 1
            consumed[c] += take
            rem[c] -= take

    # ---- remainder: what even the tuck couldn't place, capped classes ----
    rem_cls = np.nonzero(rem > 0)[0]
    sub_res = None
    if len(rem_cls):
        sub = _subproblem(problem, rem_cls, rem[rem_cls], ptr)
        # fresh-only solve: the tuck already consumed the fleet's usable
        # slivers, so existing columns would add kernel shape variants for
        # nothing.  A fully consumed launch budget removes the catalog
        # outright — then these pods come back unschedulable for the next
        # round (review r5: the old max(1, …) floor leaked an extra node).
        budget = max_nodes - len(bulk_oi)
        if budget <= 0:
            sub.options = []
            sub.option_alloc = sub.option_alloc[:0]
            sub.option_price = sub.option_price[:0]
            if sub.option_rank is not None:
                sub.option_rank = sub.option_rank[:0]
            if sub.option_zone is not None:
                sub.option_zone = sub.option_zone[:0]
            if sub.option_captype is not None:
                sub.option_captype = sub.option_captype[:0]
            sub.class_compat = sub.class_compat[:, :0]
            budget = 0
        sub_res = solve_classpack(sub, max_nodes=max(budget, 1),
                                  decode=True, guide=None,
                                  max_alternatives=max_alternatives)

    # ---- merge ----
    unschedulable: list = []
    new_nodes: list = []
    total = 0.0
    if sub_res is not None:
        unschedulable = sub_res.unschedulable
        new_nodes = sub_res.nodes
        total += sub_res.total_price

    # acceptance gate: when integrality friction blows the result past
    # the guide's design envelope (tiny fleets, where one node of ceil
    # slack is a large relative cost), price the greedy ALTERNATIVE with
    # one cheap aggregate solve and keep whichever plan is actually
    # better.  The envelope check means the extra kernel call only
    # happens on suspicious instances, never on the bench/product hot
    # path; rejections are remembered so re-solves skip straight to
    # greedy.
    probe_total = (sub_res.total_price if sub_res is not None else 0.0) + \
        sum(float(problem.option_price[oi]) for oi in bulk_oi)
    probe_unsched = len(unschedulable)
    # z_lp excludes hostname-capped classes, so on cap-heavy workloads
    # the envelope check would mis-trigger every solve (review r5) — the
    # envelope is only meaningful when the LP priced most of the demand
    capped_frac = float(problem.class_counts[caps < _BIG].sum()) / \
        max(float(problem.class_counts.sum()), 1.0)
    if z_lp > 0 and capped_frac < 0.5 and probe_total > 1.08 * z_lp:
        greedy = solve_classpack(problem, max_nodes=max_nodes, decode=False,
                                 guide=None)
        # strictly worse only: a tie keeps the guided plan (its decode is
        # already materialized) instead of permanently rejecting the key
        if (probe_unsched, probe_total) > (len(greedy.unschedulable),
                                           greedy.total_price):
            hit[5] = True
            return None

    # memo keys are the nodes' class SETS — joint-compat bits are only
    # computed for memo misses inside resolve_alternatives (a fleet-wide
    # AND costs ~100ms at 50k; the distinct keys are a few hundred)
    cls_keys = [tuple(sorted(set(cl))) for cl in bulk_cls]
    resolved = resolve_alternatives(problem, bulk_oi, None, used_mat,
                                    max_alternatives, cls_keys=cls_keys)
    nodes = []
    for i, oi in enumerate(bulk_oi):
        alts, used_rl = resolved[i]
        nodes.append(NodeDecision(option=problem.options[oi],
                                  pod_indices=bulk_pods[i],
                                  used=used_rl, alternatives=alts))
        total += float(problem.option_price[oi])
    nodes.extend(new_nodes)
    return PackingResult(nodes=nodes, unschedulable=unschedulable,
                         existing_assignments={}, total_price=total)


def _subproblem(problem: Problem, cls: np.ndarray, counts: np.ndarray,
                ptr: np.ndarray) -> Problem:
    """A Problem restricted to `cls` with `counts` pods each, whose member
    lists are the UNCONSUMED tails of the original classes — so every pod
    index in the sub-solve's result is a real original pod id."""
    import copy
    members_arr = problem.members_arrays()
    sub = copy.copy(problem)
    sub.class_requests = problem.class_requests[cls]
    sub.class_counts = counts.astype(np.int32)
    sub.class_compat = problem.class_compat[cls]
    if problem.class_node_cap is not None:
        sub.class_node_cap = problem.class_node_cap[cls]
    sub.class_members = [members_arr[c][ptr[c]:ptr[c] + n]
                         for c, n in zip(cls, counts)]
    sub.__dict__.pop("_members_arr", None)
    sub.__dict__.pop("_class_order", None)
    return sub
