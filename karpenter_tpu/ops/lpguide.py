"""LP-guided option mix: close the packer's option-choice gap.

Round-4's open question — is the measured ~9% cost-vs-bound residual on
mixed shapes bound looseness or packer waste? — was settled by
benchmarks/optimality_probe.py and ops/ggbound.py `integral_bracket`:
on the bench's 10k-mixed instance the integral optimum lies in
[642.91, 654.52] while the greedy plan costs 704.12, and the plan's
nodes are ~100% full on their bottleneck resource.  The waste is
**option-mix**, not fragmentation: each class independently buys the
type cheapest for itself, stranding the non-bottleneck resource that a
complementary class (cpu-heavy with mem-heavy) could have used.  The
reference's FFD has the same blind spot by construction
(/root/reference/designs/bin-packing.md:16-43 packs pod-at-a-time with
a per-pod type preference).

The fix: solve the class-granular LP

    min  Σ_j price_j · n_j
    s.t. Σ_c req[c,r]·x[c,j] ≤ alloc[j,r]·n_j   ∀ j,r
         Σ_j x[c,j] = cnt_c                      ∀ c,  x, n ≥ 0

EXACTLY, but fast: restricted to a small per-class support of candidate
options, then priced against the full catalog by LP reduced costs and
re-solved until no violating pair remains — textbook column generation
whose terminal solution is optimal for the FULL LP.  The support starts
at each class's cheapest sole-tenancy options, so one or two pricing
rounds settle it; the restricted LPs are ~10³ variables and solve in
tens of milliseconds (first-order methods were tried first and stall at
1.03-1.04× — see docs/design-lpguide.md).

The guide then *shapes* the existing scan kernel instead of replacing
it: each class's LP allocation is floored into **bulk rows** pinned to
their option's dedup group (one-hot group compat) plus one **remainder
row** with the class's full compat.  The unchanged first-fit kernel
packs bulk rows into the LP's option mix and lets remainders fill the
cross-option partial tails — integrality lands exactly where the greedy
was already good, and the option mix lands where the LP is provably
better.  Decode, audits, and caps are the same code path as every other
solve.  The mix is content-cached: a provisioner re-solving an
unchanged pending set (tick loops, capacity retries, bench iterations)
pays the LP once.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Optional, Tuple

import numpy as np

from .tensorize import Problem
from ..utils import metrics, tracing

_BIG = np.int32(2**30)

# content-keyed mix cache: (classes ⊕ catalog fingerprint) → guided rows.
# Same discipline as classpack's catalog/pod-side caches: check-then-insert
# under one lock, bounded size.
_MIX_CACHE: dict = {}
_MIX_CACHE_MAX = 16
_MIX_LOCK = threading.Lock()

# stale-guide cache: keyed WITHOUT pod counts (class shapes ⊕ catalog), so
# a tick whose counts changed but whose catalog fingerprint still matches
# can rescale the freshest old mix instead of blocking on column
# generation.  Entries carry a monotonic stamp; the refinery's staleness
# window bounds how old a mix may serve.
_STALE_CACHE: dict = {}
_STALE_CACHE_MAX = 16

# LP warm-start cache: class-shape digest → the terminal colgen support as
# CONTENT keys (alloc-row bytes, price), so a changed catalog maps old
# support columns back by content and counts-only deltas reuse them
# directly.  Seeds only — a wrong seed just adds columns to the restricted
# LP, never changes the optimum.
_SUPPORT_CACHE: dict = {}
_SUPPORT_CACHE_MAX = 32


def _feasible_mask(problem: Problem) -> np.ndarray:
    """class_compat ∧ fits-one-node ∧ launchable ∧ best-pool-rank — the
    same preselection the pack kernel applies, so the LP optimizes over
    exactly the kernel's action space."""
    req = problem.class_requests.astype(np.float64)
    alloc = problem.option_alloc.astype(np.float64)
    reqpos = req > 0
    safe = np.where(reqpos, req, 1.0)
    m = np.where(reqpos[:, None, :], alloc[None, :, :] // safe[:, None, :],
                 np.inf).min(axis=2)
    ok = problem.class_compat & (m >= 1.0) & \
        np.isfinite(problem.option_price)
    rank = (problem.option_rank if problem.option_rank is not None
            else np.zeros(problem.num_options, np.int32))
    best = np.min(np.where(ok, rank[None, :], _BIG), axis=1)
    return ok & (rank[None, :] == best[:, None])


def _dedup_with_inverse(alloc: np.ndarray, price: np.ndarray,
                        compat: np.ndarray):
    """Collapse options identical in (alloc, price, compat column); returns
    (alloc', price', compat', group_of: O→O' inverse map).  Zone/subnet
    copies of one offering are LP-indistinguishable, and their identical
    compat columns mean a group mask is exactly the member mask."""
    O = alloc.shape[0]
    keys: dict = {}
    group_of = np.empty(O, np.int64)
    keep = []
    for j in range(O):
        k = (alloc[j].tobytes(), float(price[j]), compat[:, j].tobytes())
        g = keys.get(k)
        if g is None:
            g = keys[k] = len(keep)
            keep.append(j)
        group_of[j] = g
    keep = np.asarray(keep, np.int64)
    return alloc[keep], price[keep], compat[:, keep], group_of


def _dual_certificate_ok(y: np.ndarray, mu: np.ndarray, reqf: np.ndarray,
                         cnt: np.ndarray, z: float, pc: np.ndarray,
                         pj: np.ndarray, xvals: np.ndarray,
                         tol: float = 1e-5) -> bool:
    """Cheap invariant pinning scipy's dual-sign convention (the pricing
    step at the rc computation below silently inverts if a scipy release
    flips marginal signs).  Two checks, both consequences of LP optimality
    under the convention the pricing assumes:

      * strong duality: the dual objective is b_eq·y + b_ub·μ, and b_ub is
        all zeros here, so y·cnt must reconstruct the primal objective;
      * complementary slackness: rc(c,j) = −y_c − Σ_r μ_jr·req[c,r] must
        vanish on in-support basic pairs (x[c,j] > 0).

    A flipped y fails the first; a flipped μ fails the second."""
    scale = max(1.0, abs(z))
    if abs(float(y @ cnt.astype(np.float64)) - z) > tol * scale:
        return False
    basic = xvals > 1e-9 * max(1.0, float(cnt.max()) if len(cnt) else 1.0)
    if not basic.any():
        return True
    rc = -y[pc[basic]] - np.einsum("pr,pr->p", reqf[pc[basic]], mu[pj[basic]])
    # rc is price-scaled (objective units); normalize like the duality gap
    return float(np.abs(rc).max()) <= tol * scale


# Device-path certificate tolerance: PDHG solves to a relative KKT
# tolerance of ~1e-4 (f32), so strong duality / complementary slackness
# hold to that order — the certificate still pins the SIGN convention
# (a flipped dual is off by O(1), not O(eps)), it just stops pretending
# the duals are vertex-exact the way HiGHS marginals are.
_DEVICE_CERT_TOL = 1e-3


def _report_device_failure(lp_health, reason: str) -> None:
    """One device-master failure: count the fallback and feed the
    DeviceLP ladder (whose `_transition` increments the demotion trip
    counter AND publishes the `solver_demotion` incident in the same
    function — the OB006 funnel)."""
    metrics.lp_solves().inc({"outcome": "demoted"})
    if lp_health is not None:
        lp_health.report_failure("device_lp", reason)


def _device_master(ub_rows, ub_cols, ub_vals, m_ub: int, pc, pj, P: int,
                   nvars: int, c_obj, cnt, reqf, O: int, R: int,
                   warm_key, lp_health):
    """Solve one restricted master on the device (ops/lpsolve.py PDHG)
    and validate its duals with the same sign certificate the scipy path
    uses.  Returns (x_vars, z, y, mu) in scipy's dual convention, or
    None after reporting the failure to the DeviceLP ladder (iteration
    cap / certificate failure — the caller re-solves through HiGHS).

    The dense operands are COMPRESSED to the active options (those with
    at least one support pair) before padding: an inactive option
    contributes only the degenerate row 0 − alloc_j·n_j ≤ 0 with n_j = 0
    at the optimum and a zero marginal — HiGHS absorbs those rows
    through sparsity, but on the dense device path a 3600-option catalog
    would pad the envelope ~50x past the ~dozens of seeded options the
    restricted master actually prices.  Their μ rows scatter back as 0,
    which is exactly the marginal HiGHS reports for them."""
    from . import lpsolve
    act = np.unique(pj)
    Oa = len(act)
    newj = np.full(O, -1, np.int64)
    newj[act] = np.arange(Oa)
    j_of_row = ub_rows // R
    keep = newj[j_of_row] >= 0
    rr = newj[j_of_row[keep]] * R + ub_rows[keep] % R
    cc = ub_cols[keep].copy()
    isn = cc >= P
    cc[isn] = P + newj[cc[isn] - P]
    A_ub = np.zeros((Oa * R, P + Oa), np.float64)
    A_ub[rr, cc] = ub_vals[keep]
    A_eq = np.zeros((len(cnt), P + Oa), np.float64)
    A_eq[pc, np.arange(P)] = 1.0
    c_act = np.concatenate([c_obj[:P], c_obj[P + act]])
    sol = lpsolve.solve_lp(c_act, A_eq=A_eq, b_eq=cnt.astype(np.float64),
                           A_ub=A_ub, b_ub=np.zeros(Oa * R),
                           warm_key=warm_key)
    if not sol.converged:
        _report_device_failure(lp_health, "cap")
        return None
    # HiGHS returns a vertex with clean zeros; PDHG leaves 1e-4-scale
    # dust on non-basic entries.  Sweep it so the certificate's basic-
    # pair selection and the striper's floors see the same support a
    # vertex solution would.
    dust = 1e-4 * max(1.0, float(cnt.max()) if len(cnt) else 1.0)
    x_act = np.where(sol.x >= dust, sol.x, 0.0)
    x_vars = np.zeros(nvars)
    x_vars[:P] = x_act[:P]
    x_vars[P + act] = x_act[P:]
    z = float(c_obj @ x_vars)
    y, mu_flat = sol.scipy_duals()
    mu = np.zeros((O, R))
    mu[act] = mu_flat.reshape(Oa, R)
    if not _dual_certificate_ok(y, mu, reqf, cnt, z, pc, pj, x_vars[:P],
                                tol=_DEVICE_CERT_TOL):
        _report_device_failure(lp_health, "certificate")
        return None
    if lp_health is not None:
        lp_health.report_success("device_lp")
    return x_vars, z, y, mu


def exact_lp_mix(req: np.ndarray, cnt: np.ndarray, compat: np.ndarray,
                 alloc: np.ndarray, price: np.ndarray,
                 pricing_rounds: int = 3, add_per_round: int = 16,
                 tol: float = 1e-6, seed_support: Optional[np.ndarray] = None,
                 device: bool = False, lp_health=None,
                 warm_key: Optional[str] = None):
    """Class-LP optimum by option-granular column generation.  Returns
    (x C×O, objective, info) or (None, None, info) when scipy is
    unavailable or the LP fails.

    Seeding is the part that makes this fast: for a small family of
    resource weightings w (each axis alone, the uniform mix, pairwise
    mixes, and the bottleneck max), every class contributes its cheapest
    option under cost_w = price_j·Σ_r w_r·req_cr/alloc_jr.  That yields
    a few dozen ratio-diverse options whose restricted LP — ALL
    compatible (class, option) pairs for seeded options — lands on the
    full-LP optimum immediately on every bench shape measured (the
    ratio-matched option family the LP blends is exactly what the
    weighting sweep enumerates).  Safety net for adversarial shapes:
    price the excluded options with the master's duals, admit the worst
    `add_per_round`, and stop as soon as the objective stops improving —
    duals of these degenerate masters routinely flag options that cannot
    actually improve the optimum, so improvement (not rc-cleanliness) is
    the stopping criterion.  Certified bounds stay lpbound's job.

    `seed_support` (option indices) unions extra columns into the initial
    support — the refinery's warm start: the terminal support of the
    previous solve of the same class shapes, mapped by content, usually
    IS the new optimum's support, so the first restricted LP lands on it
    and pricing terminates in one round."""
    try:
        from scipy import sparse
        from scipy.optimize import linprog
    except ImportError:  # pragma: no cover — scipy is baked into the image
        return None, None, {"method": "none"}

    C, R = req.shape
    O = alloc.shape[0]
    reqf = req.astype(np.float64)
    allocf = alloc.astype(np.float64)
    pricef = price.astype(np.float64)
    inv_alloc = np.where(allocf > 0, 1.0 / np.maximum(allocf, 1e-12), 0.0)

    # ---- multi-weight seeding ----
    weights = [np.eye(R)[r] for r in range(R)]
    weights.append(np.ones(R) / R)
    for a in range(R):
        for b in range(a + 1, R):
            w = np.zeros(R)
            w[a] = w[b] = 0.5
            weights.append(w)
    S = np.zeros(O, bool)
    for w in weights:
        cost_w = pricef[None, :] * (reqf @ (inv_alloc * w[None, :]).T)
        cost_w = np.where(compat, cost_w, np.inf)
        S[np.unique(np.argmin(cost_w, axis=1))] = True
    ppm = np.where(compat, pricef[None, :] *
                   np.max(reqf[:, None, :] * inv_alloc[None, :, :], axis=2),
                   np.inf)
    S[np.unique(np.argmin(ppm, axis=1))] = True
    if seed_support is not None and len(seed_support):
        S[np.asarray(seed_support, np.int64)] = True

    info = {"method": "colgen-lp", "rounds": 0, "proven": False,
            "dual_check": True}
    # device masters are only attempted while the DeviceLP ladder says
    # the rung is healthy; a single in-call failure also stops retrying
    # (the scipy master this round already has the operands built)
    use_device = device and (lp_health is None or
                             lp_health.active_rung("device_lp") ==
                             "device_lp")
    x_full = None
    z = None
    for rnd in range(pricing_rounds):
        supp = compat & S[None, :]
        pc, pj = np.nonzero(supp)
        P = len(pc)
        nvars = P + O
        rows, cols, vals = [], [], []
        for r in range(R):
            nz = reqf[pc, r] != 0
            rows.append(pj[nz] * R + r)
            cols.append(np.nonzero(nz)[0])
            vals.append(reqf[pc[nz], r])
        rows.append(np.repeat(np.arange(O), R) * R + np.tile(np.arange(R), O))
        cols.append(np.repeat(np.arange(O) + P, R))
        vals.append(-allocf.reshape(-1))
        ub_rows = np.concatenate(rows)
        ub_cols = np.concatenate(cols)
        ub_vals = np.concatenate(vals)
        c_obj = np.concatenate([np.zeros(P), pricef])
        x_vars = None
        if use_device:
            dev = _device_master(ub_rows, ub_cols, ub_vals, O * R, pc, pj,
                                 P, nvars, c_obj, cnt, reqf, O, R,
                                 warm_key, lp_health)
            if dev is None:
                use_device = False   # demoted: HiGHS for the rest of call
            else:
                x_vars, z_new, y, mu = dev
                info["method"] = "colgen-lp-device"
                cert_tol = _DEVICE_CERT_TOL
        if x_vars is None:
            A_ub = sparse.csr_matrix(
                (ub_vals, (ub_rows, ub_cols)), shape=(O * R, nvars))
            A_eq = sparse.csr_matrix((np.ones(P), (pc, np.arange(P))),
                                     shape=(C, nvars))
            res = linprog(c_obj, A_ub=A_ub, b_ub=np.zeros(O * R),
                          A_eq=A_eq, b_eq=cnt.astype(np.float64),
                          bounds=(0, None), method="highs")
            if not res.success:
                return None, None, info
            x_vars = res.x
            z_new = float(res.fun)
            # capacity rows (≤, duals μ ≤ 0 in scipy's sign), demand
            # rows (=, dual y)
            y = res.eqlin.marginals
            mu = res.ineqlin.marginals.reshape(O, R)
            cert_tol = 1e-5
        info["rounds"] = rnd + 1
        if z is not None and z_new > z - max(tol, tol * abs(z)):
            # pricing admitted options but the optimum didn't move —
            # dual-degeneracy noise, not real columns; keep the last x
            info["proven"] = True
            break
        z = z_new
        x_full = np.zeros((C, O))
        x_full[pc, pj] = x_vars[:P]
        # option pricing under the master's duals:
        # rc(c,j) = −y_c − Σ_r μ_jr·req[c,r]
        if not _dual_certificate_ok(y, mu, reqf, cnt, z_new, pc, pj,
                                    x_vars[:P], tol=cert_tol):
            # the duals don't certify this master (sign-convention drift
            # or a degenerate basis): pricing with them could admit
            # garbage columns or terminate early with a false "proven".
            # Keep the primal solution — it is still restricted-LP
            # optimal — but stop pricing and report it unproven.
            info["dual_check"] = False
            info["proven"] = False
            break
        rc = -y[:, None] - np.einsum("cr,jr->cj", reqf, mu)
        optmin = np.where(compat & ~S[None, :], rc, np.inf).min(axis=0)
        worst = np.argsort(optmin)[:add_per_round]
        worst = worst[optmin[worst] < -max(tol, tol * abs(z))]
        if len(worst) == 0:
            info["proven"] = True
            break
        S[worst] = True
    info["objective"] = z
    info["options_used"] = int(S.sum())
    info["support"] = np.nonzero(S)[0]
    return x_full, z, info


def _stripe_group(amounts: np.ndarray, ng: int, req: np.ndarray,
                  alloc: np.ndarray):
    """Distribute amounts[c] pods of each class across ng identical nodes
    WITHOUT exceeding any node's alloc.

    Least-loaded placement: classes go biggest-pod-first; each round a
    class puts one pod on each of the `remaining` least-loaded nodes
    that still fit it (load = bottleneck utilization).  Unlike
    ring-rotation striping — whose window-overlap variance demoted ~12%
    of pods on the bench's big blended group — this keeps fills balanced
    by construction, so only true integrality friction (a class whose
    pods no node can take anymore) demotes to the remainder.
    Returns (fills ng×C int64, demoted C int64)."""
    Cg = len(amounts)
    R = len(alloc)
    fills = np.zeros((ng, Cg), np.int64)
    used = np.zeros((ng, R), np.int64)
    inv_alloc = 1.0 / np.maximum(alloc.astype(np.float64), 1)
    demoted = np.zeros(Cg, np.int64)
    order = np.argsort(-np.max(req * inv_alloc[None, :], axis=1))
    for c in order:
        rem = int(amounts[c])
        rc = req[c]
        while rem > 0:
            fits = (used + rc[None, :] <= alloc[None, :]).all(axis=1)
            n_fit = int(fits.sum())
            if n_fit == 0:
                demoted[c] += rem
                break
            take = min(rem, n_fit)
            if take < n_fit:
                load = np.max(used * inv_alloc[None, :], axis=1)
                load[~fits] = np.inf
                target = np.argpartition(load, take - 1)[:take]
            else:
                target = np.nonzero(fits)[0]
            fills[target, c] += 1
            used[target] += rc
            rem -= take
    return fills, demoted


def _cache_put(cache: dict, cache_max: int, key, value) -> None:
    """Bounded check-then-insert under the shared lock (oldest-first
    eviction, same discipline as classpack's content caches)."""
    with _MIX_LOCK:
        while len(cache) >= cache_max:
            cache.pop(next(iter(cache)), None)
        cache[key] = value


def snapshot_caches() -> dict:
    """Plain-data export of the mix/stale/support caches for the
    WarmRestart snapshot (state/snapshot.py) — keys are content digests,
    values numpy arrays and scalars, all picklable.  Stale-entry stamps
    transfer as-is: they only matter inside one clock domain (the sim's
    virtual clock, or a same-boot restart); a cross-domain stamp just
    fails the staleness window and the entry recomputes."""
    with _MIX_LOCK:
        return {"mix": dict(_MIX_CACHE), "stale": dict(_STALE_CACHE),
                "support": dict(_SUPPORT_CACHE)}


def restore_caches(data: dict) -> None:
    with _MIX_LOCK:
        _MIX_CACHE.clear()
        _MIX_CACHE.update(data.get("mix", {}))
        _STALE_CACHE.clear()
        _STALE_CACHE.update(data.get("stale", {}))
        _SUPPORT_CACHE.clear()
        _SUPPORT_CACHE.update(data.get("support", {}))


def _mix_keys(problem: Problem, caps: np.ndarray, max_nodes: int):
    """Content digests at three granularities over the RAW inputs (the
    feasibility mask is a deterministic — and, at 50k scale, ~150ms —
    function of them, so cache hits skip recomputing it):

      * exact:  classes ⊕ counts ⊕ catalog ⊕ max_nodes — the mix cache key.
        max_nodes is part of it: a gate rejection under a tight launch cap
        must not disable the guide for the same pending set solved with a
        roomier budget (review r5).
      * stale:  the exact key MINUS counts/max_nodes — a tick whose pod
        counts changed but whose catalog fingerprint still matches can
        rescale an old mix (group space identical: the mask and dedup
        don't read counts).
      * shape:  class requests ⊕ caps only — the warm-start key; support
        columns survive catalog edits because they're stored by content.
    """
    rank = (problem.option_rank if problem.option_rank is not None
            else np.zeros(problem.num_options, np.int32))
    req_b = problem.class_requests.tobytes()
    cnt_b = problem.class_counts.tobytes()
    compat_b = np.packbits(problem.class_compat).tobytes()
    caps_b = caps.tobytes()
    cat_b = (problem.option_alloc.tobytes() + problem.option_price.tobytes()
             + np.ascontiguousarray(rank).tobytes())
    key = hashlib.blake2b(
        req_b + cnt_b + compat_b + caps_b + cat_b
        + str(max_nodes).encode(), digest_size=16).digest()
    stale_key = hashlib.blake2b(req_b + compat_b + caps_b + cat_b,
                                digest_size=16).digest()
    shape_key = hashlib.blake2b(req_b + caps_b, digest_size=16).digest()
    return key, stale_key, shape_key


def _round_mix(x: np.ndarray, targets: np.ndarray) -> np.ndarray:
    """Largest-remainder rounding per class: integer y with
    Σ_g y[c] == targets[c] exactly — no fractional leftovers ever reach
    the (greedy-priced) remainder solve."""
    y = np.floor(x)
    frac = x - y
    short = np.round(targets - y.sum(axis=1)).astype(np.int64)
    for c in np.nonzero(short > 0)[0]:
        top = np.argsort(-frac[c])[:short[c]]
        y[c, top] += 1
    return y


def _compute_mix(problem: Problem, caps: np.ndarray, stale_key=None,
                 shape_key=None, clock=time.monotonic, device: bool = False,
                 lp_health=None):
    """The expensive half of the guide: feasibility mask → dedup →
    (warm-started) colgen LP → largest-remainder rounding.  Returns the
    mix entry [y, n_g, group_of, z, ok, rejected] or None, refreshing the
    stale-guide and warm-start caches when keys are given.  With
    `device=True` (the DeviceLP gate) the restricted masters solve on
    the PDHG kernel — fast enough to run ON the provisioning tick, which
    is what closes the stale-guide window; otherwise this runs in-tick
    only when no refinery is wired, else in the refinery worker."""
    ok = _feasible_mask(problem)
    if ok.any(axis=1).sum() < 2:
        return None
    d_alloc, d_price, d_compat, group_of = _dedup_with_inverse(
        problem.option_alloc.astype(np.float64),
        problem.option_price.astype(np.float64), ok)
    # hostname-capped classes are excluded from the mix: the pooled LP
    # cannot honor per-node caps, so those classes go to the kernel
    uncapped = caps >= _BIG
    cnt_lp = np.where(uncapped, problem.class_counts, 0)
    seed = None
    if shape_key is not None:
        support = _SUPPORT_CACHE.get(shape_key)
        if support:
            by_content = {(d_alloc[j].tobytes(), float(d_price[j])): j
                          for j in range(len(d_price))}
            seed = [by_content[k] for k in support if k in by_content]
    x, z, info = exact_lp_mix(problem.class_requests, cnt_lp,
                              d_compat, d_alloc, d_price,
                              seed_support=seed, device=device,
                              lp_health=lp_health,
                              warm_key=(shape_key.hex() + ":master")
                              if shape_key is not None else None)
    if x is None:
        return None
    if shape_key is not None and info.get("support") is not None:
        _cache_put(_SUPPORT_CACHE, _SUPPORT_CACHE_MAX, shape_key,
                   [(d_alloc[j].tobytes(), float(d_price[j]))
                    for j in info["support"]])
    # the striper recomputes node counts from the rounded loads so the
    # slight overfill vs the fractional optimum stays inside each group's
    # ceil slack
    y = _round_mix(x, cnt_lp)
    loadg = np.einsum("cj,cr->jr", y,
                      problem.class_requests.astype(np.float64))
    n_g = np.max(loadg / np.maximum(d_alloc, 1e-12), axis=1)
    if stale_key is not None:
        _cache_put(_STALE_CACHE, _STALE_CACHE_MAX, stale_key, {
            "x": x, "cnt": cnt_lp.astype(np.float64), "group_of": group_of,
            "ok": ok, "alloc": d_alloc, "price": d_price, "stamp": clock()})
    return [y, n_g, group_of, float(z), ok, False]


def _stale_mix(problem: Problem, stale_key, caps: np.ndarray, now: float,
               ttl: float):
    """Rescale the freshest old mix whose catalog fingerprint still
    matches (same classes/compat/caps/options — only pod counts differ)
    to the current counts: per-class group distribution × new counts,
    largest-remainder rounded.  Bounded by the staleness window `ttl`.
    The gate's z is the rescaled mix's own fractional cost — achievable
    by construction, with the greedy-compare backstop unchanged."""
    ent = _STALE_CACHE.get(stale_key)
    if ent is None or not (now - ent["stamp"] <= ttl):
        return None
    covered = ent["cnt"] > 0
    uncapped = caps >= _BIG
    cnt_lp = np.where(uncapped & covered, problem.class_counts, 0)
    if not cnt_lp.any():
        return None
    frac = np.where(covered[:, None],
                    ent["x"] / np.maximum(ent["cnt"], 1e-12)[:, None], 0.0)
    x = frac * cnt_lp[:, None].astype(np.float64)
    y = _round_mix(x, cnt_lp)
    reqf = problem.class_requests.astype(np.float64)
    inv_alloc = 1.0 / np.maximum(ent["alloc"], 1e-12)
    n_g = np.max(np.einsum("cj,cr->jr", y, reqf) * inv_alloc, axis=1)
    z_est = float((np.max(np.einsum("cj,cr->jr", x, reqf) * inv_alloc,
                          axis=1) * ent["price"]).sum())
    return [y, n_g, ent["group_of"], z_est, ent["ok"], False]


def _refine_job(problem: Problem, caps: np.ndarray, max_nodes: int, key,
                stale_key, shape_key, clock, device: bool = False,
                lp_health=None):
    """Refinery worker body: compute the exact mix off the tick, land it
    in the content-keyed cache (upgrading the next tick), then price the
    greedy alternative so the refinery can raise the one-shot re-solve
    hint when the refined mix is a real saving.  Background refines use
    the device solver too when the DeviceLP rung is healthy — the same
    ladder the in-tick path consults."""
    with tracing.span("refinery.lp"):
        hit = _compute_mix(problem, caps, stale_key, shape_key, clock=clock,
                           device=device, lp_health=lp_health)
    if hit is None:
        return None
    with tracing.span("refinery.price") as sp:
        _cache_put(_MIX_CACHE, _MIX_CACHE_MAX, key, hit)
        from .classpack import solve_classpack
        greedy = solve_classpack(problem, max_nodes=max_nodes, decode=False,
                                 guide=None)
        sp.annotate(z_lp=hit[3], greedy_total=float(greedy.total_price))
    return {"z_lp": hit[3], "greedy_total": float(greedy.total_price)}


def solve_guided(problem: Problem, max_alternatives: int = 60,
                 max_nodes: int = 8192, ng_slack: float = 1.0,
                 refinery=None, device_lp: bool = False, lp_health=None):
    """LP-guided solve: stripe the LP mix into concrete node fills, then
    run the pack kernel on what the LP cannot see.

    1. `exact_lp_mix` gives x[c,g] (pods of class c on option group g)
       and the implied node counts n_g.
    2. The floor of each x[c,g] is STRIPED across ceil(n_g) nodes —
       integral per-node fills that reproduce the LP's blend (sequential
       first-fit cannot: its prefix rule concentrates every class on the
       earliest nodes and measured +19-30% cost).
    3. Everything integrality leaves over — fractional parts, striping
       repairs, hostname-capped classes the pooled LP cannot reason
       about — is a small remainder solved by the ordinary scan kernel
       against the striped nodes' leftover free space (existing columns)
       plus fresh launches.

    Returns a PackingResult indistinguishable from the greedy path's, or
    None when the guide does not apply (degenerate instance, scipy
    missing).  The mix is content-cached on (classes ⊕ catalog).

    With a `refinery` (ops/refinery.GuideRefinery), a mix-cache miss
    never blocks the caller on column generation: the freshest stale mix
    whose catalog fingerprint still matches serves immediately (bounded
    by the refinery's staleness window), else the caller falls back to
    greedy for this tick — either way the exact problem signature is
    enqueued and the refined mix upgrades the next tick.

    With `device_lp` (the DeviceLP gate; inherited from the refinery's
    wiring when one is attached) a miss is answered by the PDHG solver
    IN the same tick — the refine completes synchronously, the
    stale-guide window closes, and no refine job is enqueued.  Only when
    the device path fails (non-convergence or certificate failure, which
    demote the `lp_health` ladder and publish a solver_demotion
    incident) does the miss fall back to the stale/greedy + background-
    refine behavior above — the HiGHS rung of the LP ladder.
    """
    from .classpack import resolve_alternatives, solve_classpack
    from .ffd import NodeDecision, PackingResult

    C0, R = problem.class_requests.shape
    O0 = problem.num_options
    if C0 < 2 or O0 == 0:
        return None
    caps = (problem.class_node_cap if problem.class_node_cap is not None
            else np.full(C0, _BIG, np.int32))

    if refinery is not None:
        device_lp = device_lp or getattr(refinery, "device_lp", False)
        lp_health = lp_health if lp_health is not None else \
            getattr(refinery, "lp_health", None)

    key, stale_key, shape_key = _mix_keys(problem, caps, max_nodes)
    if device_lp:
        # device mixes are valid but not byte-equal to HiGHS mixes
        # (first-order vs vertex optimum of the same LP) — namespace the
        # cache keys so gate-on and gate-off runs sharing one process
        # never serve each other's mixes (golden determinism)
        key, stale_key, shape_key = (b"d" + key, b"d" + stale_key,
                                     b"d" + shape_key)
    hit = _MIX_CACHE.get(key)
    path = "warm"
    if hit is None:
        device_ok = device_lp and (lp_health is None or
                                   lp_health.active_rung("device_lp") ==
                                   "device_lp")
        if device_ok:
            # DeviceLP rung healthy: refine synchronously ON the tick —
            # the PDHG masters are fast enough that a cold miss ships a
            # refined (non-greedy) guide with no stale window
            clock = refinery.clock if refinery is not None \
                else time.monotonic
            hit = _compute_mix(problem, caps, stale_key, shape_key,
                               clock=clock, device=True,
                               lp_health=lp_health)
            if hit is not None:
                _cache_put(_MIX_CACHE, _MIX_CACHE_MAX, key, hit)
                path = "device"
    if hit is None:
        if refinery is not None:
            # never block the tick on column generation: serve the
            # freshest matching stale mix (or greedy), refine off-tick
            hit = _stale_mix(problem, stale_key, caps, refinery.clock(),
                             refinery.stale_ttl)
            refinery.submit(key, lambda: _refine_job(
                problem, caps, max_nodes, key, stale_key, shape_key,
                refinery.clock, device=device_lp, lp_health=lp_health))
            if hit is None:
                metrics.lpguide_requests().inc({"path": "cold"})
                tracing.annotate(guide_path="cold")
                return None
            path = "stale"
        else:
            path = "cold"
            hit = _compute_mix(problem, caps, stale_key, shape_key)
            if hit is None:
                return None
            _cache_put(_MIX_CACHE, _MIX_CACHE_MAX, key, hit)
    metrics.lpguide_requests().inc({"path": path})
    tracing.annotate(guide_path=path)
    x, n_g, group_of, z_lp, ok, rejected = hit
    if rejected:
        return None
    # per-round launch-cap contract (review r5): the striper creates
    # nodes directly, so it must honor max_nodes like the kernel's K cap
    # does — when the LP fleet alone would blow the budget, the greedy
    # path owns the cap semantics (pack what fits, leave the rest
    # unschedulable for the next round)
    if int(np.ceil(n_g - 1e-9).sum()) > max_nodes:
        return None

    members_arr = problem.members_arrays()
    reqs_int = problem.class_requests.astype(np.int64)
    consumed = np.zeros(C0, np.int64)
    ptr = np.zeros(C0, np.int64)

    # ---- stripe each LP-used group into integral node fills ----
    # assembled fully vectorized: per class one np.repeat gives each pod's
    # node id; one global stable argsort + boundary split then yields the
    # per-node pod lists (the same pattern the kernel decode uses) — no
    # per-(class, node) Python loop at 50k-pod scale
    all_node_ids: list = []
    all_pod_ids: list = []
    all_cls_ids: list = []
    node_oi_parts: list = []
    node_used_parts: list = []
    node_base = 0
    for g in np.nonzero(n_g > 1e-6)[0]:
        members = np.nonzero(group_of == g)[0]
        if not len(members):
            continue
        oi = int(members[0])
        cls = np.nonzero(x[:, g] >= 1.0)[0]
        amounts = np.floor(x[cls, g]).astype(np.int64)
        amounts = np.minimum(amounts,
                             problem.class_counts[cls] - consumed[cls])
        keep = amounts > 0
        cls, amounts = cls[keep], amounts[keep]
        if not len(cls):
            continue
        ng = int(np.ceil(n_g[g] * ng_slack - 1e-9))
        fills, demoted = _stripe_group(
            amounts, ng, reqs_int[cls],
            problem.option_alloc[oi].astype(np.int64))
        placed = amounts - demoted
        consumed[cls] += placed
        nodes_of_group = np.arange(ng)
        for k, c in enumerate(cls):
            n_pl = int(placed[k])
            if n_pl == 0:
                continue
            node_ids = np.repeat(nodes_of_group, fills[:, k]) + node_base
            all_node_ids.append(node_ids)
            all_pod_ids.append(members_arr[c][ptr[c]:ptr[c] + n_pl])
            all_cls_ids.append(np.full(n_pl, c, np.int64))
            ptr[c] += n_pl
        node_oi_parts.append(np.full(ng, oi, np.int64))
        node_used_parts.append(fills @ reqs_int[cls])
        node_base += ng

    if not all_node_ids:
        return None
    node_ids = np.concatenate(all_node_ids)
    pod_ids = np.concatenate(all_pod_ids)
    cls_ids = np.concatenate(all_cls_ids)
    order = np.argsort(node_ids, kind="stable")
    node_ids, pod_ids, cls_ids = (node_ids[order], pod_ids[order],
                                  cls_ids[order])
    starts = np.nonzero(np.diff(node_ids, prepend=np.int64(-1)))[0]
    ends = np.append(starts[1:], len(node_ids))
    occupied = node_ids[starts]                 # node id per non-empty node
    all_oi = np.concatenate(node_oi_parts) if node_oi_parts else \
        np.zeros(0, np.int64)
    all_used = np.concatenate(node_used_parts) if node_used_parts else \
        np.zeros((0, R), np.int64)
    bulk_oi = all_oi[occupied].tolist()
    bulk_pods = [pod_ids[s:e].tolist() for s, e in zip(starts, ends)]
    # duplicates are fine downstream (joint compat ANDs idempotently), so
    # skip the ~per-node np.unique
    bulk_cls = [cls_ids[s:e].tolist() for s, e in zip(starts, ends)]

    if not bulk_oi:
        return None

    # ---- cross-group tuck: demoted pods into ANY bulk node with room ----
    # Striping strands slivers per node (≈1-2% of bulk capacity) while
    # demoting the pods that no longer fit their OWN group; across groups
    # those slivers add up to whole node-equivalents.  One host-side
    # least-loaded pass over the entire fleet (compat-checked against each
    # node's option) re-places most demotions for free — measured 12%→
    # remainder drop to a few % on 50k-burst — and lets the remainder
    # solve run WITHOUT existing columns, keeping the fresh kernel's
    # compiled shapes.  Hostname-capped classes stay out (their per-node
    # caps need the kernel).
    rem = problem.class_counts.astype(np.int64) - consumed
    alloc_int = problem.option_alloc.astype(np.int64)
    used_mat = all_used[occupied].astype(np.int64)
    node_oi_arr = np.asarray(bulk_oi, np.int64)
    free_mat = alloc_int[node_oi_arr] - used_mat
    inv_node_alloc = 1.0 / np.maximum(alloc_int[node_oi_arr], 1)
    tuck_order = np.argsort(
        -(reqs_int / np.maximum(alloc_int.mean(axis=0), 1)).max(axis=1))
    # tucked placements accumulate as (node, pod, class) ARRAYS — one
    # np.repeat-style slice per round, one global stable argsort +
    # boundary split at the end — instead of a per-pod Python append loop
    # (O(remainder-pods) interpreter work on the 50k decode path)
    tuck_node_idx: list = []
    tuck_pod_ids: list = []
    tuck_cls_ids: list = []
    for c in tuck_order:
        if rem[c] <= 0:
            continue
        rc = reqs_int[c]
        # RAW compat, not the rank-restricted mask: pool-weight precedence
        # governs what to LAUNCH, never what already-bought capacity may
        # host (same rule as the kernel's existing columns; review r5)
        node_ok = problem.class_compat[c][node_oi_arr]
        # hostname-capped classes tuck too: striped bulk nodes host none
        # of their pods, so a fresh per-node counter enforces the cap
        # exactly (review r5: skipping them forced fresh launches for
        # pods the fleet's slivers could legally hold)
        placed_c = np.zeros(len(node_oi_arr), np.int64)
        cap_c = int(caps[c])
        mem = members_arr[c]
        while rem[c] > 0:
            fits = node_ok & (free_mat >= rc[None, :]).all(axis=1) & \
                (placed_c < cap_c)
            n_fit = int(fits.sum())
            if n_fit == 0:
                break
            take = min(int(rem[c]), n_fit)
            if take < n_fit:
                load = np.max(used_mat * inv_node_alloc, axis=1)
                load[~fits] = np.inf
                sel = np.argpartition(load, take - 1)[:take]
            else:
                sel = np.nonzero(fits)[0]
            tuck_node_idx.append(sel.astype(np.int64))
            tuck_pod_ids.append(mem[ptr[c]:ptr[c] + take])
            tuck_cls_ids.append(np.full(take, c, np.int64))
            ptr[c] += take
            used_mat[sel] += rc
            free_mat[sel] -= rc
            placed_c[sel] += 1
            consumed[c] += take
            rem[c] -= take
    if tuck_node_idx:
        tni = np.concatenate(tuck_node_idx)
        tpi = np.concatenate(tuck_pod_ids)
        tci = np.concatenate(tuck_cls_ids)
        t_order = np.argsort(tni, kind="stable")
        tni, tpi, tci = tni[t_order], tpi[t_order], tci[t_order]
        t_starts = np.nonzero(np.diff(tni, prepend=np.int64(-1)))[0]
        t_ends = np.append(t_starts[1:], len(tni))
        for s, e in zip(t_starts, t_ends):
            i = int(tni[s])
            bulk_pods[i].extend(tpi[s:e].tolist())
            # duplicates fine: cls_keys below sets/sorts per node
            bulk_cls[i].extend(tci[s:e].tolist())

    # ---- remainder: what even the tuck couldn't place, capped classes ----
    rem_cls = np.nonzero(rem > 0)[0]
    sub_res = None
    if len(rem_cls):
        sub = _subproblem(problem, rem_cls, rem[rem_cls], ptr)
        # fresh-only solve: the tuck already consumed the fleet's usable
        # slivers, so existing columns would add kernel shape variants for
        # nothing.  A fully consumed launch budget removes the catalog
        # outright — then these pods come back unschedulable for the next
        # round (review r5: the old max(1, …) floor leaked an extra node).
        budget = max_nodes - len(bulk_oi)
        if budget <= 0:
            sub.options = []
            sub.option_alloc = sub.option_alloc[:0]
            sub.option_price = sub.option_price[:0]
            if sub.option_rank is not None:
                sub.option_rank = sub.option_rank[:0]
            if sub.option_zone is not None:
                sub.option_zone = sub.option_zone[:0]
            if sub.option_captype is not None:
                sub.option_captype = sub.option_captype[:0]
            sub.class_compat = sub.class_compat[:, :0]
            budget = 0
        sub_res = solve_classpack(sub, max_nodes=max(budget, 1),
                                  decode=True, guide=None,
                                  max_alternatives=max_alternatives)

    # ---- merge ----
    unschedulable: list = []
    new_nodes: list = []
    total = 0.0
    if sub_res is not None:
        unschedulable = sub_res.unschedulable
        new_nodes = sub_res.nodes
        total += sub_res.total_price

    # acceptance gate: when integrality friction blows the result past
    # the guide's design envelope (tiny fleets, where one node of ceil
    # slack is a large relative cost), price the greedy ALTERNATIVE with
    # one cheap aggregate solve and keep whichever plan is actually
    # better.  The envelope check means the extra kernel call only
    # happens on suspicious instances, never on the bench/product hot
    # path; rejections are remembered so re-solves skip straight to
    # greedy.
    probe_total = (sub_res.total_price if sub_res is not None else 0.0) + \
        sum(float(problem.option_price[oi]) for oi in bulk_oi)
    probe_unsched = len(unschedulable)
    # z_lp excludes hostname-capped classes, so on cap-heavy workloads
    # the envelope check would mis-trigger every solve (review r5) — the
    # envelope is only meaningful when the LP priced most of the demand
    capped_frac = float(problem.class_counts[caps < _BIG].sum()) / \
        max(float(problem.class_counts.sum()), 1.0)
    if z_lp > 0 and capped_frac < 0.5 and probe_total > 1.08 * z_lp:
        greedy = solve_classpack(problem, max_nodes=max_nodes, decode=False,
                                 guide=None)
        # strictly worse only: a tie keeps the guided plan (its decode is
        # already materialized) instead of permanently rejecting the key
        if (probe_unsched, probe_total) > (len(greedy.unschedulable),
                                           greedy.total_price):
            hit[5] = True
            return None

    # memo keys are the nodes' class SETS — joint-compat bits are only
    # computed for memo misses inside resolve_alternatives (a fleet-wide
    # AND costs ~100ms at 50k; the distinct keys are a few hundred)
    cls_keys = [tuple(sorted(set(cl))) for cl in bulk_cls]
    resolved = resolve_alternatives(problem, bulk_oi, None, used_mat,
                                    max_alternatives, cls_keys=cls_keys)
    nodes = []
    for i, oi in enumerate(bulk_oi):
        alts, used_rl = resolved[i]
        nodes.append(NodeDecision(option=problem.options[oi],
                                  pod_indices=bulk_pods[i],
                                  used=used_rl, alternatives=alts))
        total += float(problem.option_price[oi])
    nodes.extend(new_nodes)
    return PackingResult(nodes=nodes, unschedulable=unschedulable,
                         existing_assignments={}, total_price=total)


def _subproblem(problem: Problem, cls: np.ndarray, counts: np.ndarray,
                ptr: np.ndarray) -> Problem:
    """A Problem restricted to `cls` with `counts` pods each, whose member
    lists are the UNCONSUMED tails of the original classes — so every pod
    index in the sub-solve's result is a real original pod id."""
    import copy
    members_arr = problem.members_arrays()
    sub = copy.copy(problem)
    sub.class_requests = problem.class_requests[cls]
    sub.class_counts = counts.astype(np.int32)
    sub.class_compat = problem.class_compat[cls]
    if problem.class_node_cap is not None:
        sub.class_node_cap = problem.class_node_cap[cls]
    sub.class_members = [members_arr[c][ptr[c]:ptr[c] + n]
                         for c, n in zip(cls, counts)]
    sub.__dict__.pop("_members_arr", None)
    sub.__dict__.pop("_class_order", None)
    return sub
