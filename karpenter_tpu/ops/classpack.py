"""Class-granular packing: one scan step per pod *equivalence class*.

The key TPU-first re-design of the reference's FFD loop
(/root/reference/designs/bin-packing.md:16-43): identical pods are
interchangeable, so a batch of 50k pods usually collapses to a few hundred
classes (the reference batches "similar pods" the same way, just one pod at a
time).  Each scan step places an entire class:

  * existing/open slots absorb `min(count, floor(free/req))` pods each —
    a K-vector computation with an exclusive-cumsum greedy fill that is
    exactly first-fit for identical pods;
  * overflow opens `ceil(rem/m)` new nodes of the option minimizing
    price-per-pod (the reference's "instance type that maximizes additional
    pods packed" heuristic, re-expressed as a cost score).

All arithmetic is int32 in scaled units (millicores / MiB / counts) so
feasibility math is exact — no float rounding can overfill a node.
Complexity: O(C × (K + O) × R) data-parallel work instead of the reference's
O(P × nodes × types) pointer-chasing loop.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api.resources import ResourceList
from ..utils import metrics, tracing
from .ffd import SCORE_CAP, NodeDecision, PackingResult
from .tensorize import LaunchOption, Problem, pad_to

log = logging.getLogger("karpenter_tpu.classpack")

_BIG = np.int32(2**30)

# one lock for all module caches: check-then-insert must be atomic or
# concurrent misses overshoot the size caps (the ops are once-per-solve,
# so the lock costs nothing against a device dispatch)
import threading
_CACHE_LOCK = threading.Lock()


@partial(jax.jit, static_argnames=("max_nodes", "emit_takes"))
def class_pack_kernel(requests: jax.Array,   # C×R int32, classes FFD-sorted
                      counts: jax.Array,     # C int32
                      compat: jax.Array,     # C×(O+E) bool
                      node_cap: jax.Array,   # C int32 max class pods per node
                      alloc: jax.Array,      # (O+E)×R int32
                      price: jax.Array,      # (O+E) f32; +inf == not launchable
                      rank: jax.Array,       # (O+E) int32 pool-weight rank
                      init_option: jax.Array,  # K int32, -1 closed
                      init_used: jax.Array,    # K×R int32
                      max_nodes: int,
                      emit_takes: bool = False):
    """`node_cap` lowers hostname-granular topology constraints (hostname
    anti-affinity -> 1, hostname spread -> max_skew; see ops/constraints.py).
    Each class is placed in exactly one scan step, so clamping per-slot and
    per-new-node occupancy inside the step enforces the cap exactly.

    Scan-hoisting: everything that depends only on (class × option) — pods
    per fresh node, launchability, pool-rank preselection — is one batched
    C×O computation BEFORE the scan (XLA fuses the R-reduction, nothing
    C×O×R materializes); the scan carries slot FREE space rather than used,
    so the per-step work is pure K-vector arithmetic with no O×R division
    and no K×R gather left inside the sequential region."""
    K = max_nodes
    idx = jnp.arange(K)

    # ---- per-(class × option) precompute, hoisted out of the scan ----
    reqpos_all = requests > 0                                # C×R
    safe_req_all = jnp.where(reqpos_all, requests, 1)
    m_all = jnp.min(jnp.where(reqpos_all[:, None, :],
                              alloc[None, :, :] // safe_req_all[:, None, :],
                              _BIG), axis=-1)                # C×O pods/node
    m_all = jnp.minimum(m_all, node_cap[:, None])            # hostname cap
    ok_all = compat & (m_all > 0) & jnp.isfinite(price)[None, :]
    # pool precedence: restrict to the best (lowest) weight-rank available
    best_rank_all = jnp.min(jnp.where(ok_all, rank[None, :], _BIG), axis=1)
    ok_all = ok_all & (rank[None, :] == best_rank_all[:, None])

    def step(carry, x):
        slot_option, slot_free, n_open, n_unsched = carry
        req, cnt, comp, cap, m, ok = x
        opt = jnp.maximum(slot_option, 0)
        open_mask = slot_option >= 0
        reqpos = req > 0
        safe_req = jnp.where(reqpos, req, 1)
        fit = jnp.min(jnp.where(reqpos[None, :],
                                slot_free // safe_req[None, :], _BIG),
                      axis=-1)                              # pods each slot absorbs
        fit = jnp.minimum(fit, cap)                         # hostname-cap clamp
        fit = jnp.where(open_mask & comp[opt], jnp.maximum(fit, 0), 0)
        prefix = jnp.cumsum(fit) - fit                      # exclusive cumsum
        take = jnp.clip(cnt - prefix, 0, fit)               # greedy first-fit fill
        remaining = cnt - jnp.sum(take)

        # new nodes: option minimizing TOTAL cost to absorb the class tail,
        # price × ceil(remaining/m) — the tail-aware version of the
        # reference's "maximize additional pods packed" tie-break
        m_safe = jnp.maximum(m, 1)
        nodes_needed = (jnp.maximum(remaining, 1) + m_safe - 1) // m_safe
        # clamp before the finiteness test: a viable option whose
        # price × nodes_needed overflows float32 must stay schedulable
        # (and comparable) rather than read as "no option fits"
        score = jnp.where(
            ok,
            jnp.minimum(price * nodes_needed.astype(price.dtype),
                        jnp.asarray(SCORE_CAP, price.dtype)),
            jnp.inf)
        j = jnp.argmin(score)                               # ties → cheapest (pre-sorted)
        can = jnp.isfinite(score[j])
        m_sel = jnp.maximum(m[j], 1)
        needed = jnp.where(can & (remaining > 0),
                           (remaining + m_sel - 1) // m_sel, 0)
        n_new = jnp.minimum(needed, K - n_open)
        sched_new = jnp.minimum(remaining, n_new * m_sel)
        is_new = (idx >= n_open) & (idx < n_open + n_new)
        pods_on = jnp.where(is_new, m_sel, 0)
        rem_last = sched_new - (n_new - 1) * m_sel          # last node partial
        pods_on = jnp.where(is_new & (idx == n_open + n_new - 1), rem_last, pods_on)
        slot_option = jnp.where(is_new, j.astype(slot_option.dtype), slot_option)
        placed = take + pods_on
        slot_free = slot_free - take[:, None] * req[None, :]
        slot_free = jnp.where(is_new[:, None],
                              alloc[j][None, :] - pods_on[:, None] * req[None, :],
                              slot_free)
        n_open = n_open + n_new
        n_unsched = n_unsched + (remaining - sched_new)
        carry = (slot_option, slot_free, n_open, n_unsched)
        return carry, (placed if emit_takes else jnp.sum(take))

    C = requests.shape[0]
    n_open0 = jnp.sum(init_option >= 0).astype(jnp.int32)
    init_free = jnp.where((init_option >= 0)[:, None],
                          alloc[jnp.maximum(init_option, 0)] - init_used,
                          0)
    # derive the zero from n_open0 so carry types (incl. shard_map varying-
    # axis annotations) stay consistent between init and body outputs
    (slot_option, slot_free, n_open, n_unsched), takes = jax.lax.scan(
        step, (init_option, init_free, n_open0, jnp.zeros_like(n_open0)),
        (requests, counts, compat, node_cap, m_all, ok_all),
        unroll=8)  # amortize per-step sequencing overhead on TPU
    slot_used = jnp.where((slot_option >= 0)[:, None],
                          alloc[jnp.maximum(slot_option, 0)] - slot_free,
                          0)
    return slot_option, slot_used, n_open, n_unsched, takes


@partial(jax.jit, static_argnames=("max_nodes",))
def class_pack_aggregate_kernel(requests, counts, compat, node_cap,
                                alloc, price, rank,
                                init_option, init_used, max_nodes: int):
    """Pack and reduce to the aggregate launch plan ON DEVICE, returning one
    flat float32 vector: [total_cost, n_open, n_unsched, nodes_per_option…].

    Rationale: the actuation layer only needs "how many nodes of which
    option"; collapsing to a single device→host transfer matters both on
    tunneled dev TPUs (~70ms per D2H round trip) and real pods (syncs stall
    the dispatch pipeline)."""
    slot_option, slot_used, n_open, n_unsched, _ = class_pack_kernel(
        requests, counts, compat, node_cap, alloc, price, rank,
        init_option, init_used, max_nodes, False)
    opt = jnp.maximum(slot_option, 0)
    # count only newly-launchable options: pre-opened (virtual) and padded
    # columns carry +inf price
    launched = (slot_option >= 0) & jnp.isfinite(price[opt])
    nodes_per_option = jnp.zeros((alloc.shape[0],), jnp.float32).at[opt].add(
        launched.astype(jnp.float32))
    total_cost = jnp.sum(jnp.where(launched, price[opt], 0.0))
    head = jnp.stack([total_cost, n_open.astype(jnp.float32),
                      n_unsched.astype(jnp.float32)])
    return jnp.concatenate([head, nodes_per_option])


@partial(jax.jit, static_argnames=("max_nodes", "emit_takes"))
def class_pack_kernel_packed(requests, counts, compat_packed, node_cap,
                             alloc, price, rank, init_option, init_used,
                             max_nodes: int, emit_takes: bool = False):
    """class_pack_kernel taking a bit-packed compat matrix (uint8, packbits
    along options).  The C×O bool mask dominates host→device transfer on
    tunneled TPUs; shipping bits cuts it 8× and the unpack fuses into the
    compiled program."""
    compat = jnp.unpackbits(compat_packed, axis=1,
                            count=alloc.shape[0]).astype(bool)
    return class_pack_kernel(requests, counts, compat, node_cap, alloc,
                             price, rank, init_option, init_used,
                             max_nodes, emit_takes)


@partial(jax.jit, static_argnames=("max_nodes",))
def class_pack_aggregate_kernel_packed(requests, counts, compat_packed,
                                       node_cap, alloc, price, rank,
                                       init_option, init_used, max_nodes: int):
    compat = jnp.unpackbits(compat_packed, axis=1,
                            count=alloc.shape[0]).astype(bool)
    return class_pack_aggregate_kernel(requests, counts, compat, node_cap,
                                       alloc, price, rank, init_option,
                                       init_used, max_nodes)


@partial(jax.jit, static_argnames=("max_nodes", "n_pods"))
def class_pack_assign_kernel(requests, counts, compat_packed, node_cap,
                             alloc, price, rank, init_option, init_used,
                             max_nodes: int, n_pods: int):
    """Pack and decode POD→SLOT assignments on device.

    The takes matrix (C×K placement counts) is the full decode information,
    but shipping it to the host costs O(C×K) transfer — ~8MB at 50k pods,
    seconds over a tunneled link. Instead the per-pod slot is derived here:
    within a class, pod #r lands in the first slot where the class's
    inclusive take-cumsum exceeds r; flattening the cumsum over (class, slot)
    keeps it one global searchsorted. Only O(P + K) ints leave the device —
    the tunnel moves ~7MB/s, so every byte of result payload is latency:
    the assignment ships as int16 when K allows (slot ids < 2^15) and
    per-slot resource usage is NOT returned at all (the host reconstructs
    it from the assignment with one reduceat — saves a K×R transfer)."""
    slot_option, _slot_used, n_open, n_unsched, takes = class_pack_kernel_packed(
        requests, counts, compat_packed, node_cap, alloc, price, rank,
        init_option, init_used, max_nodes, True)
    C = counts.shape[0]
    K = max_nodes
    flat = jnp.cumsum(takes.reshape(-1))                  # (C*K,) global cumsum
    ends = flat[K - 1::K]                                 # total through class c
    base = jnp.concatenate([jnp.zeros(1, flat.dtype), ends[:C - 1]])
    totals = ends - base                                  # per-class scheduled
    class_ids = jnp.repeat(jnp.arange(C, dtype=jnp.int32), counts,
                           total_repeat_length=n_pods)
    cnt_csum = jnp.concatenate(
        [jnp.zeros(1, counts.dtype), jnp.cumsum(counts)])[:-1]
    rank_in_class = (jnp.arange(n_pods, dtype=jnp.int32)
                     - cnt_csum[class_ids])
    q = base[class_ids] + rank_in_class
    f = jnp.searchsorted(flat, q, side="right").astype(jnp.int32)
    slot = f - class_ids * K
    sched = rank_in_class < totals[class_ids]
    assignment = jnp.where(sched, slot, -1)
    if K < 2**15:
        assignment = assignment.astype(jnp.int16)
    return assignment, slot_option, n_unsched


@partial(jax.jit, static_argnames=("max_nodes", "n_pods"))
def class_pack_assign_kernel_fresh(requests, counts, compat_packed,
                                   node_cap, alloc, price, rank,
                                   max_nodes: int, n_pods: int):
    """Assign kernel with NO pre-opened slots: the all-closed init state
    (K ints + K×R zeros ≈ 260KB at 50k pods) materializes on device
    instead of riding the ~7MB/s tunnel every fresh solve."""
    R = alloc.shape[1]
    init_option = jnp.full((max_nodes,), -1, jnp.int32)
    init_used = jnp.zeros((max_nodes, R), jnp.int32)
    return class_pack_assign_kernel(requests, counts, compat_packed,
                                    node_cap, alloc, price, rank,
                                    init_option, init_used, max_nodes, n_pods)


@partial(jax.jit, static_argnames=("max_nodes", "n_pods"))
def class_pack_assign_slab_kernel(requests, counts, compat_packed, node_cap,
                                  alloc, price, rank, init_option, init_used,
                                  max_nodes: int, n_pods: int):
    """Assign kernel + on-device SLAB emission for vectorized decode.

    The slab is the pod→node plan in the exact shape the columnar host
    assembler (ops/decode.py) consumes: row ids stable-sorted by slot
    (`order`), per-slot run lengths (`slot_counts`), and the slot→option
    column.  Sorting on device means the host never touches a per-pod
    value again — every decode artifact becomes a gather over `order`.

    Unscheduled AND padded rows (class_ids saturate to C-1 past the real
    pod count, rank >= totals) both carry assignment -1; they sort to the
    back under key=K, and because the sort is stable the real unscheduled
    rows (index < P) stay in row order AHEAD of padding (index >= P) — so
    order[S:S+u] is exactly the legacy unschedulable list.  The K+1-bin
    scatter gives the overflow key an explicit bin instead of relying on
    out-of-bounds drop semantics; it is sliced off before shipping."""
    assignment, slot_option, n_unsched = class_pack_assign_kernel(
        requests, counts, compat_packed, node_cap, alloc, price, rank,
        init_option, init_used, max_nodes, n_pods)
    K = max_nodes
    a = assignment.astype(jnp.int32)
    key = jnp.where(a >= 0, a, K)
    if (K + 1) * n_pods < 2**31:
        # stable sort via a single-operand sort of the composite
        # key*P + row: unique values, (key, row)-lexicographic, so the
        # sorted residue IS the stable order — ~5x faster than the
        # two-operand comparator sort argsort lowers to on CPU
        comp = key * n_pods + jnp.arange(n_pods, dtype=jnp.int32)
        order = (jnp.sort(comp) % n_pods).astype(jnp.int32)
    else:
        order = jnp.argsort(key).astype(jnp.int32)
    slot_counts = jnp.zeros((K + 1,), jnp.int32).at[key].add(1)[:K]
    return order, slot_counts, slot_option, n_unsched


@partial(jax.jit, static_argnames=("max_nodes", "n_pods"))
def class_pack_assign_slab_kernel_fresh(requests, counts, compat_packed,
                                        node_cap, alloc, price, rank,
                                        max_nodes: int, n_pods: int):
    """Slab kernel with NO pre-opened slots (init state materializes on
    device, same rationale as the other *_fresh variants)."""
    R = alloc.shape[1]
    init_option = jnp.full((max_nodes,), -1, jnp.int32)
    init_used = jnp.zeros((max_nodes, R), jnp.int32)
    return class_pack_assign_slab_kernel(requests, counts, compat_packed,
                                         node_cap, alloc, price, rank,
                                         init_option, init_used,
                                         max_nodes, n_pods)


@partial(jax.jit, static_argnames=("max_nodes",))
def class_pack_aggregate_kernel_fresh(requests, counts, compat_packed,
                                      node_cap, alloc, price, rank,
                                      max_nodes: int):
    """Aggregate solve with NO pre-opened slots: the all-closed init state
    materializes on device instead of shipping ~200KB of -1s/zeros across
    the host link every call (each host→device transfer is a round trip on
    tunneled TPUs)."""
    R = alloc.shape[1]
    init_option = jnp.full((max_nodes,), -1, jnp.int32)
    init_used = jnp.zeros((max_nodes, R), jnp.int32)
    return class_pack_aggregate_kernel_packed(
        requests, counts, compat_packed, node_cap, alloc, price, rank,
        init_option, init_used, max_nodes)


@partial(jax.jit, static_argnames=("max_nodes",))
def class_pack_sweep_kernel(requests, counts_b, compat_packed, node_cap,
                            alloc, price, rank, col_mask_b, price_cap_b,
                            init_option, init_used, max_nodes: int):
    """B masked aggregate solves in ONE device call (vmap over the batch
    axis) — the consolidation sweep's kernel.

    Shared (unbatched): the padded class arrays, the column catalog
    (options + existing-node columns), and the pre-opened slot state.
    Per-sub-problem (leading B axis): `counts_b` (which classes this probe
    reschedules), `col_mask_b` (False == this column is excluded — the
    probe's "what if these nodes were gone"), and `price_cap_b` (options
    priced >= cap are unlaunchable, the strictly-cheaper replacement rule).

    Everything derived only from the shared arrays (pods-per-node m_all,
    the compat unpack) stays unbatched under vmap, so the B-fold cost is
    the scan itself — B sequential probes become one padded program with a
    single B×3 device→host fetch: [total_cost, n_new, n_unsched] per row."""
    compat = jnp.unpackbits(compat_packed, axis=1,
                            count=alloc.shape[0]).astype(bool)

    def one(counts, colmask, cap):
        comp = compat & colmask[None, :]
        pr = jnp.where(colmask & (price < cap), price, jnp.inf)
        flat = class_pack_aggregate_kernel(
            requests, counts, comp, node_cap, alloc, pr, rank,
            init_option, init_used, max_nodes)
        # n_new from the per-option launch counts, NOT n_open: pre-opened
        # existing columns carry +inf price and never count as launches
        return jnp.stack([flat[0], jnp.sum(flat[3:]), flat[2]])

    return jax.vmap(one)(counts_b, col_mask_b, price_cap_b)


# batch-axis padding buckets for the sweep (compile reuse across candidate
# counts), and a memory guard: the vmapped ok_all mask materializes
# B×Cpad×Opad bools, so the per-call batch is clamped to keep that under
# ~256M elements — larger sweeps chunk into several calls
_SWEEP_B_BUCKETS = (8, 32, 128, 512)
_SWEEP_MASK_BUDGET = 1 << 28


def solve_classpack_sweep(problem: Problem,
                          counts_b: np.ndarray,
                          existing_alloc: Optional[np.ndarray] = None,
                          existing_used: Optional[np.ndarray] = None,
                          existing_compat: Optional[np.ndarray] = None,
                          exist_mask_b: Optional[np.ndarray] = None,
                          price_cap_b: Optional[np.ndarray] = None,
                          max_nodes: int = 8192):
    """Host wrapper for the batched sweep: one padding/lowering pass shared
    by all B sub-problems, then bucket-padded kernel calls.

    `counts_b` (B×C, problem class order) gives each sub-problem's pod
    multiset; classes with count 0 are exact no-ops in the scan.
    `exist_mask_b` (B×E bool, False == excluded) masks existing-node
    columns per sub-problem; `price_cap_b` (B float) strictly bounds
    launchable option prices (None/inf == no cap).  Returns a SweepResult
    whose rows match what decode=False solve_classpack calls over the
    same masked sub-problems would report."""
    from .ffd import SweepResult

    E = 0 if existing_alloc is None else len(existing_alloc)
    ec = None
    if E:
        ec = existing_compat if existing_compat is not None else \
            np.ones((problem.num_classes, E), bool)
    order = problem.class_order()
    requests = problem.class_requests[order]
    compat = problem.class_compat[order]
    if ec is not None:
        compat = np.concatenate([compat, ec[order]], axis=1)
    caps = (problem.class_node_cap if problem.class_node_cap is not None
            else np.full(problem.num_classes, 2**30, np.int32))[order]
    counts_b = np.asarray(counts_b, np.int32)[:, order]
    B, C = counts_b.shape
    R = requests.shape[1]

    alloc = problem.option_alloc
    price = problem.option_price.astype(np.float32)
    O = alloc.shape[0]
    if E:
        alloc = np.concatenate([alloc, existing_alloc.astype(np.float32)],
                               axis=0)
        price = np.concatenate([price, np.full(E, np.inf, np.float32)])
    if alloc.shape[0] == 0:
        per = counts_b.sum(axis=1).astype(np.int32)
        return SweepResult(total_price=np.zeros(B, np.float32),
                           new_nodes=np.zeros(B, np.int32),
                           unschedulable=per, device_calls=0)
    rank = np.zeros(alloc.shape[0], np.int32)
    rank[:O] = problem.option_rank

    Cpad = pad_to(C, (64, 256, 1024, 4096))
    Opad = pad_to(alloc.shape[0], (512, 2048, 4096, 8192, 32768))
    req_p = np.zeros((Cpad, R), np.int32)
    req_p[:C] = requests.astype(np.int32)
    cap_p = np.full(Cpad, 2**30, np.int32)
    cap_p[:C] = caps
    comp_p = np.zeros((Cpad, Opad), bool)
    comp_p[:C, :alloc.shape[0]] = compat
    packed = np.packbits(comp_p, axis=1)
    # int32 lowering TRUNCATES fractional allocatable exactly like
    # solve_classpack's astype — ceil here would let the sweep fit a pod
    # the sequential probe rejects
    alloc_p = np.zeros((Opad, R), np.int32)
    alloc_p[:alloc.shape[0]] = alloc.astype(np.int32)
    price_p = np.full(Opad, np.inf, np.float32)
    price_p[:alloc.shape[0]] = price
    rank_p = np.full(Opad, 2**30 - 1, np.int32)
    rank_p[:alloc.shape[0]] = rank

    # finer slot buckets than the single-solve path: the vmapped scan's
    # per-step cost is B×K, so a 1229-slot problem landing in an 8192
    # bucket would cost 6.7x its useful work ACROSS THE WHOLE BATCH.
    # K = P + E always suffices: each scan step opens at most one node per
    # remaining pod, so new slots never exceed the row's pod count
    P = int(counts_b.sum(axis=1).max()) if B else 0
    K = max(min(max_nodes,
                pad_to(P + E, (256, 512, 1024, 2048, 4096, 8192))),
            E + 1)
    init_option = np.full(K, -1, np.int32)
    init_used = np.zeros((K, R), np.int32)
    if E:
        init_option[:E] = np.arange(O, O + E, dtype=np.int32)
        if existing_used is not None:
            init_used[:E] = np.ceil(existing_used).astype(np.int32)

    cnt_p = np.zeros((B, Cpad), np.int32)
    cnt_p[:, :C] = counts_b
    mask_p = np.zeros((B, Opad), bool)
    mask_p[:, :alloc.shape[0]] = True
    if E and exist_mask_b is not None:
        mask_p[:, O:O + E] = np.asarray(exist_mask_b, bool)
    caps_b = (np.full(B, np.inf, np.float32) if price_cap_b is None
              else np.asarray(price_cap_b, np.float32))

    chunk = max(_SWEEP_B_BUCKETS[0], _SWEEP_MASK_BUDGET // (Cpad * Opad))
    chunk = next((b for b in _SWEEP_B_BUCKETS if b >= min(chunk, B)),
                 _SWEEP_B_BUCKETS[-1])
    d_req, d_packed, d_cap = (jnp.asarray(req_p), jnp.asarray(packed),
                              jnp.asarray(cap_p))
    d_alloc, d_price, d_rank = (jnp.asarray(alloc_p), jnp.asarray(price_p),
                                jnp.asarray(rank_p))
    d_iopt, d_iused = jnp.asarray(init_option), jnp.asarray(init_used)
    cost = np.zeros(B, np.float32)
    n_new = np.zeros(B, np.int32)
    unsched = np.zeros(B, np.int32)
    calls = 0
    for s in range(0, B, chunk):
        e = min(s + chunk, B)
        Bp = next(b for b in _SWEEP_B_BUCKETS if b >= e - s) \
            if e - s <= _SWEEP_B_BUCKETS[-1] else e - s
        cb = np.zeros((Bp, Cpad), np.int32)
        cb[:e - s] = cnt_p[s:e]
        mb = np.zeros((Bp, Opad), bool)
        mb[:e - s] = mask_p[s:e]
        pb = np.full(Bp, np.inf, np.float32)
        pb[:e - s] = caps_b[s:e]
        out = np.asarray(class_pack_sweep_kernel(
            d_req, jnp.asarray(cb), d_packed, d_cap, d_alloc, d_price,
            d_rank, jnp.asarray(mb), jnp.asarray(pb), d_iopt, d_iused, K))
        calls += 1
        cost[s:e] = out[:e - s, 0]
        n_new[s:e] = np.rint(out[:e - s, 1]).astype(np.int32)
        unsched[s:e] = np.rint(out[:e - s, 2]).astype(np.int32)
    tracing.annotate(device_calls=calls, sweep_rows=B, sweep_chunk=chunk)
    return SweepResult(total_price=cost, new_nodes=n_new,
                       unschedulable=unsched, device_calls=calls)


# device-resident catalog cache: (content fingerprint, device) → jax arrays.
# The catalog side (alloc/price/rank) changes only on ICE/pricing seq bumps,
# so consecutive solves reuse the same device buffers instead of re-uploading.
_CATALOG_CACHE: dict = {}
_CATALOG_CACHE_MAX = 8

# device-resident pod-side cache: content hash of the padded class arrays →
# uploaded jax arrays.  Re-solves over an unchanged pending set — capacity
# retries, consolidation probes, the provisioner's next tick before pods
# bind — skip the host→device transfer entirely (each upload is a round
# trip on tunneled dev TPUs; the catalog side already works this way).
_PODSIDE_CACHE: dict = {}
_PODSIDE_CACHE_MAX = 8


def _device_podside(req_p: np.ndarray, cnt_p: np.ndarray,
                    packed: np.ndarray, cap_p: np.ndarray):
    import hashlib
    key = (req_p.shape, packed.shape,
           hashlib.blake2b(req_p.tobytes() + cnt_p.tobytes()
                           + packed.tobytes() + cap_p.tobytes(),
                           digest_size=16).digest())
    hit = _PODSIDE_CACHE.get(key)
    if hit is not None:
        tracing.annotate(podside_cache="hit")
        return hit
    tracing.annotate(podside_cache="miss")
    val = (jnp.asarray(req_p), jnp.asarray(cnt_p), jnp.asarray(packed),
           jnp.asarray(cap_p))
    with _CACHE_LOCK:
        while len(_PODSIDE_CACHE) >= _PODSIDE_CACHE_MAX:
            _PODSIDE_CACHE.pop(next(iter(_PODSIDE_CACHE)), None)
        _PODSIDE_CACHE[key] = val
    return val


# cross-solve alternatives memo.  A node's flexible-alternative list depends
# only on (catalog columns, joint class compat, pool, usage vector) — all
# content below is keyed by content, never by class *indices* (which are
# batch-specific), so hits are exact across different pod batches.  The
# outer key pins the catalog identity via the option_alloc/options object
# pair (kept as a strong ref so ids can't be recycled while the entry
# lives); the catalog-side cache in ops/tensorize.py already dedups equal
# catalogs to one object, so object identity == content identity here.
_ALT_MEMO: dict = {}
_ALT_MEMO_MAX_CATALOGS = 4
_ALT_MEMO_MAX_ENTRIES = 65536


def _alt_memo_for(problem: Problem) -> dict:
    key = id(problem.options)
    hit = _ALT_MEMO.get(key)
    if hit is not None and hit[0] is problem.options:
        if len(hit[1]) > _ALT_MEMO_MAX_ENTRIES:
            hit[1].clear()
        return hit[1]
    with _CACHE_LOCK:
        hit = _ALT_MEMO.get(key)
        if hit is not None and hit[0] is problem.options:
            return hit[1]
        while len(_ALT_MEMO) >= _ALT_MEMO_MAX_CATALOGS:
            _ALT_MEMO.pop(next(iter(_ALT_MEMO)), None)
        entries: dict = {}
        _ALT_MEMO[key] = (problem.options, entries)
        return entries


def _device_catalog(alloc: np.ndarray, price: np.ndarray, rank: np.ndarray):
    import hashlib
    key = (alloc.shape, price.shape, rank.shape,
           hashlib.blake2b(
               alloc.tobytes() + price.tobytes() + rank.tobytes(),
               digest_size=16).digest())
    hit = _CATALOG_CACHE.get(key)
    if hit is not None:
        tracing.annotate(catalog_cache="hit")
        return hit
    tracing.annotate(catalog_cache="miss")
    val = (jnp.asarray(alloc), jnp.asarray(price), jnp.asarray(rank))
    with _CACHE_LOCK:
        while len(_CATALOG_CACHE) >= _CATALOG_CACHE_MAX:
            _CATALOG_CACHE.pop(next(iter(_CATALOG_CACHE)), None)
        _CATALOG_CACHE[key] = val
    return val


def _sorted_classes(problem: Problem, extra_compat: Optional[np.ndarray]):
    """FFD order over classes via Problem.class_order() — the shared key, so
    class-granular and pod-granular solves agree on ordering."""
    order = problem.class_order()
    compat = problem.class_compat[order]
    if extra_compat is not None:
        compat = np.concatenate([compat, extra_compat[order]], axis=1)
    caps = (problem.class_node_cap if problem.class_node_cap is not None
            else np.full(problem.num_classes, 2**30, np.int32))
    return (problem.class_requests[order], problem.class_counts[order],
            compat, caps[order], order)


def solve_classpack(problem: Problem,
                    max_nodes: int = 8192,
                    existing_alloc: Optional[np.ndarray] = None,
                    existing_used: Optional[np.ndarray] = None,
                    existing_compat: Optional[np.ndarray] = None,
                    decode: bool = True,
                    max_alternatives: int = 60,
                    guide: Optional[str] = "lp",
                    refinery=None,
                    device_decode: bool = False,
                    decode_health=None,
                    device_lp: bool = False,
                    lp_health=None) -> PackingResult:
    """Host wrapper: sort classes → pad → kernel → decode.

    device_decode=True (the `DeviceDecode` gate) routes batches at or
    above ops/decode.DEVICE_DECODE_FLOOR through the slab kernel: the
    pod→slot sort happens on device and the host assembles the plan with
    column operations (ops/decode.assemble_slab_single) — bit-identical
    output, no per-pod Python.  A slab-assembly failure reconstructs the
    legacy assignment vector from the slab (no kernel re-dispatch) and
    falls back to this decoder, counted in karpenter_decode_solves_total
    and reported to `decode_health` (ops/decode.DecodeHealth) so a
    persistently bad device path demotes instead of retrying every tick.
    Guided fresh solves (guide="lp") are intercepted by solve_guided
    before the kernel and keep the legacy decode; fleet-scale batches
    reach the slab through the sharded driver instead.

    With decode=False only aggregate state is materialized (bench path:
    node count + total price, no per-pod binding).

    guide="lp" (the default for fresh solves) first solves the class-LP
    on device (ops/lpguide.py) and pins each class's bulk to the LP's
    option mix via split rows — closing the greedy's option-choice gap
    (measured 9.5% → ~2% on the bench's mixed shapes) while the scan
    kernel, audits, and decode stay the same code path.  Solves against
    existing capacity (consolidation probes, E>0) skip the guide: their
    cost question is "fits into what's already bought", not mix.

    `refinery` (ops/refinery.GuideRefinery) makes guide misses
    non-blocking: the guided path answers from a stale mix or falls
    through to the greedy kernel below while the LP refines off-tick."""
    E = 0 if existing_alloc is None else len(existing_alloc)
    ec = None
    if E:
        ec = existing_compat if existing_compat is not None else \
            np.ones((problem.num_classes, E), bool)
    if guide == "lp" and E == 0 and decode:
        from .lpguide import solve_guided
        res = solve_guided(problem, max_alternatives=max_alternatives,
                           max_nodes=max_nodes, refinery=refinery,
                           device_lp=device_lp, lp_health=lp_health)
        if res is not None:
            return res
    requests, counts, compat, caps, order = _sorted_classes(problem, ec)
    C, R = requests.shape
    alloc = problem.option_alloc
    price = problem.option_price.astype(np.float32)
    O = alloc.shape[0]
    if E:
        alloc = np.concatenate([alloc, existing_alloc.astype(np.float32)], axis=0)
        price = np.concatenate([price, np.full(E, np.inf, np.float32)])

    if alloc.shape[0] == 0:  # no options and no existing nodes
        return PackingResult(
            nodes=[], unschedulable=[int(p) for m in problem.class_members
                                     for p in m],
            existing_assignments={}, total_price=0.0)
    rank = np.zeros(alloc.shape[0], np.int32)
    rank[:O] = problem.option_rank

    # pad class axis AND option axis so catalog/ICE/cluster deltas reuse
    # compiled programs
    Cpad = pad_to(C, (64, 256, 1024, 4096))
    Opad = pad_to(alloc.shape[0], (512, 2048, 4096, 8192, 32768))
    req_p = np.zeros((Cpad, R), np.int32)
    req_p[:C] = requests.astype(np.int32)
    cnt_p = np.zeros(Cpad, np.int32)
    cnt_p[:C] = counts
    cap_p = np.full(Cpad, 2**30, np.int32)
    cap_p[:C] = caps
    comp_p = np.zeros((Cpad, Opad), bool)
    comp_p[:C, :alloc.shape[0]] = compat
    alloc_p = np.zeros((Opad, R), np.float32)
    alloc_p[:alloc.shape[0]] = alloc
    price_p = np.full(Opad, np.inf, np.float32)
    price_p[:alloc.shape[0]] = price
    rank_p = np.full(Opad, 2**30 - 1, np.int32)
    rank_p[:alloc.shape[0]] = rank
    alloc, price, rank = alloc_p, price_p, rank_p

    # slot count: never more nodes than pods; bucketed for compile reuse
    P = int(problem.class_counts.sum())
    K = max(min(max_nodes, pad_to(P + E, (256, 1024, 8192))), E + 1)
    # pad buckets decide compile-cache reuse; device_calls counts the
    # kernel dispatches this solve will issue (scan kernel = 1)
    tracing.annotate(device_calls=1, pad_classes=Cpad, pad_options=Opad,
                     slots=K)

    if E == 0:
        # the pure catalog side is reusable across solves — device-cached
        # (with existing nodes the columns embed per-solve cluster state:
        # upload directly, don't pollute the cache)
        d_alloc, d_price, d_rank = _device_catalog(
            alloc.astype(np.int32), price, rank)
    else:
        d_alloc = jnp.asarray(alloc.astype(np.int32))
        d_price, d_rank = jnp.asarray(price), jnp.asarray(rank)
    if E == 0:
        pod_args = _device_podside(req_p, cnt_p, np.packbits(comp_p, axis=1),
                                   cap_p)
    else:
        # existing-node columns embed per-solve cluster state (each
        # consolidation probe differs): upload directly, don't pollute the
        # content cache — same rule as the catalog side above
        pod_args = (jnp.asarray(req_p), jnp.asarray(cnt_p),
                    jnp.asarray(np.packbits(comp_p, axis=1)),
                    jnp.asarray(cap_p))

    def init_args():
        # init slot state is only materialized (and transferred) when a
        # kernel actually consumes it — the fresh aggregate path builds an
        # all-closed state on device instead
        init_option = np.full(K, -1, np.int32)
        init_used = np.zeros((K, R), np.int32)
        if E:
            init_option[:E] = np.arange(O, O + E, dtype=np.int32)
            if existing_used is not None:
                init_used[:E] = np.ceil(existing_used).astype(np.int32)
        return jnp.asarray(init_option), jnp.asarray(init_used)

    if not decode:
        # aggregate path: ONE device→host transfer of the launch plan; with
        # no pre-opened slots the init state never leaves the device either
        if E == 0:
            flat = np.asarray(class_pack_aggregate_kernel_fresh(
                *pod_args, d_alloc, d_price, d_rank, K))
        else:
            flat = np.asarray(class_pack_aggregate_kernel_packed(
                *pod_args, d_alloc, d_price, d_rank, *init_args(), K))
        total, n_open, n_unsched = float(flat[0]), int(flat[1]), int(flat[2])
        nodes_per_option = flat[3:3 + O].astype(np.int64)
        nodes = [NodeDecision(option=problem.options[oi], pod_indices=[])
                 for oi in np.repeat(np.arange(O), nodes_per_option)]
        return PackingResult(nodes=nodes, unschedulable=[None] * n_unsched,
                             existing_assignments={}, total_price=total)

    from . import decode as decode_mod
    use_slab = bool(device_decode) and P >= decode_mod.DEVICE_DECODE_FLOOR
    if use_slab and decode_health is not None and not decode_health.allow():
        use_slab = False
        metrics.decode_solves().inc({"path": "classpack",
                                     "outcome": "suppressed"})
    elif device_decode and not use_slab:
        metrics.decode_solves().inc({"path": "classpack", "outcome": "floor"})

    # kernel dispatch + the blocking device->host transfer
    with tracing.span("solve.kernel"):
        Ppad = pad_to(P)
        if use_slab:
            if E == 0:
                out = class_pack_assign_slab_kernel_fresh(
                    *pod_args, d_alloc, d_price, d_rank, K, Ppad)
            else:
                out = class_pack_assign_slab_kernel(
                    *pod_args, d_alloc, d_price, d_rank, *init_args(),
                    K, Ppad)
            order_idx, slot_counts, slot_option, n_unsched = \
                jax.device_get(out)
            assignment = None
        else:
            if E == 0:
                out = class_pack_assign_kernel_fresh(*pod_args, d_alloc,
                                                     d_price, d_rank, K, Ppad)
            else:
                out = class_pack_assign_kernel(*pod_args, d_alloc, d_price,
                                               d_rank, *init_args(), K, Ppad)
            assignment, slot_option, n_unsched = jax.device_get(out)
    # everything below is host-side decode: rows -> NodeDecisions
    with tracing.span("solve.decode"):
        # rows follow the sorted-class order, members consumed in sequence —
        # the same walk the takes-based decode did, now fully vectorized
        members_arr = problem.members_arrays()
        pod_idx = (np.concatenate([members_arr[ci] for ci in order]) if C else
                   np.zeros(0, np.int64))
        class_of_row = np.repeat(np.asarray(order, np.int64),
                                 problem.class_counts[order]) if C else \
            np.zeros(0, np.int64)

        if use_slab:
            try:
                res = decode_mod.assemble_slab_single(
                    problem, order_idx, slot_counts,
                    np.asarray(slot_option), pod_idx, class_of_row, E, K,
                    max_alternatives, P)
                metrics.decode_solves().inc({"path": "classpack",
                                             "outcome": "device"})
                if decode_health is not None:
                    decode_health.report_success()
                return res
            except Exception:
                log.exception("slab decode failed; host assembly fallback")
                metrics.decode_solves().inc({"path": "classpack",
                                             "outcome": "fallback"})
                if decode_health is not None:
                    decode_health.report_failure("error")
                # the kernel output is still good: rebuild the legacy
                # assignment vector from the slab, no re-dispatch
                assignment = decode_mod.slab_to_assignment(
                    order_idx, slot_counts, Ppad, K)

        assignment = np.asarray(assignment, dtype=np.int32)[:P]
        sched = assignment >= 0
        unschedulable = pod_idx[~sched].tolist()
        ex = sched & (assignment < E)
        existing_assignments = dict(zip(pod_idx[ex].tolist(),
                                        assignment[ex].tolist()))
        new_rows = np.nonzero(sched & (assignment >= E))[0]
        new_rows = new_rows[np.argsort(assignment[new_rows], kind="stable")]
        ks = assignment[new_rows]
        # node boundaries by vectorized edge-detect: rows are slot-sorted, so
        # each node is one contiguous run (np.split's per-group array machinery
        # costs ~15ms at 5k nodes; slicing one pre-built list costs ~nothing)
        starts = np.nonzero(np.diff(ks, prepend=np.int32(-1)))[0]
        ends = np.append(starts[1:], len(ks))
        node_slots = ks[starts] if len(starts) else np.zeros(0, np.int32)

        # per-node resource usage, reconstructed host-side (the kernel no longer
        # ships its K×R slot_used — one gather + reduceat replaces a 200KB+
        # tunnel transfer); values are exact: same integer sums the kernel's
        # alloc-minus-free bookkeeping produces
        if len(starts):
            row_reqs = problem.class_requests[class_of_row[new_rows]]
            node_used = np.add.reduceat(row_reqs, starts, axis=0).astype(np.int64)
        else:
            node_used = np.zeros((0, problem.class_requests.shape[1]), np.int64)

        # one global unique over (slot, class) pairs replaces a per-node
        # np.unique; searchsorted then yields every node's class-set span
        Cn = problem.num_classes
        upq = np.unique(ks.astype(np.int64) * (Cn + 1) + class_of_row[new_rows]) \
            if len(ks) else np.zeros(0, np.int64)
        uslot, ucls = upq // (Cn + 1), upq % (Cn + 1)
        cls_starts = np.searchsorted(uslot, node_slots, side="left")
        cls_ends = np.searchsorted(uslot, node_slots, side="right")

        # hot loop below runs once per node (~5-6k at 50k pods): stage every
        # array it touches as plain Python lists — list indexing/slicing is an
        # order of magnitude cheaper than per-element numpy scalar access
        pod_sorted = pod_idx[new_rows].tolist()
        node_oi = slot_option[node_slots].astype(np.int64)
        # fleet cost: only pod-hosting slots launch.  Demand-driven opens
        # always host ≥1 pod so this matches the old every-open-slot sum; the
        # difference is guided solves, whose pre-opened-but-unfilled slots
        # must not be bought.
        launch_mask = (node_oi >= 0) & (node_oi < O)
        total = float(problem.option_price[node_oi[launch_mask]].sum())
        oi_l = node_oi.tolist()
        starts_l, ends_l = starts.tolist(), ends.tolist()
        options_l = problem.options

        compat_bits = np.packbits(problem.class_compat, axis=1)
        ucls_l = ucls.tolist()
        cs_l, ce_l = cls_starts.tolist(), cls_ends.tolist()
        N = len(oi_l)
        jcb_list: List = [None] * N
        for i in range(N):
            if not (0 <= oi_l[i] < O):
                continue
            cls = ucls_l[cs_l[i]:ce_l[i]]
            jcb_list[i] = (compat_bits[cls[0]] if len(cls) == 1 else
                           np.bitwise_and.reduce(compat_bits[cls], axis=0))
        resolved = resolve_alternatives(problem, oi_l, jcb_list, node_used,
                                        max_alternatives)

        nodes = []
        for i in range(N):
            hit = resolved[i]
            if hit is None:
                continue
            nodes.append(NodeDecision(
                option=options_l[oi_l[i]],
                pod_indices=pod_sorted[starts_l[i]:ends_l[i]],
                used=hit[1],
                alternatives=hit[0],
            ))
        return PackingResult(nodes=nodes, unschedulable=unschedulable,
                             existing_assignments=existing_assignments,
                             total_price=total)


def resolve_alternatives(problem: Problem, oi_l: Sequence[int],
                         jcb_list: Sequence, node_used: np.ndarray,
                         max_alternatives: int = 60,
                         cls_keys: Optional[Sequence] = None) -> List:
    """Per-node flexible alternatives (and the used ResourceList).

    These dedupe hard: full nodes of the same class mix share (pool,
    joint-compat, used) exactly, so a 5k-node plan has only a few hundred
    distinct content keys.  Every node resolves through a cross-solve
    content-keyed memo; cold keys queue ONCE (dict dedup) for a single
    batched capacity/compat filter.  Inputs: per-node option index,
    per-node joint compat bits (AND over hosted classes, packbits form;
    None to skip), per-node used vectors (N×R).  Returns a list aligned
    with the inputs of (alternatives, used_ResourceList) or None.

    `cls_keys` (per-node sorted class-id tuples) replaces `jcb_list` as
    the memo key when given: the joint-compat AND then runs only for
    memo MISSES — at 50k scale that's a few hundred small reduces
    instead of a fleet-wide 20MB reduceat (~100ms, measured)."""
    options_l = problem.options
    O = problem.num_options
    option_alloc = problem.option_alloc
    # per-resource rows contiguous for the global capacity compare
    allocT = np.ascontiguousarray(option_alloc.T)
    pool_of_option = np.asarray([o.pool for o in options_l])
    pool_masks: Dict[object, np.ndarray] = {}
    memo = _alt_memo_for(problem)
    N = len(oi_l)
    used_l = node_used.tolist()
    node_ckeys: List = [None] * N
    # thread-local view of every resolved key: the shared memo can be
    # cleared/evicted by a concurrent solve between fill and assembly, so
    # assembly must never read it directly
    resolved: Dict[tuple, tuple] = {}
    miss_index: Dict[tuple, int] = {}     # ckey -> row in the miss batch
    miss_nodes: List[int] = []
    miss_jc: List[np.ndarray] = []
    compat_bits = (np.packbits(problem.class_compat, axis=1)
                   if cls_keys is not None else None)
    # class-id tuples are batch-specific; the cross-solve memo's invariant
    # is CONTENT keying (two batches assign ids in their own order), so a
    # cls tuple maps to a digest of the classes' requests+compat rows —
    # computed once per distinct tuple per call (review r5)
    cls_digest: Dict[tuple, bytes] = {}

    def _digest(cl: tuple) -> bytes:
        d = cls_digest.get(cl)
        if d is None:
            import hashlib
            idx = list(cl)
            d = hashlib.blake2b(
                problem.class_requests[idx].tobytes()
                + compat_bits[idx].tobytes(), digest_size=16).digest()
            cls_digest[cl] = d
        return d

    for i in range(N):
        oi = oi_l[i]
        if not (0 <= oi < O) or \
                (cls_keys is None and jcb_list[i] is None):
            continue
        pool = options_l[oi].pool
        if cls_keys is not None:
            ckey = (pool, _digest(cls_keys[i]), tuple(used_l[i]),
                    max_alternatives)
        else:
            ckey = (pool, jcb_list[i].tobytes(), tuple(used_l[i]),
                    max_alternatives)
        node_ckeys[i] = ckey
        if ckey not in resolved and ckey not in miss_index:
            hit = memo.get(ckey)
            if hit is not None:
                resolved[ckey] = hit
            else:
                miss_index[ckey] = i
                miss_nodes.append(i)
                if cls_keys is not None:
                    cl = list(cls_keys[i])
                    miss_jc.append(compat_bits[cl[0]] if len(cl) == 1 else
                                   np.bitwise_and.reduce(compat_bits[cl],
                                                         axis=0))
                else:
                    miss_jc.append(jcb_list[i])

    if miss_nodes:
        # ONE global capacity filter for every distinct miss: per-resource
        # outer compare with a running AND (M×O per resource) — no
        # per-group fancy-indexed copies of the catalog, no M×O×R temporary
        used_mat = np.asarray(node_used)[miss_nodes].astype(option_alloc.dtype)
        M = len(miss_nodes)
        ok = np.ones((M, option_alloc.shape[0]), bool)
        for r in range(allocT.shape[0]):
            np.logical_and(ok, allocT[r][None, :] >= used_mat[:, r][:, None],
                           out=ok)
        n_compat_cols = problem.class_compat.shape[1]
        jc_all = np.unpackbits(np.asarray(miss_jc), axis=1,
                               count=n_compat_cols).astype(bool)
        np.logical_and(ok, jc_all, out=ok)
        for m, (ckey, i) in enumerate(miss_index.items()):
            pool = ckey[0]
            same_pool = pool_masks.get(pool)
            if same_pool is None:
                same_pool = pool_masks[pool] = pool_of_option == pool
            alt_ids = np.nonzero(ok[m] & same_pool)[0][:max_alternatives]
            val = ([options_l[a] for a in alt_ids],
                   ResourceList.from_vector(np.asarray(ckey[2], np.int64),
                                            problem.axes, problem.scales))
            resolved[ckey] = val
            memo[ckey] = val

    return [resolved[k] if k is not None else None for k in node_ckeys]
