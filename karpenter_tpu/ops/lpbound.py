"""Certified lower bounds on packing cost.

The bench harness measures the solver's plan cost against a bound on the
achievable optimum (BASELINE.md: "packing cost overhead vs optimal").
This module provides two certified bounds:

  * `class_lp_bound` — the EXACT optimum of the class-granular LP
    relaxation, solved off the clock with scipy/HiGHS:

        min  Σ_j price_j · n_j
        s.t. Σ_c req[c,r] · x[c,j] ≤ alloc[j,r] · n_j   ∀ j, r
             Σ_{j ∈ compat(c)} x[c,j] = count_c          ∀ c
             x, n ≥ 0

    (x[c,j] = pods of class c placed on option-j nodes; n_j = fractional
    node count.)  This is the relaxation the tensorized solver itself is
    built on (SURVEY.md §7): it drops node integrality AND per-node
    resource coupling (pods of one option pool their resource use across
    that option's nodes), so its optimum is a true — if sometimes loose —
    lower bound on any integral packing.

  * `dual_feasible_bound` — a certificate-carrying fallback needing only
    numpy: any λ[j,r] ≥ 0 with Σ_r alloc[j,r]·λ[j,r] ≤ price_j is
    feasible for the LP dual, giving the valid bound
    Σ_c count_c · min_{j ∈ compat(c)} Σ_r req[c,r]·λ[j,r].
    Projected supergradient ascent over λ tightens it toward the LP
    optimum; EVERY iterate is dual-feasible, so the best-so-far value is
    always a certified bound (no convergence needed for validity).

Note the subtlety the previous bench bound got wrong: the per-pod
"max-share" heuristic (pod costs ≥ price_j · max_r req_r/alloc_jr) is NOT
a valid bound — two complementary pods (cpu-heavy + mem-heavy) can share
one node while their max-shares sum past 1, so summed imputed costs can
EXCEED the true optimum.  The dual certificate replaces it: a λ
concentrated on one resource recovers exactly the safe single-resource
bound, and mixing resources stays valid because dual feasibility is
enforced by construction.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def _fit_compat(problem) -> np.ndarray:
    """class_compat ∧ (at least one pod of the class fits one node of the
    option) — the same m ≥ 1 feasibility the packing kernel enforces, so
    unfittable pods are excluded from demand exactly as they are excluded
    from the solver's total_price (they come back unschedulable)."""
    req = problem.class_requests.astype(np.float64)
    alloc = problem.option_alloc.astype(np.float64)
    reqpos = req > 0
    safe_req = np.where(reqpos, req, 1.0)
    m = np.where(reqpos[:, None, :], alloc[None, :, :] // safe_req[:, None, :],
                 np.inf).min(axis=2)
    return problem.class_compat & (m >= 1.0)


def _dedup_options(alloc: np.ndarray, price: np.ndarray,
                   compat: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Collapse options identical in (alloc row, price, compat column) —
    zone-expanded offerings of one type are LP-indistinguishable, which
    shrinks the 3600-column catalogs to ~their type count."""
    O = alloc.shape[0]
    keys = {}
    keep = []
    for j in range(O):
        k = (alloc[j].tobytes(), float(price[j]), compat[:, j].tobytes())
        if k not in keys:
            keys[k] = True
            keep.append(j)
    keep = np.asarray(keep, dtype=np.int64)
    return alloc[keep], price[keep], compat[:, keep]


def class_lp_bound(problem, time_limit_s: float = 900.0) -> Optional[float]:
    """Exact class-granular LP optimum via scipy/HiGHS; None if scipy is
    unavailable or the LP fails to solve (incl. hitting the time limit —
    a partially-solved primal is NOT a valid bound).  Off-the-clock use
    only: the 50k-pod × 600-type instance takes minutes."""
    try:
        from scipy import sparse
        from scipy.optimize import linprog
    except ImportError:  # pragma: no cover — scipy is baked into the image
        return None
    if problem.num_options == 0 or problem.num_classes == 0:
        return 0.0
    fit = _fit_compat(problem)
    feas = fit.any(axis=1)
    req = problem.class_requests[feas].astype(np.float64)
    cnt = problem.class_counts[feas].astype(np.float64)
    compat = fit[feas]
    alloc, price, compat = _dedup_options(
        problem.option_alloc.astype(np.float64),
        problem.option_price.astype(np.float64), compat)
    C, R = req.shape
    O = alloc.shape[0]
    if C == 0 or O == 0:
        return 0.0

    # variables: x over compat pairs (sparse), then n (O)
    pair_c, pair_j = np.nonzero(compat)
    P = len(pair_c)
    n_base = P
    nvars = P + O

    rows, cols, vals = [], [], []
    # capacity rows, one per (j, r): Σ_c req[c,r]·x[c,j] - alloc[j,r]·n_j ≤ 0
    for r in range(R):
        nz = req[pair_c, r] != 0
        rows.append(pair_j[nz] * R + r)
        cols.append(np.nonzero(nz)[0])
        vals.append(req[pair_c[nz], r])
    rows.append(np.repeat(np.arange(O), R) * R + np.tile(np.arange(R), O))
    cols.append(np.repeat(np.arange(O) + n_base, R))
    vals.append(-alloc.reshape(-1))
    A_ub = sparse.csr_matrix(
        (np.concatenate(vals), (np.concatenate(rows), np.concatenate(cols))),
        shape=(O * R, nvars))
    b_ub = np.zeros(O * R)
    # demand rows, one per class: Σ_j x[c,j] = count_c
    A_eq = sparse.csr_matrix(
        (np.ones(P), (pair_c, np.arange(P))), shape=(C, nvars))
    b_eq = cnt
    c_obj = np.concatenate([np.zeros(P), price])
    res = linprog(c_obj, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq,
                  bounds=(0, None), method="highs",
                  options={"time_limit": float(time_limit_s)})
    if not res.success:
        return None
    return float(res.fun)


def dual_feasible_bound(problem, iters: int = 300,
                        step0: float = 0.5) -> float:
    """Certified bound from projected supergradient ascent on the LP dual.

    λ is parameterized as λ[j,r] = price_j · μ[j,r] / alloc[j,r] with
    μ[j] ≥ 0, Σ_r μ[j,r] ≤ 1 — dual feasibility holds by construction, so
    the best iterate's value is a valid bound regardless of convergence.
    Initialized from the best single-resource concentration (recovering
    the classic per-resource bound) and improved from there."""
    if problem.num_options == 0 or problem.num_classes == 0:
        return 0.0
    fit = _fit_compat(problem)
    feas = fit.any(axis=1)
    req = problem.class_requests[feas].astype(np.float64)
    cnt = problem.class_counts[feas].astype(np.float64)
    compat = fit[feas]
    alloc, price, compat = _dedup_options(
        problem.option_alloc.astype(np.float64),
        problem.option_price.astype(np.float64), compat)
    C, R = req.shape
    O = alloc.shape[0]
    if C == 0 or O == 0:
        return 0.0
    safe_alloc = np.where(alloc > 0, alloc, np.inf)
    # unit[c,j,r]: cost contribution of one unit of μ[j,r] to class c's
    # per-pod price on option j
    unit = price[None, :, None] * req[:, None, :] / safe_alloc[None, :, :]

    def value_and_argmin(mu):
        percls = np.einsum("cjr,jr->cj", unit, mu)
        percls = np.where(compat, percls, np.inf)
        jstar = np.argmin(percls, axis=1)
        y = percls[np.arange(C), jstar]
        return float(np.dot(cnt, y)), jstar

    best = 0.0
    # single-resource concentrations (always valid starting certificates)
    start = None
    for r in range(R):
        mu = np.zeros((O, R))
        mu[:, r] = 1.0
        v, _ = value_and_argmin(mu)
        if v > best:
            best, start = v, mu
    if start is None:
        start = np.zeros((O, R))
        start[:, 0] = 1.0
    mu = start.copy()
    scale = max(best, 1.0)
    for t in range(iters):
        v, jstar = value_and_argmin(mu)
        if v > best:
            best = v
        # supergradient of Σ_c cnt_c · min_j ⟨unit[c,j], μ_j⟩ at the argmin
        g = np.zeros((O, R))
        np.add.at(g, jstar, cnt[:, None] * unit[np.arange(C), jstar])
        step = step0 * scale / (np.linalg.norm(g) + 1e-12) / np.sqrt(t + 1.0)
        mu += step * g
        # project each row onto {μ ≥ 0, Σ μ ≤ 1}
        np.clip(mu, 0.0, None, out=mu)
        s = mu.sum(axis=1)
        over = s > 1.0
        if over.any():
            mu[over] /= s[over, None]
    return best


def device_dual_bound(problem, eps: float = 1e-5,
                      iters_cap: int = 20000) -> float:
    """Certified bound from PDHG-harvested capacity duals.

    Solves the class LP (same formulation as `class_lp_bound`, dense) on
    the device solver (ops/lpsolve.py) and harvests the capacity-row
    multipliers λ[j,r] ≥ 0.  The harvested λ is then REPAIRED to exact
    dual feasibility — each option row is scaled so
    Σ_r alloc[j,r]·λ[j,r] ≤ price_j (the n_j column's dual constraint) —
    after which the `dual_feasible_bound` certificate

        Σ_c count_c · min_{j ∈ compat(c)} Σ_r req[c,r]·λ[j,r]

    is a valid lower bound by weak duality REGARDLESS of whether PDHG
    converged: non-convergence only makes λ loose, never invalid.  This
    turns the device solve into a certificate producer, so the bench can
    quote a certified gap without a HiGHS solve on the clock."""
    from . import lpsolve
    if problem.num_options == 0 or problem.num_classes == 0:
        return 0.0
    fit = _fit_compat(problem)
    feas = fit.any(axis=1)
    req = problem.class_requests[feas].astype(np.float64)
    cnt = problem.class_counts[feas].astype(np.float64)
    compat = fit[feas]
    alloc, price, compat = _dedup_options(
        problem.option_alloc.astype(np.float64),
        problem.option_price.astype(np.float64), compat)
    C, R = req.shape
    O = alloc.shape[0]
    if C == 0 or O == 0:
        return 0.0

    pair_c, pair_j = np.nonzero(compat)
    P = len(pair_c)
    nvars = P + O
    A_ub = np.zeros((O * R, nvars))
    A_ub[pair_j[None, :] * R + np.arange(R)[:, None],
         np.arange(P)[None, :]] = req[pair_c].T
    A_ub[np.arange(O * R), np.arange(O).repeat(R) + P] = -alloc.reshape(-1)
    A_eq = np.zeros((C, nvars))
    A_eq[pair_c, np.arange(P)] = 1.0
    c_obj = np.concatenate([np.zeros(P), price])
    sol = lpsolve.solve_lp(c_obj, A_eq=A_eq, b_eq=cnt,
                           A_ub=A_ub, b_ub=np.zeros(O * R),
                           warm_key="lpbound:class",
                           eps=eps, iters_cap=iters_cap)
    lam = np.maximum(sol.lam.reshape(O, R), 0.0)
    # repair: scale each option's row into the n_j dual constraint
    s = np.einsum("jr,jr->j", alloc, lam)
    lam *= np.where(s > price, price / np.maximum(s, 1e-300), 1.0)[:, None]
    percls = np.where(compat, req @ lam.T, np.inf)
    return float(np.dot(cnt, percls.min(axis=1)))


def cost_lower_bound(problem) -> float:
    """Best certified bound available: exact LP when scipy is present,
    else the dual-certificate ascent."""
    lp = class_lp_bound(problem)
    if lp is not None:
        return lp
    return dual_feasible_bound(problem)
