"""Persistent incrementally-updated cluster tensorization (the delta arena).

`Cluster.tensorize_nodes` lowers the live node set to dense packing arrays
from scratch on every call — O(nodes × classes) label/taint evaluations and
O(pods) request summing, the dominant non-kernel cost at 50k-pod scale even
though a steady-state reconcile changes ONE row (a bind, a reclaim, a taint
edit).  `ClusterArena` keeps those arrays alive between ticks as a slotted
slab and applies typed deltas in place:

* **Row slots + free-list.**  Every tracked node owns a slab row
  (`slab_alloc`/`slab_used` E×R float32, `slab_compat` E×C bool slot-major).
  Removal tombstones the row (``slab_live`` mask) and recycles the slot
  through a LIFO free-list — deterministic slot assignment for identical
  event sequences, which the sim's byte-identical-report contract depends
  on.
* **Class registry.**  Pod equivalence classes (`_class_key`) are interned
  to stable column ids; a `gather()` for reps the arena has never seen
  computes just those columns over live rows.  The table resets wholesale
  past ``class_table_max`` (per-pod-unique labels make distinct keys
  unbounded in a long-lived controller — same argument as `_CLASS_IDS` in
  ops/tensorize.py).
* **Exact row math.**  A touched row is ALWAYS recomputed through the same
  arithmetic `tensorize_nodes` uses (`requested()` → `to_vector(round_up)`,
  tolerate-then-compatible), never incrementally adjusted — float add/sub
  does not invert across round_up ordering, and the bit-identity contract
  with the from-scratch path (tests/test_arena_delta.py) is what lets the
  warm arena feed the solver unaudited.
* **Compaction + full rebuild.**  When tombstones outnumber
  ``max(compact_floor, live)`` the slab compacts (row moves, no recompute).
  `rebuild()` — re-derivation from cluster state — stays the always-correct
  fallback: `invalidate()` flags it, and `gather()` returns None (caller
  falls back to `tensorize_nodes`) for anything the slab can't express
  (extra axes, non-default scales, untracked nodes).

The arena is fed by `state.Cluster`'s mutators (bind/add/remove hooks) plus
explicit `touch_node` calls at the label/taint edit sites in the lifecycle,
termination, and disruption controllers.  All mutation happens under the
operator's state lock, like every other Cluster write.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as wk
from ..api.objects import Node, Pod
from ..api.requirements import Requirements
from ..api.resources import DEFAULT_AXES, DEFAULT_SCALES, PODS
from ..api.taints import tolerates_all
from ..utils import metrics, tracing
from .tensorize import _class_key

_INITIAL_SLOTS = 64
_INITIAL_CLASSES = 64


class ClusterArena:
    """Slotted, incrementally-maintained mirror of `tensorize_nodes`' output
    for the default resource axes.  See module docstring."""

    def __init__(self, cluster, compact_floor: int = 32,
                 class_table_max: int = 4096):
        self._cluster = cluster
        self._axes: Tuple[str, ...] = DEFAULT_AXES
        self._scales: Dict[str, float] = dict(DEFAULT_SCALES)
        self.compact_floor = compact_floor
        self.class_table_max = class_table_max
        R = len(self._axes)
        # the tensor slab — mutate ONLY through the apply_*/touch_node/
        # rebuild delta API below (graftlint AR001)
        self.slab_alloc = np.zeros((_INITIAL_SLOTS, R), np.float32)  # guarded-by: caller(state_lock)
        self.slab_used = np.zeros((_INITIAL_SLOTS, R), np.float32)   # guarded-by: caller(state_lock)
        self.slab_compat = np.zeros((_INITIAL_SLOTS, _INITIAL_CLASSES), bool)  # guarded-by: caller(state_lock)
        self.slab_live = np.zeros(_INITIAL_SLOTS, bool)              # guarded-by: caller(state_lock)
        self._slot_of: Dict[str, int] = {}      # guarded-by: caller(state_lock)
        self._node_at: List[Optional[Node]] = [None] * _INITIAL_SLOTS  # guarded-by: caller(state_lock)
        self._free: List[int] = []              # guarded-by: caller(state_lock)
        self._top = 0                           # guarded-by: caller(state_lock)
        self._rid_of: Dict[tuple, int] = {}     # guarded-by: caller(state_lock)
        self._reps: List[Pod] = []              # guarded-by: caller(state_lock)
        # node name → {gang name → resident member count}; see _fill_used
        self.gang_residents: Dict[str, Dict[str, int]] = {}  # guarded-by: caller(state_lock)
        # monotone per-delta counter: consumers (SimulationArena faces,
        # disruption's lazy re-fingerprint) compare it to decide staleness
        # without walking the object graph
        self.epoch = 0                          # guarded-by: caller(state_lock)
        self.compactions = 0                    # guarded-by: caller(state_lock)
        self._needs_rebuild = True              # guarded-by: caller(state_lock)

    # ---- bookkeeping ------------------------------------------------------
    @property
    def live_count(self) -> int:
        return len(self._slot_of)

    @property
    def tombstone_count(self) -> int:
        return len(self._free)

    def _note_delta(self, kind: str):  # guarded-by: caller(state_lock)
        self.epoch += 1
        metrics.arena_deltas().inc({"kind": kind})
        metrics.arena_epoch().set(self.epoch)
        metrics.arena_slots().set(self.live_count, {"state": "live"})
        metrics.arena_slots().set(self.tombstone_count,
                                  {"state": "tombstone"})

    def _grow_slots(self, need: int):  # guarded-by: caller(state_lock)
        cap = self.slab_alloc.shape[0]
        new = cap
        while new < need:
            new *= 2
        if new == cap:
            return
        R, C = self.slab_alloc.shape[1], self.slab_compat.shape[1]
        for name, width, dtype in (("slab_alloc", R, np.float32),
                                   ("slab_used", R, np.float32)):
            old = getattr(self, name)
            arr = np.zeros((new, width), dtype)
            arr[:cap] = old
            setattr(self, name, arr)
        compat = np.zeros((new, C), bool)
        compat[:cap] = self.slab_compat
        self.slab_compat = compat
        live = np.zeros(new, bool)
        live[:cap] = self.slab_live
        self.slab_live = live
        self._node_at.extend([None] * (new - cap))

    def _grow_classes(self, need: int):  # guarded-by: caller(state_lock)
        cap = self.slab_compat.shape[1]
        new = cap
        while new < need:
            new *= 2
        if new == cap:
            return
        compat = np.zeros((self.slab_compat.shape[0], new), bool)
        compat[:, :cap] = self.slab_compat
        self.slab_compat = compat

    # ---- row math (bit-identical to Cluster.tensorize_nodes) --------------
    @staticmethod
    def _provided(node: Node) -> Requirements:
        node_labels = dict(node.labels)
        # hostname defaults to the node name — same rule as tensorize_nodes
        node_labels.setdefault(wk.HOSTNAME, node.name)
        return Requirements.from_labels(node_labels)

    @staticmethod
    def _compat_entry(rep: Pod, node: Node, provided: Requirements) -> bool:
        if not tolerates_all(rep.tolerations, node.taints):
            return False
        return any(b.compatible(provided)
                   for b in rep.scheduling_requirements())

    def _fill_row(self, slot: int, node: Node):  # guarded-by: caller(state_lock)
        self.slab_alloc[slot] = node.allocatable.to_vector(self._axes,
                                                           self._scales)
        self._fill_used(slot, node)
        provided = self._provided(node)
        row = self.slab_compat[slot]
        row[:] = False
        for rid, rep in enumerate(self._reps):
            row[rid] = self._compat_entry(rep, node, provided)

    def _fill_used(self, slot: int, node: Node):  # guarded-by: caller(state_lock)
        req = node.requested()
        req[PODS] = len(node.pods)
        self.slab_used[slot] = req.to_vector(self._axes, self._scales,
                                             round_up=True)
        # gang-resident index (GangScheduling, ops/gang.py): node → gang →
        # member count, maintained by the same delta events that refresh
        # `used` rows.  Advisory — NOT part of snapshot_state; it re-derives
        # as rows refresh (rebuild() repopulates it in full).
        res: Dict[str, int] = {}
        for p in node.pods:
            if p.gang_name:
                res[p.gang_name] = res.get(p.gang_name, 0) + 1
        if res:
            self.gang_residents[node.name] = res
        else:
            self.gang_residents.pop(node.name, None)

    # ---- class registry ---------------------------------------------------
    def _ensure_classes(self, reps: Sequence[Pod],  # guarded-by: caller(state_lock)
                        _post_reset: bool = False) -> List[int]:
        fresh: List[Tuple[int, Pod]] = []
        rids: List[int] = []
        for rep in reps:
            k = _class_key(rep)
            rid = self._rid_of.get(k)
            if rid is None:
                if len(self._reps) >= self.class_table_max and not _post_reset:
                    # wholesale reset — same unbounded-key argument as
                    # tensorize's _CLASS_IDS table; restart registration so
                    # every requested rep gets a fresh column.  A single
                    # gather with more distinct classes than the cap still
                    # registers them all (the cap is an across-calls hygiene
                    # bound, not a per-call limit) — _post_reset stops a
                    # second reset from recursing forever.
                    self._rid_of.clear()
                    self._reps = []
                    self.slab_compat[:] = False
                    return self._ensure_classes(reps, _post_reset=True)
                rid = len(self._reps)
                self._grow_classes(rid + 1)
                self._rid_of[k] = rid
                self._reps.append(rep)
                fresh.append((rid, rep))
            rids.append(rid)
        if fresh:
            # one provided-Requirements per live node, shared by all new
            # columns (the expensive part of a cold gather)
            for name, slot in self._slot_of.items():
                node = self._node_at[slot]
                provided = self._provided(node)
                for rid, rep in fresh:
                    self.slab_compat[slot, rid] = self._compat_entry(
                        rep, node, provided)
        return rids

    # ---- delta API --------------------------------------------------------
    def apply_node_add(self, node: Node):  # guarded-by: caller(state_lock)
        slot = self._slot_of.get(node.name)
        if slot is None:
            if self._free:
                slot = self._free.pop()     # LIFO: deterministic reuse order
            else:
                slot = self._top
                self._top += 1
                self._grow_slots(self._top)
            self._slot_of[node.name] = slot
        self._node_at[slot] = node
        self.slab_live[slot] = True
        self._fill_row(slot, node)
        self._note_delta("node_add")

    def apply_node_remove(self, name: str):  # guarded-by: caller(state_lock)
        self.gang_residents.pop(name, None)
        slot = self._slot_of.pop(name, None)
        if slot is None:
            return
        self.slab_live[slot] = False
        self._node_at[slot] = None
        self._free.append(slot)
        self._note_delta("node_remove")
        if len(self._free) > max(self.compact_floor, self.live_count):
            self.compact()

    def touch_node(self, node: Node):  # guarded-by: caller(state_lock)
        """Re-derive a tracked node's whole row after an in-place label /
        taint / allocatable edit (lifecycle init, termination taint,
        disruption taint + rollback, sim boot-taint strip)."""
        slot = self._slot_of.get(node.name)
        if slot is None:
            return
        self._node_at[slot] = node
        self._fill_row(slot, node)
        self._note_delta("touch")

    def apply_pod_bind(self, pod: Pod, node_name: str,
                       old_node_name: str = ""):  # guarded-by: caller(state_lock)
        if old_node_name and old_node_name != node_name:
            self._refresh_used(old_node_name)
        self._refresh_used(node_name)
        self._note_delta("pod_bind")

    def apply_pod_unbind(self, node_name: str):  # guarded-by: caller(state_lock)
        self._refresh_used(node_name)
        self._note_delta("pod_unbind")

    def apply_pod_add(self, pod: Pod):  # guarded-by: caller(state_lock)
        # a pending pod touches no node row; the epoch bump is what
        # invalidates cached faces built over the old pod set
        self._note_delta("pod_add")

    def apply_pod_remove(self, pod: Pod, node_name: str = ""):  # guarded-by: caller(state_lock)
        if node_name:
            self._refresh_used(node_name)
        self._note_delta("pod_remove")

    def apply_offering_change(self):  # guarded-by: caller(state_lock)
        """Catalog/pricing churn: node rows don't depend on the catalog, so
        this is an epoch bump only — consumers re-key their catalog side."""
        self._note_delta("offering")

    def _refresh_used(self, node_name: str):  # guarded-by: caller(state_lock)
        slot = self._slot_of.get(node_name)
        if slot is not None:
            self._fill_used(slot, self._node_at[slot])

    def invalidate(self, reason: str = ""):  # guarded-by: caller(state_lock)
        """Flag the slab for full re-derivation on next gather — the
        always-correct escape hatch for events the delta API can't
        express."""
        self._needs_rebuild = True
        self._note_delta("invalidate")

    def apply_ingest_flush(self, touched: Sequence[Node] = (),  # guarded-by: caller(state_lock)
                           removed: Sequence[str] = (),
                           used_names: Sequence[str] = ()):
        """Apply one tick's worth of coalesced ingestion events in a single
        delta (the `IngestBatch` gate's flush path).  Rows re-derive through
        the same exact math as the eager API — a batched flush and the
        equivalent eager event stream differ only in slot layout, never in
        gather() output (which orders by cluster dict, not slot).  Removals
        run first so their slots recycle for same-tick adds."""
        with tracing.span("arena.ingest_flush"):
            for name in removed:
                self.gang_residents.pop(name, None)
                slot = self._slot_of.pop(name, None)
                if slot is None:
                    continue
                self.slab_live[slot] = False
                self._node_at[slot] = None
                self._free.append(slot)
            for node in touched:
                slot = self._slot_of.get(node.name)
                if slot is None:
                    if self._free:
                        slot = self._free.pop()
                    else:
                        slot = self._top
                        self._top += 1
                        self._grow_slots(self._top)
                    self._slot_of[node.name] = slot
                self._node_at[slot] = node
                self.slab_live[slot] = True
                self._fill_row(slot, node)
            for name in used_names:
                self._refresh_used(name)
            self._note_delta("ingest_flush")
            if len(self._free) > max(self.compact_floor, self.live_count):
                self.compact()

    # ---- snapshot / warm restart ------------------------------------------
    def snapshot_state(self) -> Dict:  # guarded-by: caller(state_lock)
        """Plain-data export of the whole slab + registries for the
        WarmRestart snapshot (state/snapshot.py).  Arrays are copied so the
        serializer can run concurrently with nothing — the caller holds the
        state lock for the duration either way.  Node objects are NOT
        exported (slots rewire by name on restore); rep Pods are, because
        their class keys are content tuples that survive pickling."""
        return {
            "axes": tuple(self._axes),
            "scales": dict(self._scales),
            "slab_alloc": self.slab_alloc.copy(),
            "slab_used": self.slab_used.copy(),
            "slab_compat": self.slab_compat.copy(),
            "slab_live": self.slab_live.copy(),
            "slot_of": dict(self._slot_of),
            "free": list(self._free),
            "top": self._top,
            "rid_of": dict(self._rid_of),
            "reps": list(self._reps),
            "epoch": self.epoch,
            "compactions": self.compactions,
            "needs_rebuild": self._needs_rebuild,
        }

    def restore_state(self, data: Dict) -> bool:  # guarded-by: caller(state_lock)
        """Adopt a `snapshot_state` export, rewiring every slot to the
        restored Cluster's node objects by name.  Returns False (leaving the
        arena flagged for rebuild) when the snapshot can't be trusted: axis/
        scale mismatch, or a tracked name the cluster no longer has — the
        caller falls back to `rebuild()`, the always-correct path."""
        if tuple(data["axes"]) != self._axes or \
                dict(data["scales"]) != self._scales:
            return False
        nodes = self._cluster.nodes
        slot_of: Dict[str, int] = dict(data["slot_of"])
        if any(name not in nodes for name in slot_of):
            return False
        alloc = np.asarray(data["slab_alloc"], np.float32)
        used = np.asarray(data["slab_used"], np.float32)
        compat = np.asarray(data["slab_compat"], bool)
        live = np.asarray(data["slab_live"], bool)
        cap = alloc.shape[0]
        if used.shape != alloc.shape or compat.shape[0] != cap or \
                live.shape[0] != cap or alloc.shape[1] != len(self._axes):
            return False
        node_at: List[Optional[Node]] = [None] * cap
        for name, slot in slot_of.items():
            if not (0 <= slot < cap):
                return False
            node_at[slot] = nodes[name]
        self.slab_alloc = alloc
        self.slab_used = used
        self.slab_compat = compat
        self.slab_live = live
        self._slot_of = slot_of
        self._node_at = node_at
        self._free = list(data["free"])
        self._top = int(data["top"])
        self._rid_of = dict(data["rid_of"])
        self._reps = list(data["reps"])
        self.epoch = int(data["epoch"])
        self.compactions = int(data["compactions"])
        self._needs_rebuild = bool(data["needs_rebuild"])
        self._note_delta("restore")
        return True

    # ---- compaction / rebuild ---------------------------------------------
    def compact(self):  # guarded-by: caller(state_lock)
        """Densify the slab: move live rows to the front in cluster dict
        order (deterministic), drop tombstones, reset the free-list.  Pure
        row moves — values are already exact, so nothing recomputes."""
        with tracing.span("arena.compact"):
            nodes = [n for n in self._cluster.nodes.values()
                     if n.name in self._slot_of]
            idx = np.asarray([self._slot_of[n.name] for n in nodes], np.int64)
            E = len(nodes)
            cap = max(_INITIAL_SLOTS, self.slab_alloc.shape[0])
            while cap // 2 >= max(E, _INITIAL_SLOTS):
                cap //= 2
            R, C = self.slab_alloc.shape[1], self.slab_compat.shape[1]
            alloc = np.zeros((cap, R), np.float32)
            used = np.zeros((cap, R), np.float32)
            compat = np.zeros((cap, C), bool)
            live = np.zeros(cap, bool)
            if E:
                alloc[:E] = self.slab_alloc[idx]
                used[:E] = self.slab_used[idx]
                compat[:E] = self.slab_compat[idx]
                live[:E] = True
            self.slab_alloc, self.slab_used = alloc, used
            self.slab_compat, self.slab_live = compat, live
            self._node_at = list(nodes) + [None] * (cap - E)
            self._slot_of = {n.name: i for i, n in enumerate(nodes)}
            self._free = []
            self._top = E
            self.compactions += 1
            metrics.arena_compactions().inc()
            self._note_delta("compact")

    def rebuild(self):  # guarded-by: caller(state_lock)
        """Full re-derivation from cluster state — the fallback that makes
        every other path merely an optimization.  Keeps the class registry
        (columns recompute with the rows)."""
        with tracing.span("arena.rebuild") as sp:
            nodes = list(self._cluster.nodes.values())
            E = len(nodes)
            self._grow_slots(max(E, 1))
            self.slab_live[:] = False
            self.slab_alloc[:] = 0.0
            self.slab_used[:] = 0.0
            self.slab_compat[:] = False
            self._node_at = [None] * self.slab_alloc.shape[0]
            self._slot_of = {}
            self._free = []
            self.gang_residents = {}
            self._top = E
            for slot, node in enumerate(nodes):
                self._slot_of[node.name] = slot
                self._node_at[slot] = node
                self.slab_live[slot] = True
                self._fill_row(slot, node)
            self._needs_rebuild = False
            sp.annotate(nodes=E, classes=len(self._reps))
            self._note_delta("rebuild")

    # ---- the consumer surface ---------------------------------------------
    def gangs_on(self, node_name: str) -> Dict[str, int]:  # guarded-by: caller(state_lock)
        """Gang name → resident member count on one node (GangScheduling):
        the delta-maintained index preemption planning and tests read
        instead of walking every node's pod list."""
        return dict(self.gang_residents.get(node_name, ()))

    def gather(self, pod_classes: Sequence[Pod],
               axes: Tuple[str, ...] = DEFAULT_AXES,
               exclude: Sequence[str] = (),
               scales=None):
        """Warm replacement for `Cluster.tensorize_nodes` with the same
        signature and bit-identical output, or None when the slab can't
        serve the request (extra axes, non-default scales, a node the
        deltas never covered) — the caller falls back to the from-scratch
        path.  Read-only on the slab: fancy indexing copies, so consumers
        can never corrupt it."""
        if tuple(axes) != self._axes or (
                scales is not None and dict(scales) != self._scales):
            metrics.arena_gather().inc({"outcome": "fallback"})
            return None
        if self._needs_rebuild:
            self.rebuild()
        excl = set(exclude)
        node_list = [n for n in self._cluster.nodes.values()
                     if n.name not in excl and not n.marked_for_deletion]
        slots = []
        for n in node_list:
            slot = self._slot_of.get(n.name)
            if slot is None or self._node_at[slot] is not n:
                # untracked or swapped-out node object: the delta stream
                # missed something — refuse rather than risk a stale row
                metrics.arena_gather().inc({"outcome": "fallback"})
                return None
            slots.append(slot)
        rids = self._ensure_classes(pod_classes)
        idx = np.asarray(slots, np.int64)
        cols = np.asarray(rids, np.int64)
        alloc = self.slab_alloc[idx]
        used = self.slab_used[idx]
        compat = np.ascontiguousarray(self.slab_compat[idx][:, cols].T) \
            if len(node_list) else np.zeros((len(rids), 0), bool)
        metrics.arena_gather().inc({"outcome": "warm"})
        return node_list, alloc, used, compat
