"""karpenter_tpu — a TPU-native node-provisioning autoscaler framework.

A ground-up re-design of the capabilities of raghibfaisal/karpenter
(Kubernetes node autoscaling: pod→instance-type bin-packing, consolidation,
interruption handling, cloud actuation) where the scheduling and
consolidation hot paths are batched pods×instance-types assignment problems
solved by jit-compiled JAX kernels on TPU, instead of per-pod greedy loops.

Layer map (mirrors SURVEY.md §1, re-architected):
  api/         CRD-analog data model (NodePool, NodeClaim, NodeClass, Pod)
  catalog/     instance types, offerings, pricing, overhead math
  ops/         tensorization + solver kernels (FFD scan, relaxed-LP) — the TPU hot path
  parallel/    device-mesh sharding of the assignment problem
  state/       cluster-state cache the simulator packs against
  controllers/ reconcile loops (provisioning, disruption, interruption, GC, nodeclass)
  cloud/       capacity-provider substrate (provider seam, fake cloud, batcher, caches)
  utils/       shared helpers
"""

__version__ = "0.1.0"
