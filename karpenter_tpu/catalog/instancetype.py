"""Instance-type catalog: InstanceType, Offering, overhead math.

Re-implements the semantics of the reference's instancetype provider types
(/root/reference/pkg/providers/instancetype/types.go:53-416 and offering
construction at /root/reference/pkg/providers/instancetype/instancetype.go:144-175):
capacity (cpu/mem/storage/pods/accelerators), overhead (kube-reserved /
system-reserved / eviction threshold), ~25 requirement labels, and per
(zone × capacity-type) priced offerings with ICE-driven availability.

TPU-first: `CatalogTensors` (built in karpenter_tpu.ops.tensorize) is the
dense projection the solver kernels consume; this module is the host-side
source of truth.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import cached_property
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from ..api import labels as wk
from ..api.requirements import IN, Requirement, Requirements
from ..api.resources import (CPU, EPHEMERAL_STORAGE, GPU, MEMORY, NEURON,
                             PODS, POD_ENI, ResourceList)
from ..api.objects import KubeletConfiguration

DEFAULT_MAX_PODS = 110
# Memory the hypervisor/VM steals from the advertised figure; reference
# default 7.5% (/root/reference/pkg/operator/options/options.go vm-memory-overhead-percent).
VM_MEMORY_OVERHEAD_PERCENT = 0.075

MiB = 2**20
GiB = 2**30


@dataclass
class Offering:
    """One purchasable (zone × capacity-type) of an instance type
    (/root/reference/pkg/providers/instancetype/instancetype.go:144-175)."""
    zone: str
    capacity_type: str  # spot | on-demand
    price: float        # $/hour
    available: bool = True

    @property
    def key(self) -> Tuple[str, str]:
        return (self.capacity_type, self.zone)


@dataclass
class InstanceTypeInfo:
    """Raw catalog row (analog of ec2.InstanceTypeInfo as consumed at
    /root/reference/pkg/providers/instancetype/types.go:53-72)."""
    name: str
    cpu_m: int                     # millicores
    memory_bytes: int              # advertised memory
    arch: str = "amd64"
    os: Tuple[str, ...] = ("linux",)
    family: str = ""
    size: str = ""
    category: str = ""
    generation: int = 0
    gpu_count: int = 0
    gpu_name: str = ""
    gpu_memory_bytes: int = 0
    neuron_count: int = 0
    network_interfaces: int = 4
    ips_per_interface: int = 15
    network_bandwidth_mbps: int = 1000
    local_nvme_gib: int = 0
    hypervisor: str = "nitro"
    encryption_in_transit: bool = True
    bare_metal: bool = False
    on_demand_price: float = 0.0   # base price; offerings may override per zone

    def __post_init__(self):
        if not self.family and "." in self.name:
            self.family, self.size = self.name.split(".", 1)
        if not self.category:
            self.category = self.family[:1] if self.family else "g"


def eni_limited_pods(info: InstanceTypeInfo, reserved_enis: int = 0) -> int:
    """max_enis * (ips_per_eni - 1) + 2
    (/root/reference/pkg/providers/instancetype/types.go:304-318)."""
    usable = max(info.network_interfaces - reserved_enis, 0)
    if usable == 0:
        return 0
    return usable * (info.ips_per_interface - 1) + 2


def max_pods(info: InstanceTypeInfo, kubelet: Optional[KubeletConfiguration] = None,
             eni_limited_density: bool = False, reserved_enis: int = 0) -> int:
    """Pod-capacity resolution order: kubelet.maxPods → ENI-limited formula →
    110; podsPerCore caps the result
    (/root/reference/pkg/providers/instancetype/types.go:401-416)."""
    if kubelet and kubelet.max_pods is not None:
        count = kubelet.max_pods
    elif eni_limited_density:
        count = eni_limited_pods(info, reserved_enis)
    else:
        count = DEFAULT_MAX_PODS
    if kubelet and kubelet.pods_per_core:
        count = min(kubelet.pods_per_core * max(info.cpu_m // 1000, 1), count)
    return count


def kube_reserved(cpu_m: int, pod_count: int,
                  kubelet: Optional[KubeletConfiguration] = None) -> ResourceList:
    """Graduated CPU reservation + 11Mi/pod + 255Mi memory + 1Gi storage
    (/root/reference/pkg/providers/instancetype/types.go:332-367)."""
    reserved_cpu = 0.0
    for start, end, pct in ((0, 1000, 0.06), (1000, 2000, 0.01),
                            (2000, 4000, 0.005), (4000, 1 << 31, 0.0025)):
        if cpu_m > start:
            reserved_cpu += (min(cpu_m, end) - start) * pct
    out = ResourceList({
        CPU: int(reserved_cpu),
        MEMORY: (11 * pod_count + 255) * MiB,
        EPHEMERAL_STORAGE: 1 * GiB,
    })
    if kubelet and kubelet.kube_reserved:
        out.update(kubelet.kube_reserved)
    return out


def system_reserved(kubelet: Optional[KubeletConfiguration] = None) -> ResourceList:
    return ResourceList(kubelet.system_reserved) if kubelet and kubelet.system_reserved else ResourceList()


def eviction_threshold(memory_bytes: int, storage_bytes: int,
                       kubelet: Optional[KubeletConfiguration] = None) -> ResourceList:
    """100Mi memory + 10% storage hard-eviction defaults, kubelet overrides
    (/root/reference/pkg/providers/instancetype/types.go:370-399): the
    MAX across eviction signals (hard vs soft) per resource, and that
    maximum REPLACES the default — an operator configuring a threshold
    below 100Mi gets exactly what they configured (the old max-with-
    default rule silently kept the default; review r5 golden cases)."""
    out = ResourceList({MEMORY: 100 * MiB,
                        EPHEMERAL_STORAGE: int(math.ceil(storage_bytes / 10))})
    if kubelet:
        override = ResourceList()
        for signal in (kubelet.eviction_hard, kubelet.eviction_soft):
            for k, v in (signal or {}).items():
                override[k] = max(override.get(k, 0), v)
        out.update(override)
    return out


@dataclass
class InstanceType:
    """The solver's catalog unit (/root/reference/pkg/providers/instancetype/types.go:53-72):
    name + requirements + priced offerings + capacity + overhead."""
    name: str
    requirements: Requirements
    offerings: List[Offering]
    capacity: ResourceList
    kube_reserved: ResourceList = field(default_factory=ResourceList)
    system_reserved: ResourceList = field(default_factory=ResourceList)
    eviction_threshold: ResourceList = field(default_factory=ResourceList)
    info: Optional[InstanceTypeInfo] = None

    @cached_property
    def overhead_total(self) -> ResourceList:
        return self.kube_reserved + self.system_reserved + self.eviction_threshold

    @cached_property
    def allocatable(self) -> ResourceList:
        return (self.capacity - self.overhead_total).clamp_nonnegative()

    def cheapest_offering(self, zones: Optional[set] = None,
                          capacity_types: Optional[set] = None) -> Optional[Offering]:
        best = None
        for o in self.offerings:
            if not o.available:
                continue
            if zones and o.zone not in zones:
                continue
            if capacity_types and o.capacity_type not in capacity_types:
                continue
            if best is None or o.price < best.price:
                best = o
        return best

    def available_offerings(self) -> List[Offering]:
        return [o for o in self.offerings if o.available]


def compute_requirements(info: InstanceTypeInfo, offerings: Sequence[Offering]) -> Requirements:
    """The ~25 instance labels the scheduler matches against
    (/root/reference/pkg/providers/instancetype/types.go:75-155)."""
    zones = sorted({o.zone for o in offerings if o.available})
    cap_types = sorted({o.capacity_type for o in offerings if o.available})
    reqs = Requirements.of(
        Requirement(wk.INSTANCE_TYPE, IN, [info.name]),
        Requirement(wk.ARCH, IN, [info.arch]),
        Requirement(wk.OS, IN, list(info.os)),
        Requirement(wk.ZONE, IN, zones),
        Requirement(wk.CAPACITY_TYPE, IN, cap_types),
        Requirement(wk.INSTANCE_CATEGORY, IN, [info.category]),
        Requirement(wk.INSTANCE_FAMILY, IN, [info.family]),
        Requirement(wk.INSTANCE_GENERATION, IN, [str(info.generation)]),
        Requirement(wk.INSTANCE_SIZE, IN, [info.size]),
        Requirement(wk.INSTANCE_CPU, IN, [str(info.cpu_m // 1000)]),
        Requirement(wk.INSTANCE_MEMORY, IN, [str(info.memory_bytes // MiB)]),
        Requirement(wk.INSTANCE_NETWORK_BANDWIDTH, IN, [str(info.network_bandwidth_mbps)]),
        Requirement(wk.INSTANCE_HYPERVISOR, IN, [info.hypervisor]),
        Requirement(wk.INSTANCE_ENCRYPTION_IN_TRANSIT, IN, [str(info.encryption_in_transit).lower()]),
    )
    if info.gpu_count:
        reqs.add(Requirement(wk.INSTANCE_GPU_COUNT, IN, [str(info.gpu_count)]),
                 Requirement(wk.INSTANCE_GPU_NAME, IN, [info.gpu_name]),
                 Requirement(wk.INSTANCE_GPU_MEMORY, IN, [str(info.gpu_memory_bytes // MiB)]))
    if info.neuron_count:
        reqs.add(Requirement(wk.INSTANCE_ACCELERATOR_COUNT, IN, [str(info.neuron_count)]))
    if info.local_nvme_gib:
        reqs.add(Requirement(wk.INSTANCE_LOCAL_NVME, IN, [str(info.local_nvme_gib)]))
    return reqs


def apply_kubelet(it: "InstanceType",
                  kubelet: Optional[KubeletConfiguration]) -> "InstanceType":
    """Re-derive the kubelet-dependent pieces of an existing type — pod
    density, kube/system reserves, eviction thresholds — keeping every
    non-kubelet knob (VM overhead shave, block device size, ENI density
    mode) exactly as the catalog built it.  The per-NodePool analog of the
    reference rebuilding its InstanceType list per kubelet hash
    (/root/reference/pkg/providers/instancetype/instancetype.go:114-124,
    types.go:53-72)."""
    if kubelet is None or kubelet.key() is None:
        return it
    base_pods = int(it.capacity.get(PODS, DEFAULT_MAX_PODS))
    cpu_m = it.info.cpu_m if it.info is not None else int(it.capacity.get(CPU, 0))
    pod_count = kubelet.max_pods if kubelet.max_pods is not None else base_pods
    if kubelet.pods_per_core:
        pod_count = min(
            kubelet.pods_per_core * max(cpu_m // 1000, 1), pod_count)
    capacity = ResourceList(it.capacity)
    capacity[PODS] = pod_count
    return InstanceType(
        name=it.name,
        requirements=it.requirements,
        offerings=it.offerings,
        capacity=capacity,
        kube_reserved=kube_reserved(cpu_m, pod_count, kubelet),
        system_reserved=system_reserved(kubelet),
        eviction_threshold=eviction_threshold(
            int(it.capacity.get(MEMORY, 0)),
            int(it.capacity.get(EPHEMERAL_STORAGE, 0)), kubelet),
        info=it.info,
    )


def root_volume_gib(nodeclass) -> Optional[int]:
    """The boot volume size a node of this nodeclass actually gets: the
    root mapping's ebs.volumeSize when blockDeviceMappings are set
    (reference derives ephemeral-storage from the mapped root volume),
    else block_device_gib; None for no nodeclass."""
    if nodeclass is None:
        return None
    for m in nodeclass.block_device_mappings:
        size = (m.get("ebs") or {}).get("volumeSize")
        if size is not None:
            from ..api.resources import EPHEMERAL_STORAGE, parse_quantity
            return max(1, int(parse_quantity(size, EPHEMERAL_STORAGE) // GiB))
    return int(nodeclass.block_device_gib)


def apply_storage(it: "InstanceType", root_gib: Optional[int]) -> "InstanceType":
    """Re-derive ephemeral-storage capacity (and its 10% hard-eviction
    share) for a different boot volume size, keeping everything else."""
    if root_gib is None or int(it.capacity.get(EPHEMERAL_STORAGE, 0)) == \
            root_gib * GiB:
        return it
    storage = root_gib * GiB
    capacity = ResourceList(it.capacity)
    capacity[EPHEMERAL_STORAGE] = storage
    eviction = ResourceList(it.eviction_threshold)
    eviction[EPHEMERAL_STORAGE] = int(math.ceil(storage / 10))
    return InstanceType(
        name=it.name, requirements=it.requirements, offerings=it.offerings,
        capacity=capacity, kube_reserved=it.kube_reserved,
        system_reserved=it.system_reserved, eviction_threshold=eviction,
        info=it.info)


def effective_instance_type(it: "InstanceType", pool,
                            nodeclass=None) -> "InstanceType":
    """The type as a node of `pool` actually presents it: boot-volume
    storage from the pool's nodeclass, then kubelet-adjusted density and
    reserves (either may be None/default — unknown pools register with the
    catalog's own math).  The one helper every registration site AND the
    solver's per-pool catalog columns share, so node allocatable always
    matches what the solver packed against."""
    it = apply_storage(it, root_volume_gib(nodeclass))
    if pool is None:
        return it
    return apply_kubelet(it, pool.template.kubelet)


def new_instance_type(info: InstanceTypeInfo, offerings: Sequence[Offering],
                      kubelet: Optional[KubeletConfiguration] = None,
                      block_device_gib: int = 20,
                      vm_memory_overhead_percent: float = VM_MEMORY_OVERHEAD_PERCENT,
                      eni_limited_density: bool = False,
                      reserved_enis: int = 0) -> InstanceType:
    """Factory mirroring NewInstanceType
    (/root/reference/pkg/providers/instancetype/types.go:53-72): capacity from
    the catalog row (memory shaved by the VM overhead percent), overhead from
    the kubelet config, requirements from the labels."""
    pod_count = max_pods(info, kubelet, eni_limited_density, reserved_enis)
    storage = block_device_gib * GiB
    mem = int(info.memory_bytes * (1 - vm_memory_overhead_percent))
    capacity = ResourceList({
        CPU: info.cpu_m, MEMORY: mem, EPHEMERAL_STORAGE: storage, PODS: pod_count,
    })
    if info.gpu_count:
        capacity[GPU] = info.gpu_count
    if info.neuron_count:
        capacity[NEURON] = info.neuron_count
    if info.network_interfaces:
        capacity[POD_ENI] = max(info.network_interfaces - reserved_enis, 0)
    return InstanceType(
        name=info.name,
        requirements=compute_requirements(info, offerings),
        offerings=list(offerings),
        capacity=capacity,
        kube_reserved=kube_reserved(info.cpu_m, pod_count, kubelet),
        system_reserved=system_reserved(kubelet),
        eviction_threshold=eviction_threshold(mem, storage, kubelet),
        info=info,
    )
