from .instancetype import (InstanceType, InstanceTypeInfo, Offering,
                           new_instance_type, compute_requirements,
                           eni_limited_pods, max_pods, kube_reserved,
                           system_reserved, eviction_threshold,
                           DEFAULT_MAX_PODS, VM_MEMORY_OVERHEAD_PERCENT, MiB, GiB)
