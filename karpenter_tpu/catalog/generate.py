"""Synthetic instance-type catalog generator.

Stands in for the reference's generated data tables
(/root/reference/pkg/providers/instancetype/zz_generated.vpclimits.go and the
DescribeInstanceTypes path at
/root/reference/pkg/providers/instancetype/instancetype.go:241-278): a
deterministic catalog of ~600-700 types across general/compute/memory
families, burstable, storage/network variants, and accelerator families,
offered in N zones × {on-demand, spot} with size-proportional pricing.

Used by the fake cloud, the test suites, and bench.py (BASELINE.json configs
call for 10/200/600-type catalogs)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .instancetype import GiB, InstanceType, InstanceTypeInfo, Offering, new_instance_type

DEFAULT_ZONES = ("zone-a", "zone-b", "zone-c")

# family → (memory per vcpu GiB, $/vcpu-hour base)
_FAMILIES = {
    "c": (2, 0.0425),   # compute optimized
    "m": (4, 0.0480),   # general purpose
    "r": (8, 0.0630),   # memory optimized
    "i": (8, 0.0780),   # storage optimized (always local nvme)
    "x": (16, 0.1670),  # high-memory
}
_VARIANTS = {          # price multiplier, network multiplier
    "": (1.00, 1.0),
    "a": (0.90, 1.0),  # alt-silicon discount
    "d": (1.13, 1.0),  # local nvme
    "n": (1.25, 4.0),  # network optimized
    "i": (1.08, 1.0),
}
_SIZES = {             # size → vcpus
    "large": 2, "xlarge": 4, "2xlarge": 8, "4xlarge": 16,
    "8xlarge": 32, "12xlarge": 48, "16xlarge": 64, "24xlarge": 96,
    "48xlarge": 192,
}
_GENERATIONS = (4, 5, 6, 7)

# accelerator families: name → (gpus per size map, vcpu/gpu, mem GiB/gpu, $/gpu-hr, gpu name)
_GPU_FAMILIES = {
    "g5": ({"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "12xlarge": 4, "24xlarge": 4, "48xlarge": 8},
           4, 16, 1.006, "a10g"),
    "p4d": ({"24xlarge": 8}, 12, 96, 4.096, "a100"),
    "p5": ({"48xlarge": 8}, 24, 128, 12.29, "h100"),
}


def generate_infos(zones: Sequence[str] = DEFAULT_ZONES) -> List[InstanceTypeInfo]:
    infos: List[InstanceTypeInfo] = []
    for fam, (mem_ratio, base) in _FAMILIES.items():
        for gen in _GENERATIONS:
            for var, (pmult, nmult) in _VARIANTS.items():
                if fam in ("i", "x") and var not in ("", "n"):
                    continue  # niche families ship fewer variants
                for size, vcpus in _SIZES.items():
                    name = f"{fam}{gen}{var}.{size}"
                    gen_mult = 1.0 - 0.02 * (7 - gen)
                    infos.append(InstanceTypeInfo(
                        name=name, cpu_m=vcpus * 1000,
                        memory_bytes=vcpus * mem_ratio * GiB,
                        family=f"{fam}{gen}{var}", size=size, category=fam,
                        generation=gen,
                        network_interfaces=min(4 + vcpus // 16, 8),
                        ips_per_interface=15,
                        network_bandwidth_mbps=int(625 * vcpus * nmult),
                        local_nvme_gib=vcpus * 75 if var == "d" or fam == "i" else 0,
                        on_demand_price=round(vcpus * base * pmult * gen_mult, 4),
                    ))
    # bare-metal flagships (filtered from launch paths unless explicitly
    # required, mirroring the reference's exotic-type filter instance.go:416-424)
    for fam, (mem_ratio, base) in _FAMILIES.items():
        infos.append(InstanceTypeInfo(
            name=f"{fam}7.metal", cpu_m=96_000, memory_bytes=96 * mem_ratio * GiB,
            family=f"{fam}7", size="metal", category=fam, generation=7,
            hypervisor="", bare_metal=True, network_interfaces=8,
            ips_per_interface=30, network_bandwidth_mbps=100_000,
            on_demand_price=round(96 * base * 1.05, 4)))
    # burstable family
    for size, vcpus in (("micro", 2), ("small", 2), ("medium", 2),
                        ("large", 2), ("xlarge", 4), ("2xlarge", 8)):
        mem = {"micro": 1, "small": 2, "medium": 4}.get(size, vcpus * 4)
        infos.append(InstanceTypeInfo(
            name=f"t3.{size}", cpu_m=vcpus * 1000, memory_bytes=mem * GiB,
            family="t3", size=size, category="t", generation=3,
            network_interfaces=3, ips_per_interface=6,
            network_bandwidth_mbps=5000,
            on_demand_price=round(0.0052 * vcpus * max(mem, 1), 4)))
    # accelerated
    for fam, (sizes, vcpu_per, mem_per, gpu_price, gpu_name) in _GPU_FAMILIES.items():
        for size, gpus in sizes.items():
            vcpus = max(int(size.rstrip("xlarge") or 1) * 4, 4)
            vcpus = max(vcpus, gpus * vcpu_per)
            infos.append(InstanceTypeInfo(
                name=f"{fam}.{size}", cpu_m=vcpus * 1000,
                memory_bytes=gpus * mem_per * GiB + vcpus * 2 * GiB,
                family=fam, size=size, category="g" if fam.startswith("g") else "p",
                generation=5, gpu_count=gpus, gpu_name=gpu_name,
                gpu_memory_bytes=24 * GiB,
                network_interfaces=8, ips_per_interface=30,
                network_bandwidth_mbps=100_000,
                on_demand_price=round(gpus * gpu_price + vcpus * 0.02, 4)))
    return infos


def zonal_price_skew(zone: str) -> float:
    """Deterministic small per-zone price variation (spot markets differ by AZ)."""
    return 1.0 + 0.015 * (sum(map(ord, zone)) % 5)


def generate_catalog(n_types: Optional[int] = None,
                     zones: Sequence[str] = DEFAULT_ZONES,
                     spot: bool = True,
                     spot_discount: float = 0.65,
                     kubelet=None) -> List[InstanceType]:
    """Build `n_types` InstanceTypes (None == all ~700)."""
    infos = generate_infos(zones)
    if n_types is not None and n_types < len(infos):
        # spread selection across the whole catalog (preserves family
        # diversity incl. the accelerator tail) deterministically
        idx = [round(i * (len(infos) - 1) / (n_types - 1)) for i in range(n_types)] \
            if n_types > 1 else [0]
        infos = [infos[i] for i in dict.fromkeys(idx)]
    out = []
    for info in infos:
        offerings = []
        for z in zones:
            offerings.append(Offering(z, "on-demand", info.on_demand_price))
            if spot:
                offerings.append(Offering(
                    z, "spot",
                    round(info.on_demand_price * (1 - spot_discount) * zonal_price_skew(z), 4)))
        out.append(new_instance_type(info, offerings, kubelet=kubelet))
    return out
