"""Scenario DSL: declarative workload waves + fault schedule.

A scenario is a YAML document (or the `Scenario` dataclass directly)
describing what hits the cluster over a virtual-time window:

  * **workload waves** — a diurnal sinusoid of arrivals, a step burst, or
    batch-job cohorts with completion times;
  * **faults** — spot-reclaim storms, ICE windows per capacity pool, spot
    price drift, API throttle bursts, node-ready latency shifts.

`expand(scenario, seed)` lowers the spec to a flat, time-sorted list of
typed events, deterministically: the same (scenario, seed) pair always
yields the same pods with the same names, requests, and arrival times.
Each wave/fault draws from its own `numpy` Generator keyed on
``[seed, stream-index]`` so adding a wave never perturbs its siblings.

Schema reference: docs/simulation.md.  `tools/simcheck.py` validates a
file and prints its expanded event count.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.objects import Pod
from ..api.resources import CPU, MEMORY, ResourceList
from .events import (ApiThrottle, IceClose, IceOpen, NodeReadyLatency,
                     PodArrival, PodDeparture, PriceDrift, SimEvent,
                     SpotReclaim)


class ScenarioError(ValueError):
    """A scenario document failed validation; the message names the field."""


# ---------------------------------------------------------------------------
# spec dataclasses
# ---------------------------------------------------------------------------

WAVE_KINDS = ("diurnal", "step", "batch")
FAULT_KINDS = ("spot_reclaim_storm", "ice_window", "price_drift",
               "api_throttle", "node_ready_latency")

# sim-friendly controller cadences: virtual seconds between reconciles.
# Coarser than the live defaults (manager.DEFAULT_INTERVALS) because at
# >1000x time compression a 10s consolidation cadence burns wall time
# re-evaluating an unchanged cluster; scenarios may override per entry.
DEFAULT_SIM_INTERVALS: Dict[str, float] = {
    "termination": 5.0,
    "disruption": 300.0,
    "lifecycle": 5.0,
    "garbagecollection": 120.0,
    "tagging": 300.0,
    "nodeclass": 3600.0,
    "interruption": 5.0,
    "pricing": 600.0,
    "forecast": 300.0,
}


@dataclass
class Wave:
    """One workload stream.

    kind=diurnal — arrivals follow a sinusoidal Poisson rate
        rate(t) = base_per_hour * (1 + amplitude * sin(2π (t-phase)/period))
      bucketed into `bucket_s` cohorts; each cohort departs `lifetime_s`
      after arrival (0 = stays forever).
    kind=step — `count` pods arrive at `at_s`, depart `duration_s` later
      (0 = stay forever).
    kind=batch — `cohorts` cohorts of `count` pods, the first at `at_s`,
      then one every `every_s`; each completes (departs) `runtime_s` after
      arrival.
    """
    kind: str
    name: str
    # shared pod shape
    cpu_m: Tuple[int, int] = (250, 2000)
    mem_mib: Tuple[int, int] = (256, 4096)
    # diurnal
    base_per_hour: float = 30.0
    amplitude: float = 0.8
    period_s: float = 86_400.0
    phase_s: float = 0.0
    bucket_s: float = 300.0
    lifetime_s: float = 7_200.0
    # step / batch
    at_s: float = 0.0
    count: int = 10
    duration_s: float = 0.0
    cohorts: int = 1
    every_s: float = 21_600.0
    runtime_s: float = 1_800.0
    # gang scheduling (docs/gang.md): gang_size > 0 folds each cohort's
    # pods into consecutive all-or-nothing gangs of that size (pods past
    # the last full gang stay ungrouped).  Pod shapes draw from the SAME
    # rng stream as ungrouped waves — adding gang fields never perturbs
    # sibling randomness or pre-gang goldens.
    gang_size: int = 0
    gang_tier: int = 0
    gang_topology: str = "zone"

    def validate(self) -> None:
        if self.kind not in WAVE_KINDS:
            raise ScenarioError(
                f"wave {self.name!r}: unknown kind {self.kind!r} "
                f"(expected one of {WAVE_KINDS})")
        if not self.name:
            raise ScenarioError("every wave needs a name")
        for fld in ("cpu_m", "mem_mib"):
            lo, hi = getattr(self, fld)
            if not (0 < lo <= hi):
                raise ScenarioError(
                    f"wave {self.name!r}: {fld} range must satisfy "
                    f"0 < lo <= hi, got {(lo, hi)}")
        if self.kind == "diurnal":
            if self.base_per_hour <= 0 or self.period_s <= 0 or self.bucket_s <= 0:
                raise ScenarioError(
                    f"wave {self.name!r}: base_per_hour, period_s and "
                    "bucket_s must be positive")
            if not 0 <= self.amplitude <= 1:
                raise ScenarioError(
                    f"wave {self.name!r}: amplitude must be in [0, 1]")
        if self.kind in ("step", "batch") and self.count <= 0:
            raise ScenarioError(f"wave {self.name!r}: count must be positive")
        if self.kind == "batch" and (self.cohorts <= 0 or self.every_s <= 0
                                     or self.runtime_s <= 0):
            raise ScenarioError(
                f"wave {self.name!r}: cohorts, every_s, runtime_s must be "
                "positive")
        if self.gang_size < 0 or self.gang_tier < 0:
            raise ScenarioError(
                f"wave {self.name!r}: gang_size and gang_tier must be >= 0")
        if self.gang_topology not in ("zone", "hostname"):
            raise ScenarioError(
                f"wave {self.name!r}: gang_topology must be 'zone' or "
                f"'hostname', got {self.gang_topology!r}")


@dataclass
class Fault:
    """One fault-schedule entry (kinds in FAULT_KINDS)."""
    kind: str
    at_s: float
    name: str = ""
    # spot_reclaim_storm
    count: int = 1
    warning_s: float = 120.0
    repeat: int = 1
    every_s: float = 600.0
    # ice_window — pool triples [capacity_type, instance_type, zone];
    # "*" wildcards resolve against the catalog at delivery
    pools: List[Tuple[str, str, str]] = field(default_factory=list)
    duration_s: float = 600.0
    # price_drift
    factor: float = 1.0
    jitter: float = 0.0
    # node_ready_latency
    latency_s: float = 0.0

    def validate(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ScenarioError(
                f"fault {self.name or self.kind!r}: unknown kind "
                f"{self.kind!r} (expected one of {FAULT_KINDS})")
        if self.at_s < 0:
            raise ScenarioError(f"fault {self.name!r}: at_s must be >= 0")
        if self.kind == "spot_reclaim_storm":
            if self.count <= 0 or self.repeat <= 0 or self.every_s <= 0:
                raise ScenarioError(
                    f"fault {self.name!r}: count, repeat, every_s must be "
                    "positive")
        if self.kind == "ice_window":
            if not self.pools:
                raise ScenarioError(
                    f"fault {self.name!r}: ice_window needs pools")
            for p in self.pools:
                if len(p) != 3:
                    raise ScenarioError(
                        f"fault {self.name!r}: pool {p!r} must be "
                        "[capacity_type, instance_type, zone]")
            if self.duration_s <= 0:
                raise ScenarioError(
                    f"fault {self.name!r}: duration_s must be positive")
        if self.kind == "price_drift" and self.factor <= 0:
            raise ScenarioError(f"fault {self.name!r}: factor must be > 0")
        if self.kind == "api_throttle" and self.duration_s <= 0:
            raise ScenarioError(
                f"fault {self.name!r}: duration_s must be positive")
        if self.kind == "node_ready_latency" and self.latency_s < 0:
            raise ScenarioError(
                f"fault {self.name!r}: latency_s must be >= 0")


@dataclass
class ForecastSpec:
    """Forecast/headroom configuration for a scenario (docs/forecast.md).
    `enabled: true` turns the Forecast gate on for the simulated operator;
    the knobs map 1:1 onto the forecast_* Options fields."""
    enabled: bool = True
    horizon_s: float = 900.0
    lead_s: float = 180.0
    ttl_s: float = 600.0
    bucket_s: float = 60.0
    confidence: float = 1.64
    max_cost_frac: float = 0.10
    model: str = "holtwinters"
    season_s: float = 86_400.0

    def validate(self) -> None:
        for fld in ("horizon_s", "lead_s", "ttl_s", "bucket_s",
                    "confidence", "season_s"):
            if getattr(self, fld) <= 0:
                raise ScenarioError(f"forecast: {fld} must be positive")
        if not 0.0 < self.max_cost_frac <= 1.0:
            raise ScenarioError("forecast: max_cost_frac must be in (0, 1]")
        if self.model not in ("ewma", "holtwinters"):
            raise ScenarioError(
                f"forecast: unknown model {self.model!r} "
                "(expected ewma or holtwinters)")


@dataclass
class ChaosRuleSpec:
    """One chaos fault stream (docs/simulation.md, utils/chaos.py).  Times
    are scenario-relative seconds; the harness rebases them onto the
    virtual clock before arming the injector.  `until_s: 0` means "until
    the end of the run"."""
    point: str
    key: str = ""
    action: str = "error"
    rate: float = 1.0
    at_s: float = 0.0
    until_s: float = 0.0
    latency_s: float = 0.0
    count: int = 0
    error_code: str = ""

    def validate(self, ctx: str) -> None:
        from ..utils.chaos import ACTIONS, POINTS
        if self.point not in POINTS:
            raise ScenarioError(f"{ctx}: unknown point {self.point!r} "
                                f"(expected one of {sorted(POINTS)})")
        if self.action not in ACTIONS:
            raise ScenarioError(f"{ctx}: unknown action {self.action!r} "
                                f"(expected one of {ACTIONS})")
        if not 0.0 < self.rate <= 1.0:
            raise ScenarioError(f"{ctx}: rate must be in (0, 1]")
        if self.at_s < 0:
            raise ScenarioError(f"{ctx}: at_s must be >= 0")
        if self.until_s and self.until_s <= self.at_s:
            raise ScenarioError(f"{ctx}: until_s must be > at_s (or 0 for "
                                "open-ended)")
        if self.action in ("latency", "hang") and self.latency_s <= 0:
            raise ScenarioError(
                f"{ctx}: {self.action} needs latency_s > 0")
        if self.count < 0:
            raise ScenarioError(f"{ctx}: count must be >= 0")


@dataclass
class ChaosSpec:
    """Deterministic fault-injection schedule for a scenario.  `seed: null`
    derives the chaos streams from the run seed, so `--seed` replays move
    the whole schedule together; an explicit seed pins the schedule while
    workload randomness still follows the run seed."""
    enabled: bool = True
    seed: Optional[int] = None
    rules: List[ChaosRuleSpec] = field(default_factory=list)

    def validate(self) -> None:
        if not self.rules:
            raise ScenarioError("chaos: needs at least one rule")
        for i, r in enumerate(self.rules):
            r.validate(f"chaos.rules[{i}]")


@dataclass
class HASpec:
    """HA failover configuration for a scenario (docs/robustness.md "HA
    failover").  `enabled: true` turns the HAFailover gate on for the
    simulated operator: a virtual-clock `LeaderElector` is wired in (so
    lease expiry, chaos at `leader.lease`, and fencing refusals all play
    out deterministically) and the report grows an "ha" section."""
    enabled: bool = True
    ttl_s: float = 15.0

    def validate(self) -> None:
        if self.ttl_s <= 0:
            raise ScenarioError("ha: ttl_s must be positive")


@dataclass
class SLOSpec:
    """SLO engine + cost ledger configuration for a scenario
    (docs/observability.md "SLO engine").  `enabled: true` turns the
    SLOEngine gate on for the simulated operator: recording rules
    evaluate over the virtual clock and the report grows gated
    `slo.budgets` / `ledger` sections."""
    enabled: bool = True
    eval_cadence_s: float = 60.0
    drift_threshold: float = 0.15

    def validate(self) -> None:
        if self.eval_cadence_s <= 0:
            raise ScenarioError("slo: eval_cadence_s must be positive")
        if self.drift_threshold <= 0:
            raise ScenarioError("slo: drift_threshold must be positive")


@dataclass
class GangSpec:
    """Gang scheduling configuration for a scenario (docs/gang.md).
    `enabled: true` turns the GangScheduling gate on for the simulated
    operator — all-or-nothing admission, topology-domain enforcement and
    tier preemption run over the virtual clock, and the report grows a
    "gang" section.  The spec lives in the scenario (not a harness flag)
    so the golden-regeneration one-liner needs no per-case arguments."""
    enabled: bool = True

    def validate(self) -> None:
        pass


@dataclass
class Scenario:
    name: str
    duration_s: float = 86_400.0
    start_s: float = 10_000.0        # nonzero so age math never sees t=0
    slo_bind_s: float = 300.0        # time-to-bind SLO for the report
    settle_s: float = 0.0            # post-workload quiesce window
    # cluster substrate
    catalog_size: int = 25
    zones: Tuple[str, ...] = ("zone-a", "zone-b")
    # manager knobs (virtual seconds)
    batch_idle_s: float = 1.0
    batch_max_s: float = 10.0
    node_ready_latency_s: float = 0.0
    intervals: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_SIM_INTERVALS))
    workload: List[Wave] = field(default_factory=list)
    faults: List[Fault] = field(default_factory=list)
    # proactive headroom provisioning (None = Forecast gate stays off)
    forecast: Optional[ForecastSpec] = None
    # deterministic fault injection (None = injector stays disarmed)
    chaos: Optional[ChaosSpec] = None
    # fenced leadership drill (None = HAFailover gate stays off)
    ha: Optional[HASpec] = None
    # SLO recording rules + cost ledger (None = SLOEngine gate stays off)
    slo: Optional[SLOSpec] = None
    # gang scheduling (None = GangScheduling gate stays off)
    gang: Optional[GangSpec] = None

    def validate(self) -> None:
        if not self.name:
            raise ScenarioError("scenario needs a name")
        if self.duration_s <= 0:
            raise ScenarioError("duration_s must be positive")
        if self.catalog_size <= 0:
            raise ScenarioError("catalog_size must be positive")
        if not self.zones:
            raise ScenarioError("at least one zone required")
        if self.batch_idle_s <= 0 or self.batch_max_s < self.batch_idle_s:
            raise ScenarioError(
                "batch windows must satisfy 0 < batch_idle_s <= batch_max_s")
        if not self.workload:
            raise ScenarioError("scenario has no workload waves")
        for w in self.workload:
            w.validate()
        for f in self.faults:
            f.validate()
        if self.forecast is not None:
            self.forecast.validate()
        if self.chaos is not None:
            self.chaos.validate()
        if self.ha is not None:
            self.ha.validate()
        if self.slo is not None:
            self.slo.validate()
        if self.gang is not None:
            self.gang.validate()
        names = [w.name for w in self.workload]
        if len(set(names)) != len(names):
            raise ScenarioError(f"duplicate wave names: {names}")
        for k in self.intervals:
            if k not in DEFAULT_SIM_INTERVALS:
                raise ScenarioError(
                    f"intervals: unknown controller {k!r} (expected one of "
                    f"{sorted(DEFAULT_SIM_INTERVALS)})")


# ---------------------------------------------------------------------------
# YAML loading
# ---------------------------------------------------------------------------

_SCENARIO_SCALARS = {
    "duration_s": float, "start_s": float, "slo_bind_s": float,
    "settle_s": float, "catalog_size": int, "batch_idle_s": float,
    "batch_max_s": float, "node_ready_latency_s": float,
}
_WAVE_FIELDS = {
    "kind": str, "name": str, "base_per_hour": float, "amplitude": float,
    "period_s": float, "phase_s": float, "bucket_s": float,
    "lifetime_s": float, "at_s": float, "count": int, "duration_s": float,
    "cohorts": int, "every_s": float, "runtime_s": float,
    "gang_size": int, "gang_tier": int, "gang_topology": str,
}
_FAULT_FIELDS = {
    "kind": str, "name": str, "at_s": float, "count": int,
    "warning_s": float, "repeat": int, "every_s": float,
    "duration_s": float, "factor": float, "jitter": float,
    "latency_s": float,
}
_FORECAST_FIELDS = {
    "enabled": bool, "horizon_s": float, "lead_s": float, "ttl_s": float,
    "bucket_s": float, "confidence": float, "max_cost_frac": float,
    "model": str, "season_s": float,
}
_CHAOS_RULE_FIELDS = {
    "point": str, "key": str, "action": str, "rate": float, "at_s": float,
    "until_s": float, "latency_s": float, "count": int, "error_code": str,
}
_HA_FIELDS = {
    "enabled": bool, "ttl_s": float,
}
_SLO_FIELDS = {
    "enabled": bool, "eval_cadence_s": float, "drift_threshold": float,
}
_GANG_FIELDS = {
    "enabled": bool,
}


def _coerce(ctx: str, doc: Dict, schema: Dict) -> Dict:
    out = {}
    for key, val in doc.items():
        if key not in schema:
            continue  # handled by caller (ranges, lists) or rejected there
        try:
            out[key] = schema[key](val)
        except (TypeError, ValueError) as e:
            raise ScenarioError(f"{ctx}: field {key!r}={val!r}: {e}") from e
    return out


def _range(ctx: str, val, default: Tuple[int, int]) -> Tuple[int, int]:
    if val is None:
        return default
    if not isinstance(val, (list, tuple)) or len(val) != 2:
        raise ScenarioError(f"{ctx}: expected [lo, hi], got {val!r}")
    return (int(val[0]), int(val[1]))


def scenario_from_dict(doc: Dict) -> Scenario:
    """Lower a parsed YAML document to a validated `Scenario`."""
    if not isinstance(doc, dict):
        raise ScenarioError(f"scenario document must be a mapping, "
                            f"got {type(doc).__name__}")
    known = {"name", "zones", "intervals", "workload", "faults",
             "forecast", "chaos", "ha", "slo", "gang", *_SCENARIO_SCALARS}
    for key in doc:
        if key not in known:
            raise ScenarioError(f"unknown scenario field {key!r} "
                                f"(expected one of {sorted(known)})")
    kw = _coerce("scenario", doc, _SCENARIO_SCALARS)
    kw["name"] = str(doc.get("name", ""))
    if "zones" in doc:
        kw["zones"] = tuple(str(z) for z in doc["zones"])
    if "intervals" in doc:
        if not isinstance(doc["intervals"], dict):
            raise ScenarioError("intervals must be a mapping")
        iv = dict(DEFAULT_SIM_INTERVALS)
        iv.update({str(k): float(v) for k, v in doc["intervals"].items()})
        kw["intervals"] = iv
    waves = []
    for i, w in enumerate(doc.get("workload", []) or []):
        if not isinstance(w, dict):
            raise ScenarioError(f"workload[{i}] must be a mapping")
        ctx = f"workload[{i}]"
        for key in w:
            if key not in _WAVE_FIELDS and key not in ("cpu_m", "mem_mib"):
                raise ScenarioError(f"{ctx}: unknown field {key!r}")
        wkw = _coerce(ctx, w, _WAVE_FIELDS)
        wkw["cpu_m"] = _range(ctx, w.get("cpu_m"), (250, 2000))
        wkw["mem_mib"] = _range(ctx, w.get("mem_mib"), (256, 4096))
        waves.append(Wave(**wkw))
    kw["workload"] = waves
    faults = []
    for i, f in enumerate(doc.get("faults", []) or []):
        if not isinstance(f, dict):
            raise ScenarioError(f"faults[{i}] must be a mapping")
        ctx = f"faults[{i}]"
        for key in f:
            if key not in _FAULT_FIELDS and key != "pools":
                raise ScenarioError(f"{ctx}: unknown field {key!r}")
        fkw = _coerce(ctx, f, _FAULT_FIELDS)
        if "pools" in f:
            fkw["pools"] = [tuple(str(x) for x in p) for p in f["pools"]]
        faults.append(Fault(**fkw))
    kw["faults"] = faults
    if doc.get("forecast") is not None:
        fdoc = doc["forecast"]
        if not isinstance(fdoc, dict):
            raise ScenarioError("forecast must be a mapping")
        for key in fdoc:
            if key not in _FORECAST_FIELDS:
                raise ScenarioError(f"forecast: unknown field {key!r}")
        kw["forecast"] = ForecastSpec(
            **_coerce("forecast", fdoc, _FORECAST_FIELDS))
    if doc.get("chaos") is not None:
        cdoc = doc["chaos"]
        if not isinstance(cdoc, dict):
            raise ScenarioError("chaos must be a mapping")
        for key in cdoc:
            if key not in ("enabled", "seed", "rules"):
                raise ScenarioError(f"chaos: unknown field {key!r}")
        rules = []
        for i, r in enumerate(cdoc.get("rules", []) or []):
            if not isinstance(r, dict):
                raise ScenarioError(f"chaos.rules[{i}] must be a mapping")
            for key in r:
                if key not in _CHAOS_RULE_FIELDS:
                    raise ScenarioError(
                        f"chaos.rules[{i}]: unknown field {key!r}")
            rules.append(ChaosRuleSpec(
                **_coerce(f"chaos.rules[{i}]", r, _CHAOS_RULE_FIELDS)))
        kw["chaos"] = ChaosSpec(
            enabled=bool(cdoc.get("enabled", True)),
            seed=None if cdoc.get("seed") is None else int(cdoc["seed"]),
            rules=rules)
    if doc.get("ha") is not None:
        hdoc = doc["ha"]
        if not isinstance(hdoc, dict):
            raise ScenarioError("ha must be a mapping")
        for key in hdoc:
            if key not in _HA_FIELDS:
                raise ScenarioError(f"ha: unknown field {key!r}")
        kw["ha"] = HASpec(**_coerce("ha", hdoc, _HA_FIELDS))
    if doc.get("slo") is not None:
        sdoc = doc["slo"]
        if not isinstance(sdoc, dict):
            raise ScenarioError("slo must be a mapping")
        for key in sdoc:
            if key not in _SLO_FIELDS:
                raise ScenarioError(f"slo: unknown field {key!r}")
        kw["slo"] = SLOSpec(**_coerce("slo", sdoc, _SLO_FIELDS))
    if doc.get("gang") is not None:
        gdoc = doc["gang"]
        if not isinstance(gdoc, dict):
            raise ScenarioError("gang must be a mapping")
        for key in gdoc:
            if key not in _GANG_FIELDS:
                raise ScenarioError(f"gang: unknown field {key!r}")
        kw["gang"] = GangSpec(**_coerce("gang", gdoc, _GANG_FIELDS))
    sc = Scenario(**kw)
    sc.validate()
    return sc


def load_scenario(path: str) -> Scenario:
    import yaml
    try:
        with open(path) as fh:
            doc = yaml.safe_load(fh)
    except OSError as e:
        raise ScenarioError(f"cannot read scenario {path!r}: {e}") from e
    except yaml.YAMLError as e:
        raise ScenarioError(f"bad YAML in {path!r}: {e}") from e
    return scenario_from_dict(doc)


# ---------------------------------------------------------------------------
# deterministic expansion
# ---------------------------------------------------------------------------

def _make_pod(wave: Wave, name: str, rng: np.random.Generator) -> Pod:
    cpu = int(rng.integers(wave.cpu_m[0], wave.cpu_m[1] + 1))
    mem = int(rng.integers(wave.mem_mib[0], wave.mem_mib[1] + 1)) * 2 ** 20
    return Pod(name=name, uid=name,
               requests=ResourceList({CPU: cpu, MEMORY: mem}),
               labels={"sim.karpenter.sh/wave": wave.name})


def _cohort(wave: Wave, tag: str, n: int, rng: np.random.Generator) -> List[Pod]:
    pods = [_make_pod(wave, f"{wave.name}-{tag}-{j:04d}", rng)
            for j in range(n)]
    if wave.gang_size > 0:
        # consecutive full gangs by pod index — deterministic, no extra
        # rng draws.  The cohort tail past the last full gang stays
        # ungrouped: a permanently-short gang would be unschedulable by
        # construction under all-or-nothing admission.
        full = (n // wave.gang_size) * wave.gang_size
        for j in range(full):
            p = pods[j]
            p.gang_name = f"{wave.name}-{tag}-g{j // wave.gang_size:03d}"
            p.gang_size = wave.gang_size
            p.gang_tier = wave.gang_tier
            p.gang_topology = wave.gang_topology
    return pods


def _expand_wave(wave: Wave, wi: int, sc: Scenario, seed: int
                 ) -> List[Tuple[float, SimEvent]]:
    # one independent stream per wave: inserting a wave never reshuffles
    # the randomness of its siblings
    rng = np.random.default_rng([int(seed), 1000 + wi])
    t0, dur = sc.start_s, sc.duration_s
    out: List[Tuple[float, SimEvent]] = []

    def arrive(at: float, pods: List[Pod], lifetime: float):
        if not pods:
            return
        out.append((at, PodArrival(pods=pods, wave=wave.name)))
        if lifetime > 0:
            out.append((at + lifetime,
                        PodDeparture(uids=[p.uid for p in pods],
                                     wave=wave.name)))

    if wave.kind == "diurnal":
        buckets = int(math.ceil(dur / wave.bucket_s))
        for b in range(buckets):
            rel = b * wave.bucket_s
            width = min(wave.bucket_s, dur - rel)
            mid = rel + width / 2.0
            rate = wave.base_per_hour * (
                1.0 + wave.amplitude * math.sin(
                    2.0 * math.pi * (mid - wave.phase_s) / wave.period_s))
            lam = max(0.0, rate) * width / 3600.0
            n = int(rng.poisson(lam))
            at = t0 + rel + float(rng.uniform(0.0, width))
            arrive(at, _cohort(wave, f"b{b:05d}", n, rng), wave.lifetime_s)
    elif wave.kind == "step":
        at = t0 + wave.at_s
        arrive(at, _cohort(wave, "step", wave.count, rng), wave.duration_s)
    elif wave.kind == "batch":
        for k in range(wave.cohorts):
            at = t0 + wave.at_s + k * wave.every_s
            if at - t0 >= dur:
                break
            arrive(at, _cohort(wave, f"c{k:03d}", wave.count, rng),
                   wave.runtime_s)
    return out


def _expand_fault(fault: Fault, fi: int, sc: Scenario, seed: int
                  ) -> List[Tuple[float, SimEvent]]:
    name = fault.name or f"{fault.kind}-{fi}"
    t0 = sc.start_s
    out: List[Tuple[float, SimEvent]] = []
    if fault.kind == "spot_reclaim_storm":
        for r in range(fault.repeat):
            at = t0 + fault.at_s + r * fault.every_s
            if at - t0 >= sc.duration_s:
                break
            out.append((at, SpotReclaim(count=fault.count,
                                        warning_s=fault.warning_s,
                                        fault=name)))
    elif fault.kind == "ice_window":
        at = t0 + fault.at_s
        out.append((at, IceOpen(pools=list(fault.pools), fault=name)))
        out.append((at + fault.duration_s,
                    IceClose(pools=list(fault.pools), fault=name)))
    elif fault.kind == "price_drift":
        out.append((t0 + fault.at_s,
                    PriceDrift(factor=fault.factor, jitter=fault.jitter,
                               fault=name)))
    elif fault.kind == "api_throttle":
        out.append((t0 + fault.at_s,
                    ApiThrottle(duration_s=fault.duration_s, fault=name)))
    elif fault.kind == "node_ready_latency":
        out.append((t0 + fault.at_s,
                    NodeReadyLatency(latency_s=fault.latency_s, fault=name)))
    return out


def expand(sc: Scenario, seed: int) -> List[Tuple[float, SimEvent]]:
    """Lower the scenario to a flat, time-sorted event list.

    Deterministic: same (scenario, seed) -> identical events, pods, and
    order.  Ties in time keep (workload-before-faults, spec order) — a
    stable key, never object identity."""
    sc.validate()
    entries: List[Tuple[float, int, SimEvent]] = []
    seq = 0
    for wi, wave in enumerate(sc.workload):
        for at, ev in _expand_wave(wave, wi, sc, seed):
            entries.append((at, seq, ev))
            seq += 1
    for fi, fault in enumerate(sc.faults):
        for at, ev in _expand_fault(fault, fi, sc, seed):
            entries.append((at, seq, ev))
            seq += 1
    entries.sort(key=lambda e: (e[0], e[1]))
    return [(at, ev) for at, _, ev in entries]
