"""Virtual time: the injectable clock and the deterministic event heap.

Every component in this repo (controllers, `TTLCache`, `PricingProvider`,
`FakeCloud`, the manager's batch window) takes a ``clock`` callable.  A
`VirtualClock` satisfies that contract while advancing only when the
harness says so — no wall-clock coupling, no sleeps, and a 24-hour run
costs exactly as many clock reads as the event count demands.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, List, Optional, Tuple


class VirtualClock:
    """A monotonically advancing simulated clock.

    Callable (``clock()``) so it drops into every ``clock=`` injection
    point in the stack.  `advance_to` refuses to move backwards — virtual
    time, like real time, only goes one way, and a backwards jump would
    silently corrupt TTL caches and batch windows built on it.
    """

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> float:
        if t < self._now:
            raise ValueError(
                f"virtual clock cannot rewind: now={self._now} target={t}")
        self._now = float(t)
        return self._now

    def advance(self, dt: float) -> float:
        return self.advance_to(self._now + dt)

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return f"VirtualClock(t={self._now:.3f})"


class EventHeap:
    """Deterministic priority queue of (time, event) pairs.

    Ties on time break on insertion order (a monotonically increasing
    sequence number), never on payload comparison — events are plain
    dataclasses with no ordering, and hash-order must never leak into
    delivery order."""

    def __init__(self):
        self._heap: List[Tuple[float, int, Any]] = []
        self._seq = itertools.count(1)

    def push(self, at: float, event: Any) -> None:
        heapq.heappush(self._heap, (float(at), next(self._seq), event))

    def peek_time(self) -> Optional[float]:
        return self._heap[0][0] if self._heap else None

    def pop_due(self, now: float) -> List[Tuple[float, Any]]:
        """All events with time <= now, in (time, insertion) order."""
        out: List[Tuple[float, Any]] = []
        while self._heap and self._heap[0][0] <= now:
            at, _, ev = heapq.heappop(self._heap)
            out.append((at, ev))
        return out

    def __len__(self) -> int:
        return len(self._heap)

    def __bool__(self) -> bool:
        return bool(self._heap)
