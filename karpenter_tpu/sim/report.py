"""Simulation report: one deterministic JSON document per run.

Everything in the report derives from virtual time and harness-tracked
state — cost integral in $·h, pod time-to-bind percentiles, node churn and
disruption counts by reason, SLO-violation and unschedulable-provenance
rollups.  Wall-clock measurements (speedup) are deliberately excluded so
two same-seed runs serialize byte-identically; they live on `SimRun` and
in the metrics registry instead.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence


def percentile(sorted_values: Sequence[float], q: float) -> float:
    """Linear-interpolation percentile over an already-sorted sequence
    (numpy's default method, inlined so the report never depends on float
    printing quirks of array scalars)."""
    if not sorted_values:
        return 0.0
    if len(sorted_values) == 1:
        return float(sorted_values[0])
    pos = (len(sorted_values) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = pos - lo
    return float(sorted_values[lo] * (1 - frac) + sorted_values[hi] * frac)


def _r(x: float, digits: int = 4) -> float:
    return round(float(x), digits)


def build_report(harness) -> Dict:
    """Assemble the report from a finished `SimHarness`."""
    from ..forecast.headroom import is_headroom
    sc = harness.scenario
    binds: List[float] = sorted(harness._bind_t.values())
    arrived = len(harness._arrive_t)
    bound = len(binds)
    # pods placed on a node still booting at sim end never started running:
    # they are pending, not bound (their bind clock stops at NodeReady)
    # — headroom placeholders are capacity reservations, not workload, so
    # they never count as pending (with forecast off this filter is a no-op
    # and every pre-forecast report is byte-identical)
    still_booting = sum(
        1 for uids in harness._booting.values() for uid in uids
        if uid not in harness._bind_t and uid in harness.cluster.pods
        and not is_headroom(harness.cluster.pods[uid]))
    pending_at_end = sum(
        1 for p in harness.cluster.pending_pods()
        if not is_headroom(p)) + still_booting
    slo = sc.slo_bind_s
    late = sum(1 for b in binds if b > slo)
    # pods that never bound and are still waiting (or left unbound) breach
    # the SLO just as surely as a late bind
    violations = late + pending_at_end + harness._departed_unbound

    with harness.cloud._lock:
        instances = list(harness.cloud._instances.values())
    launched = len(instances)
    terminated = sum(1 for i in instances if i.state != "running")
    running_at_end = launched - terminated

    provenance: Dict[str, int] = {}
    for rec in harness.op.provenance.all():
        provenance[rec.constraint] = provenance.get(rec.constraint, 0) + 1

    total_reclaims = harness._reclaims_honored + harness._reclaims_forced
    virtual = harness.clock.now() - sc.start_s
    virtual_h = virtual / 3600.0 if virtual > 0 else 1.0

    report = {
        "scenario": sc.name,
        "seed": harness.seed,
        "virtual_seconds": _r(virtual, 3),
        "workload": {
            "pods_arrived": arrived,
            "pods_bound": bound,
            "pods_pending_at_end": pending_at_end,
            "pods_departed_unbound": harness._departed_unbound,
        },
        "time_to_bind_s": {
            "p50": _r(percentile(binds, 0.50), 3),
            "p95": _r(percentile(binds, 0.95), 3),
            "p99": _r(percentile(binds, 0.99), 3),
            "max": _r(binds[-1], 3) if binds else 0.0,
            "mean": _r(sum(binds) / len(binds), 3) if binds else 0.0,
        },
        "slo": {
            "bind_slo_s": _r(slo, 3),
            "violations": violations,
            "violation_rate": _r(violations / arrived, 6) if arrived else 0.0,
        },
        "cost": {
            "dollar_hours": _r(harness._cost_dollar_hours, 4),
            "dollars_per_hour_avg": _r(
                harness._cost_dollar_hours / virtual_h, 4),
            "node_hours": _r(harness._node_hours, 4),
            "peak_nodes": harness._peak_nodes,
        },
        "churn": {
            "nodes_launched": launched,
            "nodes_terminated": terminated,
            "nodes_running_at_end": running_at_end,
            "disruption_actions": dict(sorted(harness._disruptions.items())),
            "interruption_recycled": harness._interruption_recycled,
            "liveness_terminated": harness._liveness_terminated,
        },
        "spot": {
            "warnings": harness._warnings,
            "reclaims": total_reclaims,
            "reclaims_honored": harness._reclaims_honored,
            "reclaims_forced": harness._reclaims_forced,
            "warning_honor_rate": _r(
                harness._reclaims_honored / total_reclaims, 6)
                if total_reclaims else 1.0,
        },
        "events": {
            "total": sum(harness._events_by_kind.values()),
            "by_kind": dict(sorted(harness._events_by_kind.items())),
        },
        "unschedulable_provenance": dict(sorted(provenance.items())),
        "errors": {
            "tick_exceptions": harness._tick_exceptions,
        },
    }
    forecast = harness.mgr.controllers.get("forecast")
    if forecast is not None:
        # present ONLY when the Forecast gate ran — reports without the
        # gate (every existing golden) carry no forecast section at all
        report["forecast"] = {k: forecast.stats[k]
                              for k in sorted(forecast.stats)}
    if getattr(harness, "_chaos_enabled", False):
        # present ONLY when the scenario armed the injector — same
        # conditional contract as the forecast section, so every chaos-off
        # report stays byte-identical.  Everything here is deterministic:
        # injection counts come from the seeded schedule, supervisor and
        # ladder totals from virtual-clock state machines.
        from ..utils.chaos import CHAOS
        sups = getattr(harness.mgr, "supervisors", {})
        chaos_sec = {
            "injections": CHAOS.counts(),
            "injections_total": CHAOS.fired_total(),
            "controller_failures": {
                n: s.total_failures for n, s in sorted(sups.items())
                if s.total_failures},
            "controller_skips": {
                n: s.total_skips for n, s in sorted(sups.items())
                if s.total_skips},
            "quarantines": {
                n: s.total_quarantines for n, s in sorted(sups.items())
                if s.total_quarantines},
        }
        prov = harness.mgr.controllers.get("provisioning")
        health = getattr(prov, "health", None)
        if health is not None:
            chaos_sec["solver_transitions"] = dict(
                sorted(health.transitions.items()))
        report["chaos"] = chaos_sec
    if getattr(harness, "_ha_enabled", False):
        # present ONLY when the HAFailover gate ran — same conditional
        # contract as forecast/chaos, so every HA-off report (all
        # pre-existing goldens) stays byte-identical.  Everything is
        # deterministic: lease transitions follow the virtual clock and
        # the chaos schedule, fencing refusals the seeded injections.
        leader = harness.leader
        mgr = harness.mgr
        fence = getattr(mgr, "fence", None)
        report["ha"] = {
            "acquisitions": leader.acquisitions,
            "losses": leader.losses,
            "releases": leader.releases,
            "fence_epoch": leader.fence_epoch(),
            "lease_errors": mgr._lease_errors,
            "skipped_ticks": mgr._skipped_ticks,
            "midtick_aborts": mgr._midtick_aborts,
            "promotions": mgr.promotions,
            "phase_at_end": mgr.phase,
            "fence_refusals": dict(sorted(fence.refusals.items()))
            if fence is not None else {},
        }
    if getattr(harness, "_fr_enabled", False) and \
            getattr(harness.mgr, "flight", None) is not None:
        # present ONLY when the FlightRecorder gate ran — same conditional
        # contract as forecast/chaos/ha, so every recorder-off report
        # (all pre-existing goldens) stays byte-identical.  The summary is
        # deterministic: bundle ids are virtual-clock millisecond stamps,
        # dedup windows follow the same clock, and no wall-clock payloads
        # (trace timings, health latencies) are included.
        report["incidents"] = harness.mgr.flight.summary()
    if getattr(harness, "_slo_enabled", False) and \
            getattr(harness.mgr, "slo", None) is not None:
        # present ONLY when the SLOEngine gate ran — same conditional
        # contract as forecast/chaos/ha/incidents, so every gate-off
        # report (all pre-existing goldens) stays byte-identical.  The
        # budgets ride as a sub-key of the existing "slo" section (which
        # every golden already carries); "ledger" and the cost breakdowns
        # are new keys and therefore safely absent gate-off.  The ledger
        # summary is taken at the sim-end clock so open entries accrue to
        # exactly the instant the cost integral stopped — per-source
        # expected $·h sums match `cost.dollar_hours` to within the
        # launch-intent-vs-landing (ICE) divergence.
        from ..obs.ledger import LEDGER
        report["slo"]["budgets"] = harness.mgr.slo.summary()
        ledger_sum = LEDGER.summary(harness.clock.now())
        report["ledger"] = ledger_sum
        report["cost"]["by_nodepool"] = {
            k: v["realized_dh"]
            for k, v in ledger_sum["by_nodepool"].items()}
        report["cost"]["by_decision_source"] = {
            k: v["realized_dh"]
            for k, v in ledger_sum["by_decision_source"].items()}
    if getattr(harness, "_gang_enabled", False):
        # present ONLY when the GangScheduling gate ran — same conditional
        # contract as forecast/chaos/ha/incidents/slo, so every gate-off
        # report (all pre-existing goldens) stays byte-identical.  The
        # time-to-full percentiles come from the harness sampler (virtual
        # clock); admission/preemption counters from the provisioner's
        # gang registry, which the sim drives deterministically.
        fulls: List[float] = sorted(harness._gang_full_t.values())
        gang_sec: Dict = {
            "gangs_seen": len(harness._gang_arrive_t),
            "gangs_full": len(fulls),
            "time_to_full_gang_s": {
                "p50": _r(percentile(fulls, 0.50), 3),
                "p95": _r(percentile(fulls, 0.95), 3),
                "max": _r(fulls[-1], 3) if fulls else 0.0,
            },
        }
        prov = harness.mgr.controllers.get("provisioning")
        registry = getattr(prov, "gang_registry", None)
        if registry is not None:
            summary = registry.summary()
            gang_sec["admissions"] = sum(
                g["admissions"] for g in summary.values())
            gang_sec["rejections"] = sum(
                g["rejections"] for g in summary.values())
            gang_sec["preempted_pods"] = sum(
                g["preempted"] for g in summary.values())
            gang_sec["rejected_gangs_at_end"] = sorted(
                n for n, g in summary.items()
                if not g["admitted"] and g["rejections"])
        report["gang"] = gang_sec
    return report


def report_to_json(report: Dict) -> str:
    """Canonical serialization: sorted keys, two-space indent, trailing
    newline — the byte-identical artifact the determinism tests and golden
    files compare."""
    return json.dumps(report, sort_keys=True, indent=2) + "\n"
