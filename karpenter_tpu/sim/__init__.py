"""Deterministic virtual-clock cluster simulator.

Wires the *real* controller stack (provisioning, disruption, interruption,
lifecycle, termination, garbage collection) plus `FakeCloud` onto a shared
`VirtualClock` driven by an event heap, so days of cluster time replay in
seconds of wall time with zero sleeps.  The evaluation bed CvxCluster and
"Priority Matters" (PAPERS.md) use for allocation policies, grown here for
the karpenter-tpu stack.

Layout:
  * clock.py    — `VirtualClock` (the injectable clock callable) and the
                  deterministic `EventHeap`;
  * events.py   — typed simulation events (pod arrival/departure, spot
                  reclaim with its 2-minute warning, ICE windows, price
                  drift, node-ready latency, API throttle bursts);
  * scenario.py — declarative scenario spec (YAML or dataclass) expanded
                  deterministically from a seed;
  * harness.py  — the event loop: advance the clock to the next event,
                  deliver it, tick the controller stack, append to the
                  event log;
  * report.py   — the one-JSON-document run report (cost integral,
                  time-to-bind percentiles, churn, SLO/provenance rollups).

CLI: ``python -m karpenter_tpu.sim scenarios/diurnal.yaml --seed 0``.
See docs/simulation.md for the schema and report glossary.
"""

from .clock import EventHeap, VirtualClock
from .harness import SimHarness, SimRun
from .report import build_report, report_to_json
from .scenario import Scenario, ScenarioError, expand, load_scenario

__all__ = [
    "EventHeap", "VirtualClock", "SimHarness", "SimRun",
    "Scenario", "ScenarioError", "expand", "load_scenario",
    "build_report", "report_to_json",
]
