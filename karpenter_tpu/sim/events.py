"""Typed simulation events.

Events are inert data — `scenario.expand` produces them, the harness
delivers them.  Each carries a ``kind`` string (the event-log and metrics
label domain) and a ``to_log()`` projection kept deliberately small so the
append-only event log stays byte-stable across runs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from ..api.objects import Pod

# kind strings (event log + karpenter_sim_events_delivered_total label values)
POD_ARRIVAL = "pod_arrival"
POD_DEPARTURE = "pod_departure"
SPOT_RECLAIM = "spot_reclaim"
ICE_OPEN = "ice_open"
ICE_CLOSE = "ice_close"
PRICE_DRIFT = "price_drift"
NODE_READY_LATENCY = "node_ready_latency"
API_THROTTLE = "api_throttle"
NODE_READY = "node_ready"          # harness-internal (ready-latency lapse)


@dataclass
class SimEvent:
    kind = "event"

    def to_log(self) -> Dict:
        return {"kind": self.kind}


@dataclass
class PodArrival(SimEvent):
    """A cohort of pods hits the cluster (one wave bucket)."""
    pods: List[Pod]
    wave: str = ""
    kind = POD_ARRIVAL

    def to_log(self) -> Dict:
        return {"kind": self.kind, "wave": self.wave, "pods": len(self.pods)}


@dataclass
class PodDeparture(SimEvent):
    """A cohort completes / scales down: its pods leave the cluster."""
    uids: List[str]
    wave: str = ""
    kind = POD_DEPARTURE

    def to_log(self) -> Dict:
        return {"kind": self.kind, "wave": self.wave, "pods": len(self.uids)}


@dataclass
class SpotReclaim(SimEvent):
    """Reclaim `count` running spot instances: the 2-minute warning is
    published immediately, capacity is pulled `warning_s` later unless the
    controllers drained it first (the honor-rate input)."""
    count: int = 1
    warning_s: float = 120.0
    fault: str = ""
    kind = SPOT_RECLAIM

    def to_log(self) -> Dict:
        return {"kind": self.kind, "fault": self.fault, "count": self.count,
                "warning_s": self.warning_s}


@dataclass
class IceOpen(SimEvent):
    """Capacity pools start answering InsufficientInstanceCapacity.  Pool
    triples are (capacity_type, instance_type, zone); "*" wildcards resolve
    against the live catalog at delivery, deterministically."""
    pools: List[Tuple[str, str, str]]
    fault: str = ""
    kind = ICE_OPEN

    def to_log(self) -> Dict:
        return {"kind": self.kind, "fault": self.fault,
                "pools": len(self.pools)}


@dataclass
class IceClose(SimEvent):
    pools: List[Tuple[str, str, str]]
    fault: str = ""
    kind = ICE_CLOSE

    def to_log(self) -> Dict:
        return {"kind": self.kind, "fault": self.fault,
                "pools": len(self.pools)}


@dataclass
class PriceDrift(SimEvent):
    """Multiply every spot price by `factor`, each entry additionally
    jittered by up to ±`jitter` (resolved at delivery from the run seed)."""
    factor: float = 1.0
    jitter: float = 0.0
    fault: str = ""
    kind = PRICE_DRIFT

    def to_log(self) -> Dict:
        return {"kind": self.kind, "fault": self.fault,
                "factor": round(self.factor, 6),
                "jitter": round(self.jitter, 6)}


@dataclass
class NodeReadyLatency(SimEvent):
    """From now on, freshly launched nodes take `latency_s` of virtual time
    to become Ready (kubelet join + startup-taint clearance)."""
    latency_s: float = 0.0
    fault: str = ""
    kind = NODE_READY_LATENCY

    def to_log(self) -> Dict:
        return {"kind": self.kind, "fault": self.fault,
                "latency_s": self.latency_s}


@dataclass
class ApiThrottle(SimEvent):
    """Every cloud API call fails with RequestLimitExceeded for the next
    `duration_s` of virtual time (an API throttle burst)."""
    duration_s: float = 60.0
    fault: str = ""
    kind = API_THROTTLE

    def to_log(self) -> Dict:
        return {"kind": self.kind, "fault": self.fault,
                "duration_s": self.duration_s}


@dataclass
class NodeReady(SimEvent):
    """Harness-internal: a booting node's ready latency lapsed — clear its
    boot condition so the lifecycle controller can initialize it."""
    node: str = ""
    kind = NODE_READY

    def to_log(self) -> Dict:
        return {"kind": self.kind, "node": self.node}
