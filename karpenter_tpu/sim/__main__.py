"""CLI: replay a scenario through the virtual-clock simulator.

    python -m karpenter_tpu.sim scenarios/diurnal.yaml --seed 0

Prints the deterministic report JSON to stdout (or --out); the wall-clock
speedup line goes to stderr so piping stdout stays byte-stable across
runs.  --log writes the append-only event log as JSON lines.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from .harness import SimHarness
from .report import report_to_json
from .scenario import ScenarioError, load_scenario


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.sim",
        description="Deterministic virtual-clock cluster simulation")
    p.add_argument("scenario", help="scenario YAML file (see scenarios/)")
    p.add_argument("--seed", type=int, default=0,
                   help="expansion seed (default 0)")
    p.add_argument("--duration", type=float, default=None,
                   help="override scenario duration_s (virtual seconds)")
    p.add_argument("--out", default="",
                   help="write the report JSON here instead of stdout")
    p.add_argument("--log", default="",
                   help="write the event log (JSON lines) to this file")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="INFO-level controller logging")
    p.add_argument("--flight-recorder", action="store_true",
                   help="arm the incident flight recorder (FlightRecorder "
                        "gate): the report grows an `incidents` section")
    p.add_argument("--slo", action="store_true",
                   help="arm the SLO engine + cost ledger (SLOEngine "
                        "gate): the report grows `slo.budgets` and "
                        "`ledger` sections")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.ERROR,
        format="%(levelname)s %(name)s %(message)s", stream=sys.stderr)

    try:
        scenario = load_scenario(args.scenario)
    except ScenarioError as e:
        print(f"scenario error: {e}", file=sys.stderr)
        return 2
    harness = SimHarness(scenario, seed=args.seed,
                         duration_s=args.duration,
                         flight_recorder=True if args.flight_recorder
                         else None,
                         slo=True if args.slo else None)
    run = harness.run()

    doc = report_to_json(run.report)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(doc)
    else:
        sys.stdout.write(doc)
    if args.log:
        with open(args.log, "w") as fh:
            for entry in run.log:
                fh.write(json.dumps(entry, sort_keys=True) + "\n")
    print(f"replayed {run.virtual_seconds:.0f} virtual seconds "
          f"({run.events_delivered} events) in {run.wall_seconds:.2f}s wall "
          f"— {run.speedup:.0f}x real time", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
