"""The discrete-event simulation harness.

Wires the *real* controller stack — provisioning, disruption, interruption,
lifecycle, termination, GC, pricing — plus the fake cloud substrate onto a
shared `VirtualClock`, then replays a scenario's expanded event stream
against it.  Nothing in the loop sleeps: the harness advances the clock
straight to the next due moment (scenario event, scheduled cloud delivery,
controller cadence, or batch-window close), so a 24-hour diurnal day costs
seconds of wall time and two runs of the same (scenario, seed) produce
byte-identical event logs and reports.

Determinism notes (each bit matters):
  * module-global name counters (`state.cluster._names`, `api.objects._ids`,
    `cloud.queue._msg_ids`, `cloud.fake._fleet_ids`) are reset per run so
    node/message ids restart from 1 regardless of what ran earlier in the
    process;
  * the three request batchers keep their *wall* clock (their flusher
    threads would deadlock against a virtual clock nobody advances) but
    have their windows zeroed, so every call flushes immediately and
    batching adds no wall time and no ordering nondeterminism;
  * the harness's own randomness (reclaim victim selection, price jitter)
    comes from one `numpy` Generator keyed on the run seed, consumed in
    delivery order;
  * the report excludes every wall-clock-derived value — speedup goes to
    stderr/metrics/bench only.
"""

from __future__ import annotations

import itertools
import logging
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api.taints import Taint
from ..catalog.generate import generate_catalog
from ..cloud.fake import (FakeCloud, ImageInfo, SecurityGroupInfo,
                          SubnetInfo)
from ..cloud.queue import FakeQueue
from ..cloud.services import FakeParameterStore
from ..operator.manager import ControllerManager
from ..operator.operator import Operator, build_controllers
from ..operator.options import Options
from ..utils import metrics
from ..utils.chaos import CHAOS, ChaosRule
from . import events as ev
from .clock import EventHeap, VirtualClock
from .scenario import Scenario, expand

log = logging.getLogger("karpenter_tpu.sim")

# startup taint carried by booting nodes while their ready latency runs;
# node.kubernetes.io/ prefix so the lifecycle controller *waits* on it
# (it never clears condition-taints it does not own) until the harness's
# NodeReady event removes it
BOOT_TAINT = "node.kubernetes.io/sim-booting"

# bounded zero-advance: consecutive same-time passes allowed before the
# harness forces a minimum step (defends against due-time computation bugs
# ever turning into an infinite same-instant loop)
_MAX_ZERO_ADVANCES = 16
_FORCED_STEP_S = 0.5


@dataclass
class SimRun:
    """Everything one simulation produced.  `report` and `log` are fully
    deterministic; `wall_seconds`/`speedup` are measurements about the run
    and deliberately live outside the report document."""
    report: Dict
    log: List[Dict]
    virtual_seconds: float
    wall_seconds: float
    events_delivered: int

    @property
    def speedup(self) -> float:
        return self.virtual_seconds / self.wall_seconds \
            if self.wall_seconds > 0 else float("inf")


def _reset_global_counters() -> None:
    """Restart the module-global id/name counters so object names are a
    function of the run, not of process history."""
    from ..api import objects as api_objects
    from ..cloud import fake as cloud_fake
    from ..cloud import queue as cloud_queue
    from ..state import cluster as state_cluster
    api_objects._ids = itertools.count()
    state_cluster._names = itertools.count(1)
    cloud_queue._msg_ids = itertools.count(1)
    cloud_fake._fleet_ids = itertools.count(1)


class SimHarness:
    """One scenario replay over the real controller stack."""

    def __init__(self, scenario: Scenario, seed: int = 0,
                 duration_s: Optional[float] = None,
                 forecast: Optional[bool] = None,
                 incremental_arena: Optional[bool] = None,
                 sharded_solve: Optional[bool] = None,
                 warm_restart: Optional[bool] = None,
                 ingest_batch: Optional[bool] = None,
                 device_decode: Optional[bool] = None,
                 device_lp: Optional[bool] = None,
                 ha_failover: Optional[bool] = None,
                 flight_recorder: Optional[bool] = None,
                 slo: Optional[bool] = None,
                 gang: Optional[bool] = None):
        """`forecast` overrides the scenario's forecast.enabled so A/B
        comparisons (bench, the slow forecast test) can replay one scenario
        twice — knobs still come from the scenario's forecast block.
        `incremental_arena` likewise overrides the IncrementalArena gate
        (default on): False replays the exact pre-arena full-rebuild code
        paths, the golden byte-identity escape hatch.  `sharded_solve`
        overrides the ShardedSolve gate (default off): goldens are recorded
        with the gate off, so the default replay stays byte-identical.
        `warm_restart` / `ingest_batch` override the WarmRestart and
        IngestBatch gates (both default off) for the durability tests —
        goldens are recorded with both off.  `device_decode` overrides the
        DeviceDecode gate (default off): columnar slab decode with
        bit-identical plans, so gate-ON replays match the same goldens for
        scenarios whose batches clear the decode floor.  `device_lp`
        overrides the DeviceLP gate (default off): guide misses refine
        in-tick on the PDHG solver — mixes may legitimately differ from
        the HiGHS path's (first-order vs vertex optimum of the same LP),
        so gate-ON runs have their own golden; every existing golden is
        recorded with the gate off.  `ha_failover`
        overrides the HAFailover gate (default off): a virtual-clock
        LeaderElector is wired into the manager so lease expiry, fencing
        refusals, and `leader.lease` chaos replay deterministically —
        goldens for non-HA scenarios are recorded with the gate off.
        `flight_recorder` overrides the FlightRecorder gate (default
        off): the incident bus arms, the metric ring samples on the
        virtual clock, and the report grows a gated `incidents` section
        — every golden is recorded with the gate off.  `slo` overrides
        the SLOEngine gate, else the scenario's `slo.enabled` decides
        (default off): error budgets and the cost ledger run on the
        virtual clock and the report grows gated `slo.budgets`, `ledger`,
        and cost-breakdown sections — every golden is recorded with the
        gate off.  `gang` overrides the GangScheduling gate, else the
        scenario's `gang.enabled` decides (default off): all-or-nothing
        gang admission plus priority preemption run in the provisioner
        and the report grows a gated `gang` section — every golden is
        recorded with the gate off (time-to-full-gang is tracked either
        way, so A/B runs can read `_gang_full_t` on the naive side)."""
        if duration_s is not None:
            scenario = replace(scenario, duration_s=float(duration_s))
        scenario.validate()
        self.scenario = scenario
        self.seed = int(seed)
        _reset_global_counters()

        self.clock = VirtualClock(scenario.start_s)
        self.heap = EventHeap()
        for at, event in expand(scenario, self.seed):
            self.heap.push(at, event)
        self._total_events = len(self.heap)
        # harness-owned randomness (victim picks, price jitter): one stream,
        # consumed in delivery order — distinct from the expansion streams
        self._rng = np.random.default_rng([self.seed, 999])

        # -- substrate + operator over the virtual clock ------------------
        opts = Options(interruption_queue="sim-interruptions",
                       batch_idle_duration=scenario.batch_idle_s,
                       batch_max_duration=scenario.batch_max_s)
        if incremental_arena is not None:
            opts.feature_gates["IncrementalArena"] = bool(incremental_arena)
        if sharded_solve is not None:
            opts.feature_gates["ShardedSolve"] = bool(sharded_solve)
        if warm_restart is not None:
            opts.feature_gates["WarmRestart"] = bool(warm_restart)
        if ingest_batch is not None:
            opts.feature_gates["IngestBatch"] = bool(ingest_batch)
        if device_decode is not None:
            opts.feature_gates["DeviceDecode"] = bool(device_decode)
        if device_lp is not None:
            opts.feature_gates["DeviceLP"] = bool(device_lp)
        self._fr_enabled = bool(flight_recorder) \
            if flight_recorder is not None else False
        if self._fr_enabled:
            opts.feature_gates["FlightRecorder"] = True
        ss = scenario.slo
        self._slo_enabled = bool(slo) if slo is not None \
            else (ss is not None and ss.enabled)
        if self._slo_enabled:
            opts.feature_gates["SLOEngine"] = True
            if ss is not None:
                opts.slo_eval_cadence_s = ss.eval_cadence_s
                opts.ledger_drift_threshold = ss.drift_threshold
        gs = scenario.gang
        self._gang_enabled = bool(gang) if gang is not None \
            else (gs is not None and gs.enabled)
        if self._gang_enabled:
            opts.feature_gates["GangScheduling"] = True
        ha = scenario.ha
        self._ha_enabled = bool(ha_failover) if ha_failover is not None \
            else (ha is not None and ha.enabled)
        if self._ha_enabled:
            opts.feature_gates["HAFailover"] = True
            opts.leader_elect = True
        fc = scenario.forecast
        fc_on = forecast if forecast is not None \
            else (fc is not None and fc.enabled)
        if fc_on:
            opts.feature_gates["Forecast"] = True
            if fc is not None:
                opts.forecast_horizon_s = fc.horizon_s
                opts.forecast_lead_s = fc.lead_s
                opts.forecast_ttl_s = fc.ttl_s
                opts.forecast_bucket_s = fc.bucket_s
                opts.forecast_confidence = fc.confidence
                opts.forecast_max_cost_frac = fc.max_cost_frac
                opts.forecast_model = fc.model
                opts.forecast_season_s = fc.season_s
        queue = FakeQueue(clock=self.clock)
        cloud = FakeCloud(clock=self.clock, queue=queue)
        cloud.subnets = [SubnetInfo(f"s-{z}", z, 1_000_000, {})
                         for z in scenario.zones]
        cloud.security_groups = [SecurityGroupInfo("sg-sim", "nodes", {})]
        cloud.images = [ImageInfo("img-sim-1", "std", "amd64", 1.0)]
        params = FakeParameterStore()
        params.parameters = {
            "/karpenter-tpu/images/standard/1.28/amd64/latest": "img-sim-1"}
        catalog = generate_catalog(scenario.catalog_size,
                                   zones=scenario.zones)
        # seed the spot market so price-drift faults have a base to move
        for it in catalog:
            for o in it.offerings:
                if o.capacity_type == "spot":
                    cloud.spot_prices[(it.name, o.zone)] = o.price
        self.op = Operator(opts, cloud=cloud, catalog=catalog,
                           params=params, queue=queue, clock=self.clock)
        self.cloud = cloud
        self.cluster = self.op.cluster
        # batchers stay on the wall clock (their flusher threads would wait
        # forever on a clock only this thread advances) but with zero-width
        # windows every add() flushes immediately — no wall time, no
        # cross-call coalescing to perturb ordering
        for b in (self.op.batched_cloud.fleet, self.op.batched_cloud.describe,
                  self.op.batched_cloud.terminate):
            b.batcher.options.idle_timeout = 0.0
            b.batcher.options.max_timeout = 0.0

        controllers = build_controllers(self.op)
        # HAFailover: a real (virtual-clock) elector so the whole fencing
        # machinery — epoch bumps at lease expiry, mid-tick guards, chaos
        # at leader.lease — replays deterministically.  The lease lives in
        # a tempdir owned by the harness; its path never reaches the report.
        self.leader = None
        if self._ha_enabled:
            import os
            import tempfile
            from ..operator.manager import LeaderElector
            self._ha_dir = tempfile.TemporaryDirectory(
                prefix="karpenter-sim-ha-")
            self.leader = LeaderElector(
                os.path.join(self._ha_dir.name, "sim.lease"), "sim-leader",
                ttl=float(ha.ttl_s) if ha is not None else 15.0,
                clock=self.clock)
        self.mgr = ControllerManager(self.op, controllers, clock=self.clock,
                                     leader=self.leader)
        for entry in self.mgr._entries:
            entry.interval = scenario.intervals.get(entry.name,
                                                    entry.interval)
        self._terminator = controllers.get("termination")
        self._lifecycle = controllers.get("lifecycle")
        self._queue = queue

        # -- node-ready latency: intercept the sync register path ---------
        self._ready_latency = float(scenario.node_ready_latency_s)
        # booting node → pod uids bound there before it turned ready; their
        # time-to-bind clock stops at NodeReady, not at the API bind
        self._booting: Dict[str, List[str]] = {}
        self._wrap_register()
        self._wrap_bind()

        # -- run bookkeeping ----------------------------------------------
        self.log_entries: List[Dict] = []
        self._arrive_t: Dict[str, float] = {}      # pod uid → arrival time
        self._bind_t: Dict[str, float] = {}        # pod uid → time-to-bind
        # gang bookkeeping is tracked regardless of the gate so an A/B
        # run can read time-to-full-gang on the naive (gate-off) side;
        # only the report section is gated on _gang_enabled
        self._gang_of: Dict[str, str] = {}         # pod uid → gang name
        self._gang_members: Dict[str, set] = {}    # gang → member uids
        self._gang_arrive_t: Dict[str, float] = {}  # gang → first arrival
        self._gang_full_t: Dict[str, float] = {}   # gang → time to all-bound
        self._departed_unbound = 0
        self._cost_dollar_hours = 0.0
        self._node_hours = 0.0
        self._peak_nodes = 0
        self._events_by_kind: Dict[str, int] = {}
        self._disruptions: Dict[str, int] = {}     # "kind/reason" → count
        self._interruption_recycled = 0
        self._liveness_terminated = 0
        self._warnings = 0
        self._reclaims_honored = 0
        self._reclaims_forced = 0
        self._tick_exceptions = 0
        # provisioning faults are absorbed by its supervisor now, not
        # re-raised through tick(); the report's tick_exceptions counter
        # tracks the supervisor's failure total instead (same semantics)
        self._prov_failures_seen = 0
        ch = scenario.chaos
        self._chaos_enabled = bool(ch is not None and ch.enabled and ch.rules)

    # ------------------------------------------------------------------
    def _wrap_register(self) -> None:
        """Model node-ready latency without touching the provisioner: the
        sync path registers the node uninitialized and booting (a
        node.kubernetes.io/* taint the lifecycle controller waits on);
        a scheduled NodeReady event lifts the taint and the real
        LifecycleController performs initialization on its next pass."""
        original = self.cluster.register_nodeclaim
        harness = self

        def register(claim, allocatable, capacity=None, initialized=True,
                     rehydrate=False):
            if rehydrate or harness._ready_latency <= 0:
                return original(claim, allocatable, capacity,
                                initialized=initialized, rehydrate=rehydrate)
            node = original(claim, allocatable, capacity,
                            initialized=False, rehydrate=rehydrate)
            node.taints = list(node.taints) + [Taint(BOOT_TAINT)]
            harness.cluster.touch_node(node)
            harness._booting[node.name] = []
            harness.heap.push(harness.clock.now() + harness._ready_latency,
                              ev.NodeReady(node=node.name))
            return node

        self.cluster.register_nodeclaim = register

    def _wrap_bind(self) -> None:
        """Record each pod's first bind so the report's time-to-bind
        percentiles come straight from harness state."""
        original = self.cluster.bind_pod
        harness = self

        def bind(pod, node_name):
            if pod.uid not in harness._bind_t and \
                    pod.uid in harness._arrive_t:
                if node_name in harness._booting:
                    # node is still booting: the pod is placed but cannot
                    # run — its bind clock stops at the NodeReady event
                    harness._booting[node_name].append(pod.uid)
                else:
                    harness._bind_t[pod.uid] = \
                        harness.clock.now() - harness._arrive_t[pod.uid]
            original(pod, node_name)

        self.cluster.bind_pod = bind

    # ------------------------------------------------------------------
    # event delivery
    # ------------------------------------------------------------------
    def _log(self, at: float, payload: Dict) -> None:
        self.log_entries.append({"t": round(at - self.scenario.start_s, 6),
                                 **payload})

    def _deliver(self, at: float, event) -> None:
        self._events_by_kind[event.kind] = \
            self._events_by_kind.get(event.kind, 0) + 1
        metrics.sim_events_delivered().inc({"kind": event.kind})
        self._log(at, event.to_log())
        if isinstance(event, ev.PodArrival):
            now = self.clock.now()
            for p in event.pods:
                self._arrive_t[p.uid] = now
                if p.gang_name:
                    self._gang_of[p.uid] = p.gang_name
                    self._gang_members.setdefault(
                        p.gang_name, set()).add(p.uid)
                    self._gang_arrive_t.setdefault(p.gang_name, now)
            self.cluster.add_pods(event.pods)
        elif isinstance(event, ev.PodDeparture):
            for uid in event.uids:
                pod = self.cluster.pods.get(uid)
                if pod is None:
                    continue
                if uid not in self._bind_t:
                    self._departed_unbound += 1
                g = self._gang_of.pop(uid, None)
                if g is not None:
                    # departed members shrink the tracked set: a gang
                    # whose remainder is all bound still counts as full
                    members = self._gang_members.get(g)
                    if members is not None:
                        members.discard(uid)
                        if not members:
                            self._gang_members.pop(g, None)
                self.cluster.delete_pod(pod)
                self.op.provenance.clear(pod.name)
        elif isinstance(event, ev.SpotReclaim):
            self._start_reclaims(event)
        elif isinstance(event, ev.IceOpen):
            self.cloud.insufficient_capacity_pools |= \
                self._resolve_pools(event.pools)
        elif isinstance(event, ev.IceClose):
            self.cloud.insufficient_capacity_pools -= \
                self._resolve_pools(event.pools)
        elif isinstance(event, ev.PriceDrift):
            self._drift_prices(event)
        elif isinstance(event, ev.ApiThrottle):
            self.cloud.throttle_until = max(
                self.cloud.throttle_until,
                self.clock.now() + event.duration_s)
        elif isinstance(event, ev.NodeReadyLatency):
            self._ready_latency = float(event.latency_s)
        elif isinstance(event, ev.NodeReady):
            node = self.cluster.nodes.get(event.node)
            if node is not None:
                node.taints = [t for t in node.taints
                               if t.key != BOOT_TAINT]
                self.cluster.touch_node(node)
            now = self.clock.now()
            for uid in self._booting.pop(event.node, []):
                if uid not in self._bind_t and uid in self._arrive_t:
                    self._bind_t[uid] = now - self._arrive_t[uid]

    def _start_reclaims(self, event: ev.SpotReclaim) -> None:
        """Pick victims among running spot capacity and schedule the
        warn-then-reclaim pipeline on the cloud."""
        with self.cloud._lock:
            candidates = sorted(
                iid for iid, inst in self.cloud._instances.items()
                if inst.state == "running" and inst.capacity_type == "spot")
        n = min(event.count, len(candidates))
        if n == 0:
            return
        picks = sorted(self._rng.choice(len(candidates), size=n,
                                        replace=False).tolist())
        now = self.clock.now()
        for i in picks:
            self.cloud.interrupt(candidates[i], at=now + event.warning_s,
                                 warning_s=event.warning_s)

    def _resolve_pools(self, pools) -> set:
        """Expand "*" wildcards against the catalog/zones, deterministically
        (sorted iteration)."""
        cap_types = ("on-demand", "spot")
        type_names = sorted(it.name for it in self.op.catalog)
        zones = sorted(self.scenario.zones)
        out = set()
        for ct, itype, zone in pools:
            for c in (cap_types if ct == "*" else (ct,)):
                for t in (type_names if itype == "*" else (itype,)):
                    for z in (zones if zone == "*" else (zone,)):
                        out.add((c, t, z))
        return out

    def _drift_prices(self, event: ev.PriceDrift) -> None:
        for key in sorted(self.cloud.spot_prices):
            jitter = 1.0
            if event.jitter > 0:
                jitter = 1.0 + event.jitter * float(
                    self._rng.uniform(-1.0, 1.0))
            self.cloud.spot_prices[key] = round(
                self.cloud.spot_prices[key] * event.factor * jitter, 6)

    def _on_cloud_delivery(self, rec: Dict) -> None:
        if rec["action"] == "spot_warning":
            self._warnings += 1
            metrics.sim_reclaim_warnings().inc()
            self._log(rec["at"], {"kind": "spot_warning",
                                  "instance": rec["instance"]})
        else:
            honored = bool(rec.get("honored"))
            if honored:
                self._reclaims_honored += 1
            else:
                self._reclaims_forced += 1
                # a forced reclaim killed the instance without passing
                # through the provider's delete funnel — close its ledger
                # entry here or its realized $·h would accrue forever
                from ..obs.ledger import LEDGER
                if LEDGER.enabled:
                    LEDGER.record_close(rec["instance"], at=rec["at"],
                                        reason="spot_reclaim")
            metrics.sim_reclaims().inc(
                {"honored": "true" if honored else "false"})
            self._log(rec["at"], {"kind": "spot_reclaim_fired",
                                  "instance": rec["instance"],
                                  "honored": honored})

    # ------------------------------------------------------------------
    def _check_gangs(self) -> None:
        """Sample gang completeness after each tick: the moment every
        member of a gang is simultaneously bound on ready (non-booting)
        nodes, record its time-to-full.  Sampling the cluster beats
        wrapping every (un)bind path — preemption, reclaim recycling,
        and consolidation all move pods, and a sample can't miss a
        transition that persists to the next tick."""
        if not self._gang_members:
            return
        now = self.clock.now()
        for g in sorted(self._gang_members):
            if g in self._gang_full_t:
                continue
            members = self._gang_members[g]
            full = True
            for uid in members:
                pod = self.cluster.pods.get(uid)
                if pod is None or not pod.node_name or \
                        pod.node_name in self._booting:
                    full = False
                    break
            if full and members:
                self._gang_full_t[g] = now - self._gang_arrive_t[g]

    # ------------------------------------------------------------------
    # controller ticking + due-time computation
    # ------------------------------------------------------------------
    def _tick(self) -> None:
        try:
            results = self.mgr.tick()
        except Exception as e:
            # the supervisors absorb controller faults, so anything that
            # reaches here is a harness/manager bug — still cost one tick,
            # not the run, and not a traceback per retry
            self._tick_exceptions += 1
            log.warning("sim tick failed at t=%.1f: %s",
                        self.clock.now(), e)
            return
        # provisioning faults (e.g. an injected throttle burst) used to
        # propagate out of tick(); its supervisor now catches them, so the
        # report counter follows the supervisor's running failure total
        prov_sup = self.mgr.supervisors.get("provisioning")
        if prov_sup is not None and \
                prov_sup.total_failures > self._prov_failures_seen:
            self._tick_exceptions += \
                prov_sup.total_failures - self._prov_failures_seen
            self._prov_failures_seen = prov_sup.total_failures
        disruption = results.get("disruption")
        if disruption is not None and disruption.action is not None:
            name = disruption.action.name
            self._disruptions[name] = self._disruptions.get(name, 0) + 1
        interruption = results.get("interruption")
        if interruption is not None:
            self._interruption_recycled += len(interruption.recycled)
        lifecycle = results.get("lifecycle")
        if lifecycle is not None:
            self._liveness_terminated += len(lifecycle.liveness_terminated)

    def _controller_due(self, now: float) -> float:
        """Earliest moment any controller has work: entry cadences (skipping
        no-op-prone loops with provably nothing to do) plus the pod batch
        window's close."""
        due = float("inf")
        queue_busy = len(self._queue) > 0 or bool(self._queue._inflight)
        termination_busy = bool(self._terminator and
                                self._terminator.pending)
        lifecycle_busy = bool(
            getattr(self._lifecycle, "_pending", None) or
            any(not c.initialized
                for c in self.cluster.nodeclaims.values()))
        for entry in self.mgr._entries:
            if entry.name == "interruption" and not queue_busy:
                continue
            if entry.name == "termination" and not termination_busy:
                continue
            if entry.name == "lifecycle" and not lifecycle_busy:
                continue
            edue = entry.last_run + entry.interval
            sup = self.mgr.supervisors.get(entry.name)
            if sup is not None:
                # a crash-looping controller's backoff window is jumped,
                # not crawled through the zero-advance guard
                edue = max(edue, sup.next_allowed())
            due = min(due, edue)
        window = self.mgr.batch_window
        if self.cluster.pending_pods():
            if window._opened is None:
                wdue = now              # next tick opens the window
            else:
                wdue = min(window._last_add + window.idle,
                           window._opened + window.max_timeout)
            # while a throttle burst has the cloud refusing every call —
            # or the provisioning supervisor is backing a crash loop off —
            # re-solving just burns ticks: hold the launch path to the
            # latest of the window close, the throttle end, and the
            # supervisor's retry time, like a live controller's retry
            prov_sup = self.mgr.supervisors.get("provisioning")
            prov_at = prov_sup.next_allowed() if prov_sup else float("-inf")
            due = min(due, max(wdue, self.cloud.throttle_until, prov_at))
        return due

    # ------------------------------------------------------------------
    def run(self) -> SimRun:
        try:
            return self._run_gated()
        finally:
            # the incident bus is process-global: it must not stay armed
            # past this run, or the next harness/test would publish into
            # a recorder whose clock and ring are gone
            if self._fr_enabled and self.mgr.flight is not None:
                self.mgr.flight.disarm()
            # likewise the cost ledger — but only after build_report read
            # its summary (report building happens inside the try)
            if self._slo_enabled:
                from ..obs.ledger import LEDGER
                LEDGER.disarm()

    def _run_gated(self) -> SimRun:
        if not self._chaos_enabled:
            return self._run_loop()
        ch = self.scenario.chaos
        sc = self.scenario
        # rebase scenario-relative rule times onto the virtual clock; the
        # no-op sleep keeps latency/hang rules from burning wall time (a
        # hang is only meaningful under a watchdog deadline, which uses
        # its own wall-clock wait)
        rules = [ChaosRule(point=r.point, key=r.key, action=r.action,
                           rate=r.rate, at_s=sc.start_s + r.at_s,
                           until_s=(sc.start_s + r.until_s) if r.until_s
                           else float("inf"),
                           latency_s=r.latency_s, count=r.count,
                           error_code=r.error_code)
                 for r in ch.rules]
        CHAOS.configure(rules,
                        seed=self.seed if ch.seed is None else int(ch.seed),
                        clock=self.clock, sleep=lambda s: None)
        try:
            # the report reads the injector's counters before this returns
            return self._run_loop()
        finally:
            CHAOS.reset()

    def _run_loop(self) -> SimRun:
        sc = self.scenario
        t_end = sc.start_s + sc.duration_s + sc.settle_s
        wall0 = time.perf_counter()
        zero_advances = 0
        while True:
            now = self.clock.now()
            for at, event in self.heap.pop_due(now):
                self._deliver(at, event)
            for rec in self.cloud.deliver_due():
                self._on_cloud_delivery(rec)
            self._tick()
            self._check_gangs()
            self._peak_nodes = max(self._peak_nodes,
                                   len(self.cluster.nodes))
            if now >= t_end:
                break
            target = min(t_end, self._next_due(now))
            if target <= now:
                zero_advances += 1
                if zero_advances < _MAX_ZERO_ADVANCES:
                    continue
                target = now + _FORCED_STEP_S   # progress guard
            zero_advances = 0
            self._accrue(now, target)
            self.clock.advance_to(target)
        wall = time.perf_counter() - wall0
        virtual = self.clock.now() - sc.start_s
        speedup = virtual / wall if wall > 0 else float("inf")
        metrics.sim_virtual_time_speedup().set(speedup)
        total_reclaims = self._reclaims_honored + self._reclaims_forced
        if total_reclaims:
            metrics.sim_reclaim_honor_rate().set(
                self._reclaims_honored / total_reclaims)
        from .report import build_report
        return SimRun(report=build_report(self), log=self.log_entries,
                      virtual_seconds=virtual, wall_seconds=wall,
                      events_delivered=sum(self._events_by_kind.values()))

    def _next_due(self, now: float) -> float:
        due = self._controller_due(now)
        head = self.heap.peek_time()
        if head is not None:
            due = min(due, head)
        cloud_due = self.cloud.next_due()
        if cloud_due is not None:
            due = min(due, cloud_due)
        return due

    def _accrue(self, t0: float, t1: float) -> None:
        dt_h = (t1 - t0) / 3600.0
        with self.cloud._lock:
            rate = sum(inst.price for inst in self.cloud._instances.values()
                       if inst.state == "running")
            n = sum(1 for inst in self.cloud._instances.values()
                    if inst.state == "running")
        self._cost_dollar_hours += rate * dt_h
        self._node_hours += n * dt_h
