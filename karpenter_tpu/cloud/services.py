"""Auxiliary fake services backing the L2 providers.

Analogs of the reference's non-EC2 fakes
(/root/reference/pkg/fake/{iamapi,ssmapi,pricingapi,eksapi}.go): an identity
service for instance profiles, a parameter store for image resolution, an
on-demand price list, and a control-plane version endpoint.  Each counts
calls and supports one-shot error injection like FakeCloud.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .fake import CloudError


class _Service:
    def __init__(self):
        self._lock = threading.RLock()
        self.calls: Dict[str, int] = {}
        self.next_error: Optional[Exception] = None

    def _count(self, api: str):
        self.calls[api] = self.calls.get(api, 0) + 1

    def _maybe_raise(self):
        if self.next_error is not None:
            err, self.next_error = self.next_error, None
            raise err

    def reset(self):
        with self._lock:
            self.calls.clear()
            self.next_error = None


class FakeIAM(_Service):
    """Instance-profile store (/root/reference/pkg/fake/iamapi.go)."""

    def __init__(self):
        super().__init__()
        self.profiles: Dict[str, Dict[str, str]] = {}  # name → {role, ...tags}

    def create_instance_profile(self, name: str, tags: Dict[str, str]) -> None:
        with self._lock:
            self._count("create_instance_profile")
            self._maybe_raise()
            if name in self.profiles:
                raise CloudError("EntityAlreadyExists", name)
            self.profiles[name] = {"_roles": "", **(tags or {})}

    def get_instance_profile(self, name: str) -> Dict[str, str]:
        with self._lock:
            self._count("get_instance_profile")
            self._maybe_raise()
            if name not in self.profiles:
                raise CloudError("NoSuchEntity", name)
            return dict(self.profiles[name])

    def add_role_to_instance_profile(self, name: str, role: str) -> None:
        with self._lock:
            self._count("add_role_to_instance_profile")
            self._maybe_raise()
            if name not in self.profiles:
                raise CloudError("NoSuchEntity", name)
            if self.profiles[name]["_roles"]:
                raise CloudError("LimitExceeded", "profile already has a role")
            self.profiles[name]["_roles"] = role

    def remove_role_from_instance_profile(self, name: str, role: str) -> None:
        with self._lock:
            self._count("remove_role_from_instance_profile")
            self._maybe_raise()
            if name in self.profiles:
                self.profiles[name]["_roles"] = ""

    def delete_instance_profile(self, name: str) -> None:
        with self._lock:
            self._count("delete_instance_profile")
            self._maybe_raise()
            if name not in self.profiles:
                raise CloudError("NoSuchEntity", name)
            del self.profiles[name]


class FakeParameterStore(_Service):
    """Published-image parameter store — the SSM analog the image resolver
    queries (/root/reference/pkg/fake/ssmapi.go)."""

    def __init__(self):
        super().__init__()
        self.parameters: Dict[str, str] = {}

    def get_parameter(self, name: str) -> str:
        with self._lock:
            self._count("get_parameter")
            self._maybe_raise()
            if name not in self.parameters:
                raise CloudError("ParameterNotFound", name)
            return self.parameters[name]


class FakePricingAPI(_Service):
    """On-demand price list (/root/reference/pkg/fake/pricingapi.go)."""

    def __init__(self):
        super().__init__()
        self.on_demand: Dict[str, float] = {}  # instance type → $/h

    def list_prices(self) -> Dict[str, float]:
        with self._lock:
            self._count("list_prices")
            self._maybe_raise()
            return dict(self.on_demand)


class FakeControlPlane(_Service):
    """Cluster control-plane endpoint (/root/reference/pkg/fake/eksapi.go +
    the kube version the version provider caches)."""

    def __init__(self, version: str = "1.28", endpoint: str = "https://cluster.local",
                 kube_dns_ip: str = "10.100.0.10"):
        super().__init__()
        self.version = version
        self.endpoint = endpoint
        # the kube-dns service address: IPv4 by default; an IPv6 (single-
        # stack) cluster publishes an IPv6 service IP here (the reference
        # discovers it from the kube-dns Service, operator.go:248-261)
        self.kube_dns_ip = kube_dns_ip

    def server_version(self) -> str:
        with self._lock:
            self._count("server_version")
            self._maybe_raise()
            return self.version

    def describe_cluster(self) -> Dict[str, str]:
        with self._lock:
            self._count("describe_cluster")
            self._maybe_raise()
            return {"endpoint": self.endpoint, "version": self.version}

    def kube_dns(self) -> str:
        with self._lock:
            self._count("kube_dns")
            self._maybe_raise()
            return self.kube_dns_ip
