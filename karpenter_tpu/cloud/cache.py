"""TTL caches and the unavailable-offerings (ICE) cache.

Re-implements the throughput substrate at
/root/reference/pkg/cache/unavailableofferings.go:31-81 and
/root/reference/pkg/cache/cache.go: a TTL cache keyed
`capacityType:instanceType:zone` of recently capacity-exhausted offerings,
with an atomic sequence number so downstream memoization (the instance-type
catalog hash, /root/reference/pkg/providers/instancetype/instancetype.go:114-121)
invalidates when availability changes."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from ..analysis.lockorder import named_lock

UNAVAILABLE_OFFERINGS_TTL = 3 * 60.0  # seconds (reference: 3m, pkg/cache/cache.go)


class TTLCache:
    """Minimal expiring map (patrickmn/go-cache analog)."""

    def __init__(self, default_ttl: float, clock: Callable[[], float] = time.time):
        self.default_ttl = default_ttl
        self.clock = clock
        self._lock = named_lock("ttlcache")
        self._data: Dict[Any, Tuple[float, Any]] = {}  # guarded-by: _lock

    def set(self, key, value, ttl: Optional[float] = None):
        expires = self.clock() + (self.default_ttl if ttl is None else ttl)
        with self._lock:
            self._data[key] = (expires, value)

    def get(self, key, default=None):
        now = self.clock()
        with self._lock:
            item = self._data.get(key)
            if item is None:
                return default
            expires, value = item
            if expires < now:
                # leave removal to purge_expired() so eviction is observable
                # (seq-num bump) even when nobody re-reads this key
                return default
            return value

    def __contains__(self, key):
        return self.get(key, _SENTINEL) is not _SENTINEL

    def delete(self, key):
        with self._lock:
            self._data.pop(key, None)

    def flush(self):
        with self._lock:
            self._data.clear()

    def purge_expired(self) -> int:
        """Drop expired entries; returns how many were dropped (the OnEvicted
        analog callers use to invalidate downstream memoization)."""
        now = self.clock()
        with self._lock:
            dead = [k for k, (exp, _) in self._data.items() if exp < now]
            for k in dead:
                del self._data[k]
            return len(dead)

    def items(self):
        now = self.clock()
        with self._lock:
            return [(k, v) for k, (exp, v) in self._data.items() if exp >= now]

    def __len__(self):
        return len(self.items())


_SENTINEL = object()


class UnavailableOfferings:
    """ICE-driven offering blacklist
    (/root/reference/pkg/cache/unavailableofferings.go:31-81)."""

    def __init__(self, ttl: float = UNAVAILABLE_OFFERINGS_TTL,
                 clock: Callable[[], float] = time.time):
        self._cache = TTLCache(ttl, clock)
        self._lock = named_lock("unavailable.seq")
        self._seq = 0                           # guarded-by: _lock

    @staticmethod
    def key(capacity_type: str, instance_type: str, zone: str) -> str:
        return f"{capacity_type}:{instance_type}:{zone}"

    @property
    def seq_num(self) -> int:
        """Monotone availability version. TTL expiry counts as a change —
        the reference bumps its seq from the cache's OnEvicted hook
        (/root/reference/pkg/cache/unavailableofferings.go:37-43) so the
        memoized catalog re-admits recovered offerings."""
        expired = self._cache.purge_expired()
        if expired:
            with self._lock:
                self._seq += expired
        return self._seq

    def is_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> bool:
        return self.key(capacity_type, instance_type, zone) in self._cache

    def mark_unavailable(self, reason: str, instance_type: str, zone: str,
                         capacity_type: str) -> None:
        with self._lock:
            self._seq += 1
        self._cache.set(self.key(capacity_type, instance_type, zone), reason)

    def mark_unavailable_for_fleet_err(self, err_code: str, instance_type: str,
                                       zone: str, capacity_type: str) -> None:
        self.mark_unavailable(f"fleet:{err_code}", instance_type, zone, capacity_type)

    def delete(self, instance_type: str, zone: str, capacity_type: str) -> None:
        with self._lock:
            self._seq += 1
        self._cache.delete(self.key(capacity_type, instance_type, zone))

    def flush(self):
        with self._lock:
            self._seq += 1
        self._cache.flush()

    # ---- warm restart (state/snapshot.py) ---------------------------------
    def snapshot_state(self) -> Dict:
        """Round-trippable export: raw entries with absolute expiry stamps
        plus the sequence number.  Entries whose TTL lapsed while the
        operator was down simply read as expired after restore — the
        purge-on-read path counts them as availability changes as usual."""
        with self._cache._lock:
            data = dict(self._cache._data)
        with self._lock:
            seq = self._seq
        return {"entries": data, "seq": seq}

    def restore_state(self, data: Dict) -> None:
        with self._cache._lock:
            self._cache._data.clear()
            self._cache._data.update(data["entries"])
        with self._lock:
            self._seq = int(data["seq"])
