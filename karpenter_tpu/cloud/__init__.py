from .cache import TTLCache, UnavailableOfferings, UNAVAILABLE_OFFERINGS_TTL
from .fake import (CloudError, CloudInstance, FakeCloud, FleetError,
                   FleetOverride, FleetResult, ICE_CODE)
from .provider import (CloudProvider, InstanceTypesProvider,
                       InsufficientCapacityError, MAX_INSTANCE_TYPES,
                       MIN_SPOT_FLEXIBILITY)
