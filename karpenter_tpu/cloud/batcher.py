"""L1 throughput substrate: the windowed request batcher.

Re-expresses the reference's generic batcher
(/root/reference/pkg/batcher/batcher.go:52-197): callers `add()` requests
which are hashed into buckets; a bucket's window closes when the stream goes
idle for `idle_timeout`, when `max_timeout` elapses since the first request,
or when `max_items` accumulate; then one `batch_executor` call fans results
back to every caller.

Three concrete batchers mirror the reference's instances:
  * CreateFleetBatcher     — 35ms idle / 1s max / ≤1000; merges N
    single-capacity requests into one fleet call and splits the launched
    instance ids back one per caller
    (/root/reference/pkg/batcher/createfleet.go:33-90).
  * DescribeInstancesBatcher — 100ms idle / 1s max / ≤500; unions id sets,
    fans each caller its own instances
    (/root/reference/pkg/batcher/describeinstances.go:39-41).
  * TerminateInstancesBatcher — same window; unions ids
    (/root/reference/pkg/batcher/terminateinstances.go:38-40).

Unlike the Go original (goroutines + channels) this is a thread-per-bucket
design with condition variables; `add()` blocks the calling thread until its
result is fanned back, which matches how the synchronous controllers here
consume it.  A process-wide default can be swapped for the C++ native core
(karpenter_tpu/native) transparently.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generic, Hashable, List, Optional, Sequence, Tuple, TypeVar

Req = TypeVar("Req")
Res = TypeVar("Res")

# Window constants (reference createfleet.go:36-39, describeinstances.go:39-41).
CREATE_FLEET_IDLE = 0.035
CREATE_FLEET_MAX = 1.0
CREATE_FLEET_MAX_ITEMS = 1000
DESCRIBE_IDLE = 0.100
DESCRIBE_MAX = 1.0
DESCRIBE_MAX_ITEMS = 500
TERMINATE_IDLE = 0.100
TERMINATE_MAX = 1.0
TERMINATE_MAX_ITEMS = 500


@dataclass
class BatchStats:
    """Per-batcher observability (batch_size / window_duration histograms,
    /root/reference/pkg/batcher/metrics.go:40-47).  Bounded: only the most
    recent windows are retained (full distributions live in the metrics
    histograms)."""
    batches: int = 0
    requests: int = 0
    sizes: "deque" = field(default_factory=lambda: deque(maxlen=1024))
    window_durations: "deque" = field(default_factory=lambda: deque(maxlen=1024))


@dataclass
class Options:
    """Batching window policy (batcher.go Options)."""
    name: str
    idle_timeout: float
    max_timeout: float
    max_items: int
    request_hasher: Callable[[Any], Hashable]
    batch_executor: Callable[[Sequence[Any]], Sequence[Any]]


class _Bucket:
    """One in-flight window of same-hash requests."""

    def __init__(self):
        self.requests: List[Any] = []
        self.results: Optional[List[Any]] = None
        self.error: Optional[BaseException] = None
        self.opened: float = 0.0
        self.last_add: float = 0.0
        self.closed = False
        self.closed_event = threading.Event()  # wakes the flusher on early close
        self.done = threading.Condition()


class Batcher(Generic[Req, Res]):
    """Generic windowed batcher (batcher.go:52-197)."""

    def __init__(self, options: Options, clock: Callable[[], float] = time.monotonic):
        from ..analysis.lockorder import named_lock
        self.options = options
        self.clock = clock
        self._lock = named_lock("batcher")
        self.stats = BatchStats()               # guarded-by: _lock
        self._open: Dict[Hashable, _Bucket] = {}  # guarded-by: _lock

    def add(self, request: Req) -> Res:
        """Join the open window for this request's hash (opening one and its
        flusher thread if needed) and block until the executor fans the
        result back (batcher.go Add:99 + waitForIdle:161)."""
        key = self.options.request_hasher(request)
        with self._lock:
            bucket = self._open.get(key)
            if bucket is None or bucket.closed:
                bucket = _Bucket()
                bucket.opened = self.clock()
                self._open[key] = bucket
                threading.Thread(target=self._flusher, args=(key, bucket),
                                 daemon=True).start()
            idx = len(bucket.requests)
            bucket.requests.append(request)
            bucket.last_add = self.clock()
            if len(bucket.requests) >= self.options.max_items:
                self._close(key, bucket)
        with bucket.done:
            while bucket.results is None and bucket.error is None:
                bucket.done.wait()
        if bucket.error is not None:
            raise bucket.error
        return bucket.results[idx]

    def _close(self, key: Hashable, bucket: _Bucket) -> None:  # graftlint: holds(_lock)
        if not bucket.closed:
            bucket.closed = True
            bucket.closed_event.set()
            if self._open.get(key) is bucket:
                del self._open[key]

    def _flusher(self, key: Hashable, bucket: _Bucket) -> None:
        """Window clock: wake at the earlier of idle/max deadline, then run
        the batch (batcher.go waitForIdle:161-182 + runCalls:184).

        The computed wait is in CLOCK seconds, which for an injected
        fake/test clock bears no relation to real time — so the sleep is
        capped at a 50ms real-time slice and the deadline re-checked against
        the clock on every wake.  Real-clock windows here are 35ms-1s, so
        the cap costs at most ~20 wakeups/s per open bucket (buckets live
        one window) while bounding any injected clock's deadline latency to
        one slice; no clock-kind heuristic that a fake clock's step pattern
        could defeat.  Early close on max_items is signaled via
        closed_event."""
        while True:
            with self._lock:
                if bucket.closed:
                    break
                now = self.clock()
                idle_deadline = bucket.last_add + self.options.idle_timeout
                max_deadline = bucket.opened + self.options.max_timeout
                deadline = min(idle_deadline, max_deadline)
                if now >= deadline:
                    self._close(key, bucket)
                    break
                wait = deadline - now
            bucket.closed_event.wait(timeout=min(wait, 0.05))
        self._run(bucket)

    def _run(self, bucket: _Bucket) -> None:
        # flusher threads are their own trace roots: a flush belongs to the
        # window, not to any single caller's tick
        from ..utils import tracing
        with tracing.span("batcher.flush", batcher=self.options.name,
                          size=len(bucket.requests)) as sp:
            try:
                results = list(self.options.batch_executor(list(bucket.requests)))
                if len(results) != len(bucket.requests):
                    raise RuntimeError(
                        f"batcher {self.options.name}: executor returned "
                        f"{len(results)} results for {len(bucket.requests)} requests")
                error = None
            except BaseException as e:  # fan the failure back to every caller
                results, error = None, e
            window = self.clock() - bucket.opened
            sp.annotate(window_s=round(window, 4), error=bool(error))
        # shared stats guarded by the batcher lock, not the per-bucket one —
        # concurrent buckets flush in parallel
        with self._lock:
            self.stats.batches += 1
            self.stats.requests += len(bucket.requests)
            self.stats.sizes.append(len(bucket.requests))
            self.stats.window_durations.append(window)
        with bucket.done:
            bucket.results = results
            bucket.error = error
            bucket.done.notify_all()
        # batch_size / batch_time histograms (reference pkg/batcher/metrics.go:40-47)
        from ..utils import metrics
        labels = {"batcher": self.options.name}
        metrics.batch_size().observe(len(bucket.requests), labels)
        metrics.batch_window_duration().observe(window, labels)


# ---------------------------------------------------------------------------
# Concrete batchers over the cloud substrate
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FleetRequest:
    """One caller's single-capacity fleet ask; hashed on its launch shape so
    identical asks merge (createfleet.go FleetRequestHasher)."""
    overrides: Tuple  # Tuple[FleetOverride, ...]
    tags: Tuple[Tuple[str, str], ...]

    def shape(self) -> Hashable:
        # every field that affects what gets launched must hash (the
        # reference hashes the full fleet input, batcher DefaultHasher)
        return (tuple((ov.instance_type, ov.zone, ov.capacity_type, ov.price,
                       ov.subnet_id, ov.launch_template, ov.image_id)
                      for ov in self.overrides), self.tags)


class CreateFleetBatcher:
    """Merges same-shape single-instance fleet requests into one
    `create_fleet(count=N)` and deals the launched instances back one per
    caller; callers beyond the fulfilled count get the fleet errors
    (createfleet.go:52-90)."""

    def __init__(self, cloud, clock: Callable[[], float] = time.monotonic,
                 idle: float = CREATE_FLEET_IDLE, max_timeout: float = CREATE_FLEET_MAX,
                 max_items: int = CREATE_FLEET_MAX_ITEMS):
        self.cloud = cloud
        self.batcher: Batcher = Batcher(Options(
            name="create_fleet", idle_timeout=idle, max_timeout=max_timeout,
            max_items=max_items, request_hasher=lambda r: r.shape(),
            batch_executor=self._execute), clock=clock)

    def create_fleet(self, overrides, tags: Dict[str, str]):
        req = FleetRequest(tuple(overrides), tuple(sorted(tags.items())))
        return self.batcher.add(req)

    def _execute(self, requests: Sequence[FleetRequest]):
        from .fake import FleetResult
        req = requests[0]
        result = self.cloud.create_fleet(
            list(req.overrides), count=len(requests), tags=dict(req.tags))
        out = []
        for i in range(len(requests)):
            if i < len(result.instances):
                out.append(FleetResult(instances=[result.instances[i]],
                                       errors=list(result.errors)))
            else:
                out.append(FleetResult(instances=[], errors=list(result.errors)))
        return out


class DescribeInstancesBatcher:
    """Unions many id-filtered describes into one call; each caller gets only
    its own instances back (describeinstances.go:39-41)."""

    def __init__(self, cloud, clock: Callable[[], float] = time.monotonic,
                 idle: float = DESCRIBE_IDLE, max_timeout: float = DESCRIBE_MAX,
                 max_items: int = DESCRIBE_MAX_ITEMS):
        self.cloud = cloud
        self.batcher: Batcher = Batcher(Options(
            name="describe_instances", idle_timeout=idle,
            max_timeout=max_timeout, max_items=max_items,
            request_hasher=lambda r: "describe",
            batch_executor=self._execute), clock=clock)

    def describe_instances(self, ids: Sequence[str]):
        return self.batcher.add(tuple(ids))

    def _execute(self, requests: Sequence[Tuple[str, ...]]):
        all_ids = sorted({i for req in requests for i in req})
        found = {inst.id: inst for inst in self.cloud.describe_instances(ids=all_ids)}
        return [[found[i] for i in req if i in found] for req in requests]


class TerminateInstancesBatcher:
    """Unions termination ids into one call (terminateinstances.go:38-40)."""

    def __init__(self, cloud, clock: Callable[[], float] = time.monotonic,
                 idle: float = TERMINATE_IDLE, max_timeout: float = TERMINATE_MAX,
                 max_items: int = TERMINATE_MAX_ITEMS):
        self.cloud = cloud
        self.batcher: Batcher = Batcher(Options(
            name="terminate_instances", idle_timeout=idle,
            max_timeout=max_timeout, max_items=max_items,
            request_hasher=lambda r: "terminate",
            batch_executor=self._execute), clock=clock)

    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        return self.batcher.add(tuple(ids))

    def _execute(self, requests: Sequence[Tuple[str, ...]]):
        all_ids = sorted({i for req in requests for i in req})
        done = set(self.cloud.terminate_instances(all_ids))
        return [[i for i in req if i in done] for req in requests]


class BatchedCloud:
    """Facade wrapping a cloud substrate with the three batchers — the
    `batcher.EC2(ctx, api)` analog (/root/reference/pkg/batcher/ec2api.go:23-29).
    Non-batched calls pass through."""

    def __init__(self, cloud, **kw):
        self._cloud = cloud
        self.fleet = CreateFleetBatcher(cloud, **kw)
        self.describe = DescribeInstancesBatcher(cloud, **kw)
        self.terminate = TerminateInstancesBatcher(cloud, **kw)

    def create_fleet(self, overrides, count: int = 1, tags: Optional[Dict[str, str]] = None):
        if count != 1:  # only single-capacity requests merge (createfleet.go:44)
            return self._cloud.create_fleet(overrides, count=count, tags=tags or {})
        return self.fleet.create_fleet(overrides, tags or {})

    def describe_instances(self, ids=None, tag_filter=None):
        if ids is None or tag_filter is not None:
            return self._cloud.describe_instances(ids=ids, tag_filter=tag_filter)
        return self.describe.describe_instances(ids)

    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        return self.terminate.terminate_instances(ids)

    def __getattr__(self, name):
        return getattr(self._cloud, name)
