"""The CloudProvider seam: catalog + actuation.

Re-implements the L3/L2 surface of the reference:
  * `InstanceTypesProvider` — the solver's catalog with ICE-masked offering
    availability and seq-num memoization
    (/root/reference/pkg/providers/instancetype/instancetype.go:89-175,241-278);
  * `CloudProvider` — the core seam `Create/Delete/Get/List/GetInstanceTypes/
    IsDrifted` (/root/reference/pkg/cloudprovider/cloudprovider.go:66-229),
    including the launch path's candidate filtering, price ordering, 60-type
    cap and capacity-type choice
    (/root/reference/pkg/providers/instance/instance.go:88-105,197-253,380-424).
"""

from __future__ import annotations

import copy
import json
import logging
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api import labels as wk
from ..api.objects import NodeClaim, NodeClass, NodePool
from ..api.taints import Taint
from ..api.requirements import IN, Requirement, Requirements
from ..api.resources import CPU, MEMORY, ResourceList
from ..catalog.instancetype import InstanceType, Offering
from ..utils import metrics
from .cache import UnavailableOfferings
from .fake import CloudError, FakeCloud, FleetOverride, FleetResult, ICE_CODE

log = logging.getLogger("karpenter_tpu.cloud.provider")

# Launch action-space cap (/root/reference/pkg/providers/instance/instance.go:56-57).
MAX_INSTANCE_TYPES = 60
MIN_SPOT_FLEXIBILITY = 5  # OD-flexibility warning floor


class InsufficientCapacityError(Exception):
    """All candidate pools ICE'd — the caller retries with a fresh catalog
    (error taxonomy analog: /root/reference/pkg/errors/errors.go:56-103)."""


class NodeClassNotFoundError(InsufficientCapacityError):
    """The claim references a nodeclass that doesn't exist — a persistent
    configuration error, not a capacity shortage (reference NotFound class,
    errors.go:56-103).  Subclasses InsufficientCapacityError so the launch
    path's retry handling still applies, but callers can log it distinctly."""


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded in-call retry for RETRYABLE cloud faults (cloud/errors.py
    is_retryable: throttles + provider internal errors).  `attempts` is
    extra tries beyond the first call; 0 (the default) disables retry
    entirely — the sim must NOT wall-sleep against its virtual clock, so
    only live operators arm this via --cloud-retry-attempts.  Jitter is a
    hash of (method, attempt), not an RNG, for deterministic tests."""
    attempts: int = 0
    base_s: float = 0.2
    max_s: float = 5.0

    def delay(self, method: str, attempt: int) -> float:
        raw = min(self.max_s, self.base_s * 2.0 ** max(0, attempt - 1))
        h = zlib.crc32(f"{method}:{attempt}".encode()) & 0xFFFFFFFF
        return raw * (0.5 + (h / 2**32) * 0.5)


class ProviderCircuitBreaker:
    """Error-storm breaker over the whole provider: `threshold`
    consecutive retryable-class failures OPEN the circuit and launches
    fast-fail as InsufficientCapacityError for `cooldown_s` — feeding the
    pending-pod/ICE backoff machinery instead of hot-looping CreateFleet
    against a melting API.  After the cooldown one call probes half-open.
    threshold=0 (default) disables the breaker."""

    def __init__(self, threshold: int = 0, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.failures = 0
        self.state = "closed"
        self.open_until = float("-inf")
        self.total_opens = 0

    def allow(self) -> bool:
        if self.threshold <= 0 or self.state == "closed":
            return True
        if self.clock() < self.open_until:
            return False
        self._set_state("half_open")  # one probe call through
        return True

    def record_success(self) -> None:
        if self.threshold <= 0:
            return
        self.failures = 0
        if self.state != "closed":
            log.info("cloud circuit recovered (%s -> closed)", self.state)
            self._set_state("closed")

    def record_failure(self) -> None:
        if self.threshold <= 0:
            return
        self.failures += 1
        if self.state == "half_open" or self.failures >= self.threshold:
            self.open_until = self.clock() + self.cooldown_s
            if self.state != "open":
                self.total_opens += 1
                metrics.cloud_breaker_opens().inc()
                log.warning("cloud circuit OPEN after %d consecutive "
                            "failures; fast-failing launches for %.0fs",
                            self.failures, self.cooldown_s)
            self._set_state("open")

    def _set_state(self, state: str) -> None:
        if state != self.state:
            self.state = state
            metrics.cloud_breaker_state().set(
                {"closed": 0, "half_open": 1, "open": 2}[state])

    def snapshot(self) -> Dict:
        return {"state": self.state, "consecutive_failures": self.failures,
                "total_opens": self.total_opens}


@dataclass
class InstanceTypesProvider:
    """Catalog provider with ICE masking + memoization keyed on the
    unavailable-offerings sequence number (instancetype.go:114-124).
    With a pricing provider wired, offering prices come from the live
    on-demand/spot tables instead of the catalog's static values
    (createOfferings price lookup, instancetype.go:144-175)."""
    base_catalog: List[InstanceType]
    unavailable: UnavailableOfferings
    pricing: object = None  # providers.pricing.PricingProvider, optional
    _memo: Tuple[tuple, List[InstanceType]] = field(default=None, repr=False)

    def _offering_price(self, it: InstanceType, o: Offering,
                        live_od: bool, live_spot: bool) -> float:
        # until a table's first live refresh the catalog's own (zone- and
        # capacity-type-differentiated) prices are authoritative — the
        # pricing provider's fallbacks are lossy (per-type min OD, synthetic
        # spot discount); liveness is decided per table
        if o.capacity_type == wk.CAPACITY_TYPE_SPOT:
            if not live_spot:
                return o.price
            p = self.pricing.spot_price(it.name, o.zone)
        else:
            if not live_od:
                return o.price
            p = self.pricing.on_demand_price(it.name)
        return o.price if p is None else p

    def list(self) -> List[InstanceType]:
        # the pricing seqs are read ONCE per rebuild: they key the memo and
        # decide which tables apply live, so a refresh landing mid-rebuild
        # just invalidates the next lookup instead of mixing tables
        od_seq, spot_seq = (0, 0) if self.pricing is None \
            else self.pricing.seq_num
        key = (self.unavailable.seq_num, od_seq, spot_seq)
        if self._memo is not None and self._memo[0] == key:
            return self._memo[1]
        live_od, live_spot = od_seq > 0, spot_seq > 0
        out = []
        cpu_gauge = metrics.instance_type_cpu()
        mem_gauge = metrics.instance_type_memory()
        for it in self.base_catalog:
            offerings = [
                Offering(o.zone, o.capacity_type,
                         self._offering_price(it, o, live_od, live_spot),
                         available=o.available and not self.unavailable.is_unavailable(
                             o.capacity_type, it.name, o.zone))
                for o in it.offerings
            ]
            if any(o.available for o in offerings):
                out.append(InstanceType(
                    name=it.name, requirements=it.requirements,
                    offerings=offerings, capacity=it.capacity,
                    kube_reserved=it.kube_reserved,
                    system_reserved=it.system_reserved,
                    eviction_threshold=it.eviction_threshold, info=it.info))
                # cpu/mem gauges (pkg/providers/instancetype/metrics.go:35-46)
                cpu_gauge.set(it.capacity.get(CPU, 0) / 1000.0,
                              {"instance_type": it.name})
                mem_gauge.set(it.capacity.get(MEMORY, 0),
                              {"instance_type": it.name})
        self._memo = (key, out)
        return out


def _claim_compatible_types(claim: NodeClaim,
                            instance_types: Sequence[InstanceType]) -> List[InstanceType]:
    """Types whose requirements intersect the claim's and whose allocatable
    covers the claim's aggregate requests
    (/root/reference/pkg/cloudprovider/cloudprovider.go:255-266)."""
    out = []
    for it in instance_types:
        # keys the type doesn't define (nodepool, user labels) are provided by
        # the NodePool template at node creation — AllowUndefinedWellKnownLabels
        # semantics (/root/reference/pkg/cloudprovider/cloudprovider.go:260-265)
        allow = [k for k in claim.requirements if k not in it.requirements]
        if not claim.requirements.compatible(it.requirements, allow_undefined=allow):
            continue
        if not claim.requests.fits(it.allocatable):
            continue
        if not any(o.available for o in it.offerings):
            continue
        out.append(it)
    return out


def _build_overrides(claim: NodeClaim, candidates: Sequence[InstanceType]) -> List[FleetOverride]:
    """Cross-product (type × zone × capacity-type) filtered by claim
    requirements, price-ordered, capped at MAX_INSTANCE_TYPES
    (/root/reference/pkg/providers/instance/instance.go:327-367,395-412)."""
    zone_req = claim.requirements.get(wk.ZONE)
    cap_req = claim.requirements.get(wk.CAPACITY_TYPE)
    # capacity-type choice: spot if allowed and available, else on-demand
    # (instance.go:380-393)
    allowed_caps = {wk.CAPACITY_TYPE_SPOT, wk.CAPACITY_TYPE_ON_DEMAND}
    if cap_req is not None:
        # set→set filter feeding only membership tests: order-insensitive
        # graftlint: disable=DT003
        allowed_caps = {c for c in allowed_caps if cap_req.has(c)}
    spot_available = any(
        o.capacity_type == wk.CAPACITY_TYPE_SPOT and o.available
        and (zone_req is None or zone_req.has(o.zone))
        for it in candidates for o in it.offerings)
    capacity_type = (wk.CAPACITY_TYPE_SPOT
                     if wk.CAPACITY_TYPE_SPOT in allowed_caps and spot_available
                     else wk.CAPACITY_TYPE_ON_DEMAND)
    overrides = []
    for it in candidates:
        for o in it.offerings:
            if not o.available or o.capacity_type != capacity_type:
                continue
            if zone_req is not None and not zone_req.has(o.zone):
                continue
            overrides.append(FleetOverride(it.name, o.zone, o.capacity_type, o.price))
    overrides.sort(key=lambda ov: (ov.price, ov.instance_type, ov.zone))
    # cap by distinct instance types, keeping all zones of kept types
    kept_types: List[str] = []
    out = []
    for ov in overrides:
        if ov.instance_type not in kept_types:
            if len(kept_types) >= MAX_INSTANCE_TYPES:
                continue
            kept_types.append(ov.instance_type)
        out.append(ov)
    return out


class CloudProvider:
    """core CloudProvider implementation over the (fake) cloud substrate."""

    name = "karpenter-tpu"

    def __init__(self, cloud: FakeCloud, catalog: List[InstanceType],
                 unavailable: Optional[UnavailableOfferings] = None,
                 node_classes: Optional[Dict[str, NodeClass]] = None,
                 cluster_name: str = "default",
                 clock: Callable[[], float] = time.time,
                 subnets=None, launch_templates=None, pricing=None,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[ProviderCircuitBreaker] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.cloud = cloud
        # call hardening (both default OFF): bounded jittered retry for
        # transient API faults, provider-level circuit breaker for storms
        self.retry = retry
        self.breaker = breaker
        self.sleep = sleep
        self.unavailable = unavailable or UnavailableOfferings()
        self.instance_types = InstanceTypesProvider(catalog, self.unavailable,
                                                    pricing=pricing)
        self.node_classes = node_classes or {"default": NodeClass()}
        self.cluster_name = cluster_name
        self.clock = clock
        # optional L2 wiring (providers/subnet.py, providers/launchtemplate.py);
        # None keeps the bare fleet path for unit tests and benchmarks
        self.subnets = subnets
        self.launch_templates = launch_templates
        self._claims_by_provider_id: Dict[str, NodeClaim] = {}
        # HAFailover fencing (utils/fencing.LeaseFence, attached by the
        # ControllerManager): when set, the _create/_delete funnels refuse
        # to mutate the cloud under a stale fencing epoch.  None = no HA.
        self.fence = None

    # ---- catalog ----
    def get_instance_types(self, nodepool: Optional[NodePool] = None) -> List[InstanceType]:
        its = self.instance_types.list()
        if nodepool is None:
            return its
        reqs = nodepool.requirements()
        return [it for it in its
                if reqs.compatible(it.requirements, allow_undefined=[wk.NODEPOOL])]

    def _call_cloud(self, method: str, fn: Callable):
        """Run one cloud API call under the retry policy + breaker
        bookkeeping.  Only RETRYABLE faults (throttles/internal errors)
        are retried; everything else — and exhausted retries — propagates
        to the caller's existing taxonomy handling."""
        from .errors import is_retryable
        budget = self.retry.attempts if self.retry is not None else 0
        attempt = 0
        while True:
            try:
                out = fn()
                if self.breaker is not None:
                    self.breaker.record_success()
                if attempt:
                    metrics.cloud_retries().inc(
                        {"method": method, "outcome": "recovered"})
                return out
            except CloudError as err:
                if not is_retryable(err):
                    raise
                if self.breaker is not None:
                    self.breaker.record_failure()
                if attempt >= budget:
                    if budget:
                        metrics.cloud_retries().inc(
                            {"method": method, "outcome": "exhausted"})
                    raise
                attempt += 1
                metrics.cloud_retries().inc(
                    {"method": method, "outcome": "retried"})
                delay = self.retry.delay(method, attempt)
                log.info("retrying %s after %s (attempt %d/%d, %.2fs)",
                         method, err.code, attempt, budget, delay)
                self.sleep(delay)

    # ---- actuation ----
    def create(self, claim: NodeClaim) -> NodeClaim:
        t0 = time.perf_counter()
        try:
            out = self._create(claim)
            # claim creation and instance launch coincide at this seam, and
            # EVERY launch path (provisioner, disruption replacement,
            # lifecycle) funnels through it — counting here keeps
            # launched == created >= registered
            metrics.nodeclaims_created().inc({"nodepool": claim.nodepool or ""})
            metrics.nodeclaims_launched().inc({"nodepool": claim.nodepool or ""})
            return out
        finally:
            metrics.cloudprovider_duration().observe(
                time.perf_counter() - t0, {"method": "create"})

    def _create(self, claim: NodeClaim) -> NodeClaim:
        """Launch capacity for a NodeClaim
        (/root/reference/pkg/cloudprovider/cloudprovider.go:92-118 →
        /root/reference/pkg/providers/instance/instance.go:88-105)."""
        if not claim.created_at:
            claim.created_at = self.clock()
        if self.fence is not None and not self.fence.check("launch"):
            # deposed leader mid-tick: the new leader owns the substrate
            # now — refuse (counted), never launch a ghost node
            from ..utils.fencing import StaleFenceError
            raise StaleFenceError(
                f"stale fencing epoch: launch of {claim.name} refused")
        if self.breaker is not None and not self.breaker.allow():
            # fast-fail into the same path an all-ICE'd launch takes: the
            # claim fails, pending pods back off and re-solve later —
            # instead of hammering CreateFleet through an error storm
            raise InsufficientCapacityError(
                "cloud circuit open: launches fast-fail during cooldown")
        nodeclass = self.node_classes.get(claim.node_class_ref)
        # capacity-fit validation must see the nodeclass's boot volume: a
        # mapped 200Gi root makes storage-heavy claims valid even though
        # the base catalog's default volume couldn't hold them (the solver
        # already packed against the adjusted columns)
        types = self.instance_types.list()
        if nodeclass is not None:
            from ..catalog.instancetype import apply_storage, root_volume_gib
            gib = root_volume_gib(nodeclass)
            types = [apply_storage(it, gib) for it in types]
        candidates = _claim_compatible_types(claim, types)
        if not candidates:
            raise InsufficientCapacityError(
                f"no compatible instance types for claim {claim.name}")
        if nodeclass is None and (self.subnets is not None
                                  or self.launch_templates is not None):
            # with the L2 path wired, a dangling nodeclass ref is a config
            # error — launching without subnets/images would produce a
            # misconfigured node (reference errors on nodeclass resolution,
            # cloudprovider.go:231-241)
            raise NodeClassNotFoundError(
                f"nodeclass {claim.node_class_ref!r} not found for claim "
                f"{claim.name}")
        # zonal subnet choice with in-flight IP accounting
        # (/root/reference/pkg/providers/instance/instance.go:197-253 →
        #  subnet.go ZonalSubnetsForLaunch:110-147)
        zonal_subnets = None
        if self.subnets is not None and nodeclass is not None:
            zonal_subnets = self.subnets.zonal_subnets_for_launch(nodeclass)
            if not zonal_subnets:
                raise InsufficientCapacityError(
                    f"no subnets resolve for nodeclass {nodeclass.name}")
        settled = []
        try:
            return self._launch(claim, candidates, nodeclass, zonal_subnets,
                                settled)
        finally:
            # refund predictions the fleet response never settled (launch
            # failed before/at create_fleet) so inflight counts can't leak
            if zonal_subnets is not None and not settled:
                self.subnets.update_inflight_ips([], zonal_subnets)

    def _launch(self, claim: NodeClaim, candidates: List[InstanceType],
                nodeclass: Optional[NodeClass], zonal_subnets,
                settled: List[bool]) -> NodeClaim:
        # launch-template ensure per (image × userdata) group; restricts
        # candidates to types an image covers (launchtemplate.go EnsureAll)
        lt_by_type: Dict[str, Tuple[str, str]] = {}
        if self.launch_templates is not None and nodeclass is not None:
            resolved = self.launch_templates.ensure_all(
                nodeclass, candidates, labels=dict(claim.labels),
                security_group_ids=tuple(nodeclass.status_security_groups),
                instance_profile=nodeclass.status_instance_profile)
            for rt in resolved:
                for it in rt.instance_types:
                    lt_by_type[it.name] = (rt.template.name, rt.template.image_id)
            candidates = [it for it in candidates if it.name in lt_by_type]
            if not candidates:
                raise InsufficientCapacityError(
                    f"no image covers any candidate type for claim {claim.name}")
        overrides = _build_overrides(claim, candidates)
        if zonal_subnets is not None:
            overrides = [ov for ov in overrides if ov.zone in zonal_subnets]
            for ov in overrides:
                ov.subnet_id = zonal_subnets[ov.zone].id
        for ov in overrides:
            if ov.instance_type in lt_by_type:
                ov.launch_template, ov.image_id = lt_by_type[ov.instance_type]
        if not overrides:
            raise InsufficientCapacityError(
                f"no available offerings for claim {claim.name}")
        # fleet tags are POOL-scoped only: the batcher hashes them, and
        # per-claim-unique values would put every single-capacity request in
        # its own bucket, making merging dead code. Claim identity goes on
        # post-launch via create_tags, mirroring the reference (getTags uses
        # only pool-scoped values; identity lands via the tagging flow,
        # /root/reference/pkg/providers/instance/instance.go:255-275 +
        # /root/reference/pkg/controllers/nodeclaim/tagging/controller.go).
        tags = {
            "karpenter.sh/cluster": self.cluster_name,
            "karpenter.sh/nodepool": claim.nodepool,
        }
        if claim.taints:
            # taints ride along as a tag so restart hydration can restore
            # them (cloud tags are the durable store, SURVEY §5.4)
            tags["karpenter.sh/taints"] = json.dumps(
                [{"key": t.key, "effect": t.effect, "value": t.value}
                 for t in claim.taints])
        # user/template labels the catalog can't reconstruct (team=..., etc.)
        # must also survive restarts or selector pods can't re-bind
        custom = {k: v for k, v in claim.labels.items()
                  if "kubernetes.io" not in k and not k.startswith("karpenter")}
        if custom:
            tags["karpenter.sh/labels"] = json.dumps(custom, sort_keys=True)
        # stamp the nodeclass spec hash the node was launched from — the
        # static-drift input (utils/nodeclass.HashAnnotation via
        # cloudprovider.go:116)
        # the ref tag is durable identity — written even when the nodeclass
        # doesn't currently resolve (bare launch path), so hydration never
        # falls back to "default" and mis-attributes the node
        tags["karpenter.sh/nodeclass"] = claim.node_class_ref
        if nodeclass is not None:
            if not nodeclass.hash_annotation:
                from ..controllers.nodeclass import static_hash
                nodeclass.hash_annotation = static_hash(nodeclass)
            claim.node_class_hash = nodeclass.hash_annotation
            tags["karpenter.sh/nodeclass-hash"] = nodeclass.hash_annotation
        result = self._call_cloud(
            "create_fleet",
            lambda: self.cloud.create_fleet(overrides, count=1, tags=tags))
        # settle the in-flight IP predictions against where the launch landed
        # (subnet.go UpdateInflightIPs:149)
        if zonal_subnets is not None:
            self.subnets.update_inflight_ips(
                [i.subnet_id for i in result.instances], zonal_subnets)
            settled.append(True)
        # feed partial failures back into the ICE cache
        # (instance.go:369-375 updateUnavailableOfferingsCache)
        from .errors import classify, is_unfulfillable_capacity
        err_counter = metrics.cloud_errors_total()
        for err in result.errors:
            err_counter.inc({"classification": classify(err)})
            if is_unfulfillable_capacity(err):
                self.unavailable.mark_unavailable_for_fleet_err(
                    err.code, err.override.instance_type, err.override.zone,
                    err.override.capacity_type)
        if not result.instances:
            raise InsufficientCapacityError(
                f"all {len(overrides)} offerings ICE'd for claim {claim.name}")
        inst = result.instances[0]
        # claim identity (unique per launch) is tagged after the fleet call
        # so same-shape requests keep merging in the batcher
        try:
            self.cloud.create_tags(inst.id, {
                "karpenter.sh/nodeclaim": claim.name,
                "Name": f"{claim.nodepool}/{claim.name}",
            })
        except CloudError as e:
            # instance launched; identity tag retries via TaggingController
            log.warning("post-launch identity tagging failed for %s: %s",
                        inst.id, e)
        claim.provider_id = inst.id
        claim.instance_type = inst.instance_type
        claim.zone = inst.zone
        claim.capacity_type = inst.capacity_type
        claim.price = inst.price
        claim.launched_at = inst.launched_at
        claim.image_id = inst.image_id
        claim.labels.update(self._instance_labels(inst, claim))
        self._claims_by_provider_id[inst.id] = claim
        # cost-ledger seam (SLOEngine gate, free when disarmed): expected
        # $/h is the cheapest offering this launch INTENDED (overrides[0],
        # price-sorted upstream); realized is what the fleet landed on —
        # they diverge exactly when ICE pushed the claim onto a pricier
        # offering, which is the drift the ledger watches
        from ..obs.ledger import LEDGER, current_trace_id
        if LEDGER.enabled:
            LEDGER.record_launch(
                inst.id, nodepool=claim.nodepool,
                pod_class=inst.instance_type,
                expected_rate=overrides[0].price,
                realized_rate=inst.price,
                at=self.clock(),
                fence_epoch=self.fence.epoch() if self.fence is not None
                else 0,
                trace_id=current_trace_id())
        return claim

    def _instance_labels(self, inst, claim: NodeClaim) -> Dict[str, str]:
        """instance → node labels
        (instanceToNodeClaim, /root/reference/pkg/cloudprovider/cloudprovider.go:307-339)."""
        labels = {
            wk.INSTANCE_TYPE: inst.instance_type,
            wk.ZONE: inst.zone,
            wk.CAPACITY_TYPE: inst.capacity_type,
            wk.NODEPOOL: claim.nodepool,
        }
        it = next((t for t in self.instance_types.base_catalog
                   if t.name == inst.instance_type), None)
        if it is not None:
            labels.update({k: v for k, v in it.requirements.labels().items()
                           if k not in (wk.ZONE, wk.CAPACITY_TYPE)})
        return labels

    def delete(self, claim: NodeClaim) -> None:
        t0 = time.perf_counter()
        try:
            return self._delete(claim)
        finally:
            metrics.cloudprovider_duration().observe(
                time.perf_counter() - t0, {"method": "delete"})

    def _delete(self, claim: NodeClaim) -> None:
        if not claim.provider_id:
            return
        if self.fence is not None and not self.fence.check("terminate"):
            from ..utils.fencing import StaleFenceError
            raise StaleFenceError(
                f"stale fencing epoch: terminate of {claim.provider_id} "
                "refused")
        done = self.cloud.terminate_instances([claim.provider_id])
        claim.terminating = True
        # ledger close: realized lifetime ends here.  The reason is the
        # active decision context (disruption/interruption controllers
        # tag their actuation funnels); untagged deletes are terminations.
        from ..obs.ledger import LEDGER
        if LEDGER.enabled:
            LEDGER.record_close(
                claim.provider_id, at=self.clock(),
                reason=LEDGER.current_source(default="termination"))
        if not done:
            raise CloudError("InstanceNotFound", claim.provider_id)

    def get(self, provider_id: str) -> Optional[NodeClaim]:
        try:
            inst = self.cloud.get_instance(provider_id)
        except CloudError:
            return None
        return self._instance_to_claim(inst)

    def list(self) -> List[NodeClaim]:
        t0 = time.perf_counter()
        try:
            return self._list()
        finally:
            metrics.cloudprovider_duration().observe(
                time.perf_counter() - t0, {"method": "list"})

    def _list(self) -> List[NodeClaim]:
        """All cluster-owned instances as NodeClaims (GC ground truth,
        /root/reference/pkg/controllers/nodeclaim/garbagecollection/controller.go:57-91)."""
        out = []
        for inst in self._call_cloud(
                "describe_instances",
                lambda: self.cloud.describe_instances(
                    tag_filter={"karpenter.sh/cluster": self.cluster_name})):
            out.append(self._instance_to_claim(inst))
        return out

    def _instance_to_claim(self, inst) -> NodeClaim:
        known = self._claims_by_provider_id.get(inst.id)
        if known is not None:
            return known
        claim = NodeClaim(nodepool=inst.tags.get("karpenter.sh/nodepool", ""))
        # restore the durable identity from tags (cloud tags are the durable
        # store — SURVEY §5.4; reference restores machine identity the same
        # way via its Link hook)
        if inst.tags.get("karpenter.sh/nodeclaim"):
            claim.name = inst.tags["karpenter.sh/nodeclaim"]
        claim.provider_id = inst.id
        claim.instance_type = inst.instance_type
        claim.zone = inst.zone
        claim.capacity_type = inst.capacity_type
        claim.price = inst.price
        claim.launched_at = inst.launched_at
        # the boot image is durable on the instance record itself (EC2
        # DescribeInstances returns ImageId), so hydration restores the
        # AMI-drift input with no extra tag
        claim.image_id = inst.image_id
        # labels/taints must survive hydration or recovered nodes reject
        # every selector/affinity pod (compat fails closed on absent keys):
        # custom labels come back from the tag, well-known from the catalog
        labels_json = inst.tags.get("karpenter.sh/labels")
        if labels_json:
            claim.labels.update(json.loads(labels_json))
        claim.labels.update(self._instance_labels(inst, claim))
        taints_json = inst.tags.get("karpenter.sh/taints")
        if taints_json:
            claim.taints = [Taint(d["key"], d["effect"], d.get("value", ""))
                            for d in json.loads(taints_json)]
        # the ref must restore WITH the hash, else a restarted operator
        # compares a non-default nodeclass's launch hash against "default"
        # and churn-replaces every healthy recovered node as drifted
        if inst.tags.get("karpenter.sh/nodeclass"):
            claim.node_class_ref = inst.tags["karpenter.sh/nodeclass"]
        claim.node_class_hash = inst.tags.get("karpenter.sh/nodeclass-hash", "")
        return claim

    def is_drifted(self, claim: NodeClaim, nodepool: Optional[NodePool] = None) -> Optional[str]:
        """Drift detection analog
        (/root/reference/pkg/cloudprovider/drift.go:42-67): static hash of
        the nodeclass spec the node was launched from vs its current hash
        (the reference's primary mechanism), plus catalog/pool/zone checks."""
        it = next((t for t in self.instance_types.base_catalog
                   if t.name == claim.instance_type), None)
        if it is None:
            return "InstanceTypeRemoved"
        if nodepool is not None:
            if not nodepool.requirements().compatible(
                    it.requirements, allow_undefined=[wk.NODEPOOL]):
                return "NodePoolDrifted"
        nc = self.node_classes.get(claim.node_class_ref)
        if nc is not None:
            # the reference's precedence (drift.go:42-67): static fields
            # first — it saves the instance lookup — then the live
            # instance's AMI, security groups, and subnet against the
            # nodeclass's resolved status, first hit wins
            if claim.node_class_hash:
                from ..controllers.nodeclass import static_hash
                current = nc.hash_annotation or static_hash(nc)
                if claim.node_class_hash != current:
                    return "NodeClassHashDrifted"
            # the instance attributes drift checks read (boot AMI, subnet,
            # launch template) are immutable post-launch, so the lookup
            # runs ONCE per claim and memoizes on it — the disruption
            # controller calls is_drifted for every candidate every tick,
            # and N live describes per tick would be pure waste (review
            # r5).  A failed lookup memoizes too (warn once, not per
            # tick); deleting the attr forces a refresh.
            meta = getattr(claim, "_drift_instance_meta", None)
            if meta is None and claim.provider_id:
                try:
                    inst = self.cloud.get_instance(claim.provider_id)
                    meta = (inst.image_id, inst.subnet_id,
                            inst.launch_template)
                    claim._drift_instance_meta = meta
                except Exception as e:
                    # failures are NOT memoized — the next reconcile
                    # retries (a transient throttle must not disable
                    # SG/subnet drift for the node's lifetime); only the
                    # warning is deduped per claim
                    if not getattr(claim, "_drift_lookup_warned", False):
                        log.warning(
                            "drift check for %s: instance %s lookup "
                            "failed (%s); static checks only until the "
                            "lookup succeeds", claim.name,
                            claim.provider_id, e)
                        claim._drift_lookup_warned = True
                    meta = ("", "", "")
            inst_image, inst_subnet, inst_lt = meta or ("", "", "")
            # AMI drift (isAMIDrifted): a newer image published under the
            # same selector resolves into status_images and drifts every
            # node booted from the old one; prefer the live instance's AMI
            image = inst_image or claim.image_id
            if image and nc.status_images and image not in nc.status_images:
                return "ImageDrifted"
            # security-group drift (areSecurityGroupsDrifted): the launch
            # template the instance booted from carries its SG set — any
            # mismatch with the nodeclass's resolved set drifts
            if inst_lt and nc.status_security_groups:
                lt = getattr(self.cloud, "launch_templates", {}).get(inst_lt)
                if lt is not None and set(lt.security_group_ids) != \
                        set(nc.status_security_groups):
                    return "SecurityGroupDrifted"
            # subnet drift (isSubnetDrifted): instance's subnet no longer
            # among the nodeclass's resolved subnets
            if (inst_subnet and nc.status_subnets
                    and inst_subnet not in nc.status_subnets):
                return "SubnetDrifted"
            if nc.status_zones and claim.zone not in nc.status_zones:
                return "ZoneDrifted"
        return None

    def liveness_probe(self) -> bool:
        # an open breaker means the substrate is failing hard enough that
        # we've stopped talking to it — surface that on /readyz
        return self.breaker is None or self.breaker.state != "open"
