"""Cloud error taxonomy: code classification, not string matching.

Re-expresses the reference's error classifier
(/root/reference/pkg/errors/errors.go:56-103): call sites ask *what kind*
of failure they got — not-found, already-exists, unfulfillable capacity,
launch-template-not-found — instead of comparing code strings inline.
The code sets mirror the reference's lists; `CloudError` is the
transport (cloud/fake.py), `InsufficientCapacityError` /
`NodeClassNotFoundError` (cloud/provider.py) are the launch-path
wrappers layered on top.
"""

from __future__ import annotations

from typing import Optional

from .fake import CloudError, ICE_CODE

# errors.go:56-66 notFoundErrorCodes (+ the fake cloud's own spellings)
NOT_FOUND_CODES = frozenset({
    "InstanceNotFound",
    "InvalidInstanceID.NotFound",
    "InvalidLaunchTemplateId.NotFound",
    "InvalidLaunchTemplateName.NotFoundException",
    "ParameterNotFound",
    "ImageNotFound",
    "NoSuchEntity",
    "ResourceNotFoundException",
})

# errors.go alreadyExistsErrorCodes
ALREADY_EXISTS_CODES = frozenset({
    "EntityAlreadyExists",
    "AlreadyExists",
    "InvalidLaunchTemplateName.AlreadyExistsException",
})

# errors.go:83-94 unfulfillableCapacityErrorCodes — fleet error codes that
# mean "this offering cannot be fulfilled right now" and should feed the
# ICE cache rather than fail the claim
UNFULFILLABLE_CAPACITY_CODES = frozenset({
    ICE_CODE,
    "InsufficientInstanceCapacity",
    "MaxSpotInstanceCountExceeded",
    "VcpuLimitExceeded",
    "UnfulfillableCapacity",
    "Unsupported",
    "InsufficientFreeAddressesInSubnet",
})

LAUNCH_TEMPLATE_NOT_FOUND_CODES = frozenset({
    "InvalidLaunchTemplateId.NotFound",
    "InvalidLaunchTemplateName.NotFoundException",
})

# Transient faults worth an in-call retry: throttles and provider-side
# internal errors (the aws-sdk retryer's default retryable set).  NOT
# unfulfillable capacity — that is a *state*, fed to the ICE cache, and
# re-asking the same offering inside one call can't change it.
RETRYABLE_CODES = frozenset({
    "RequestLimitExceeded",
    "Throttling",
    "ThrottlingException",
    "RequestThrottled",
    "TooManyRequestsException",
    "InternalError",
    "InternalFailure",
    "ServiceUnavailable",
    "Unavailable",
})


def _code(err: Optional[BaseException]) -> str:
    return getattr(err, "code", "") or ""


def is_not_found(err: Optional[BaseException]) -> bool:
    """IsNotFound (errors.go:68-74): the named resource no longer exists —
    for deletes this means success (idempotent terminate), for gets it
    means the caller should treat the object as gone."""
    c = _code(err)
    return c in NOT_FOUND_CODES or c.endswith(".NotFound") \
        or c.endswith("NotFoundException")


def is_already_exists(err: Optional[BaseException]) -> bool:
    """IsAlreadyExists: create raced with another creator — the resource is
    there, proceed as if the create succeeded."""
    c = _code(err)
    return c in ALREADY_EXISTS_CODES or "AlreadyExists" in c


def is_unfulfillable_capacity(err: Optional[BaseException]) -> bool:
    """IsUnfulfillableCapacity (errors.go:96-103): feed the ICE cache and
    retry other offerings instead of failing the claim."""
    return _code(err) in UNFULFILLABLE_CAPACITY_CODES


def is_launch_template_not_found(err: Optional[BaseException]) -> bool:
    """IsLaunchTemplateNotFound: the cached template was deleted out from
    under us — invalidate and recreate (instance.go:96-100 retry)."""
    return _code(err) in LAUNCH_TEMPLATE_NOT_FOUND_CODES


def is_retryable(err: Optional[BaseException]) -> bool:
    """IsRetryable: a transient throttle/internal fault — safe to retry
    the SAME request after a jittered backoff (cloud/provider.py
    RetryPolicy).  Unfulfillable capacity is deliberately excluded."""
    return _code(err) in RETRYABLE_CODES


def classify(err) -> str:
    """One-word classification for logs/metrics labels.  Duck-typed on the
    `code` attribute so fleet per-override errors (cloud/fake.py FleetError)
    classify the same way CloudError exceptions do."""
    if not _code(err):
        return "other"
    if is_unfulfillable_capacity(err):
        return "unfulfillable_capacity"
    if is_launch_template_not_found(err):
        return "launch_template_not_found"
    if is_not_found(err):
        return "not_found"
    if is_already_exists(err):
        return "already_exists"
    if is_retryable(err):
        return "retryable"
    return "cloud_error"
