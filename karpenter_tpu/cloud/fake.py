"""In-memory fake cloud.

Behavior-port of the reference's test backend
(/root/reference/pkg/fake/ec2api.go:40-120: recordable behaviors, a
thread-safe instance store, a stateful CreateFleet that launches in-memory
instances, and an `InsufficientCapacityPools` knob injecting ICE per
(type, zone, capacityType)) — here promoted to a first-class substrate the
end-to-end slice and benchmarks run against (SURVEY.md §7.4)."""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

ICE_CODE = "InsufficientInstanceCapacity"

_fleet_ids = itertools.count(1)


class CloudError(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}")
        self.code = code


@dataclass
class CloudInstance:
    id: str
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    tags: Dict[str, str] = field(default_factory=dict)
    state: str = "running"
    launched_at: float = field(default_factory=time.time)
    subnet_id: str = ""
    image_id: str = ""
    launch_template: str = ""


@dataclass
class FleetOverride:
    """One (instanceType × zone × capacityType) launch candidate, price-ordered
    — the CreateFleet override list
    (/root/reference/pkg/providers/instance/instance.go:327-367)."""
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    subnet_id: str = ""
    launch_template: str = ""
    image_id: str = ""


@dataclass
class FleetError:
    override: FleetOverride
    code: str


@dataclass
class FleetResult:
    instances: List[CloudInstance]
    errors: List[FleetError]


@dataclass
class SubnetInfo:
    """A network placement target — subnet analog with free-IP accounting
    (/root/reference/pkg/providers/subnet/subnet.go:59,110-147)."""
    id: str
    zone: str
    available_ip_count: int
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class SecurityGroupInfo:
    """A firewall group discoverable by id/name/tags
    (/root/reference/pkg/providers/securitygroup/securitygroup.go:54-76)."""
    id: str
    name: str
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class ImageInfo:
    """A bootable node image — AMI analog
    (/root/reference/pkg/providers/amifamily/ami.go:116-136)."""
    id: str
    name: str
    architecture: str = "amd64"
    creation_ts: float = 0.0
    deprecated: bool = False
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class LaunchTemplateInfo:
    """A stored launch template
    (/root/reference/pkg/providers/launchtemplate/launchtemplate.go:233)."""
    name: str
    image_id: str
    user_data: str = ""
    security_group_ids: Tuple[str, ...] = ()
    block_device_gib: int = 20
    block_device_mappings: Tuple[str, ...] = ()   # canonical JSON strings
    metadata_options: Tuple = ()                  # sorted (key, value) pairs
    detailed_monitoring: bool = False
    instance_store_policy: str = ""
    associate_public_ip: object = None            # None == subnet default
    instance_profile: str = ""
    tags: Dict[str, str] = field(default_factory=dict)


class FakeCloud:
    """The cloud API the provider talks to. Thread-safe; failure injection via
    `insufficient_capacity_pools` and `next_error`."""

    def __init__(self, clock: Callable[[], float] = time.time,
                 queue: Optional["FakeQueue"] = None):
        from ..analysis.lockorder import named_lock
        self.clock = clock
        self._lock = named_lock("cloud", threading.RLock)
        self._instances: Dict[str, CloudInstance] = {}  # guarded-by: _lock
        self._ids = itertools.count(1)
        # (capacity_type, instance_type, zone) pools that ICE
        self.insufficient_capacity_pools: Set[Tuple[str, str, str]] = set()
        self.next_error: Optional[Exception] = None
        self.calls: Dict[str, int] = {}
        self.queue = queue  # interruption events published here when attached
        # network inventory (seeded by tests / the operator)
        self.subnets: List[SubnetInfo] = []
        self.security_groups: List[SecurityGroupInfo] = []
        self.images: List[ImageInfo] = []
        self.launch_templates: Dict[str, LaunchTemplateInfo] = {}
        # (instance_type, zone) → spot price history, newest wins
        self.spot_prices: Dict[Tuple[str, str], float] = {}
        # clock-scheduled deliveries: (at, seq, action, instance_id) heap,
        # drained by deliver_due() — the virtual-time interruption pipeline
        # (warning at T-120, reclaim at T)
        self._scheduled: List[Tuple[float, int, str, str]] = []  # guarded-by: _lock
        self._sched_seq = itertools.count(1)
        # every API call fails with RequestLimitExceeded while
        # clock() < throttle_until (API throttle burst injection)
        self.throttle_until: float = 0.0

    # ---- test knobs ----
    def reset(self):
        with self._lock:
            self._instances.clear()
            self.insufficient_capacity_pools.clear()
            self.next_error = None
            self.calls.clear()
            self._scheduled.clear()
            self.throttle_until = 0.0

    def _count(self, api: str):
        self.calls[api] = self.calls.get(api, 0) + 1

    def _maybe_raise(self, api: str = ""):
        if self.next_error is not None:
            err, self.next_error = self.next_error, None
            raise err
        if self.clock() < self.throttle_until:
            raise CloudError("RequestLimitExceeded", "throttle window open")
        # chaos seam: rules targeting point "cloud.api" key on the API name
        # (utils/chaos.py); a no-op unless the injector is armed
        from ..utils.chaos import CHAOS
        if CHAOS.enabled:
            CHAOS.inject("cloud.api", key=api)

    # ---- APIs ----
    def create_fleet(self, overrides: Sequence[FleetOverride], count: int = 1,
                     tags: Optional[Dict[str, str]] = None) -> FleetResult:
        """Launch `count` instances from the cheapest non-ICE'd override —
        CreateFleet(instant) semantics incl. partial-failure reporting
        (/root/reference/pkg/providers/instance/instance.go:369-375,522-536)."""
        with self._lock:
            self._count("create_fleet")
            self._maybe_raise("create_fleet")
            errors: List[FleetError] = []
            usable: List[FleetOverride] = []
            seen_ice: Set[Tuple[str, str, str]] = set()
            for ov in sorted(overrides, key=lambda o: (o.price, o.instance_type, o.zone)):
                pool = (ov.capacity_type, ov.instance_type, ov.zone)
                if pool in self.insufficient_capacity_pools:
                    if pool not in seen_ice:
                        errors.append(FleetError(ov, ICE_CODE))
                        seen_ice.add(pool)
                    continue
                usable.append(ov)
            instances = []
            if usable:
                ov = usable[0]
                for _ in range(count):
                    iid = f"i-{next(self._ids):017x}"
                    inst = CloudInstance(
                        id=iid, instance_type=ov.instance_type, zone=ov.zone,
                        capacity_type=ov.capacity_type, price=ov.price,
                        tags=dict(tags or {}), launched_at=self.clock(),
                        subnet_id=ov.subnet_id, image_id=ov.image_id,
                        launch_template=ov.launch_template)
                    self._instances[iid] = inst
                    instances.append(inst)
            return FleetResult(instances=instances, errors=errors)

    def describe_instances(self, ids: Optional[Sequence[str]] = None,
                           tag_filter: Optional[Dict[str, str]] = None,
                           include_terminated: bool = False) -> List[CloudInstance]:
        with self._lock:
            self._count("describe_instances")
            self._maybe_raise("describe_instances")
            out = []
            for inst in self._instances.values():
                if ids is not None and inst.id not in ids:
                    continue
                if not include_terminated and inst.state != "running":
                    continue
                if tag_filter and any(inst.tags.get(k) != v for k, v in tag_filter.items()):
                    continue
                out.append(inst)
            return out

    def get_instance(self, iid: str) -> CloudInstance:
        with self._lock:
            self._count("get_instance")
            inst = self._instances.get(iid)
            if inst is None or inst.state != "running":
                raise CloudError("InstanceNotFound", iid)
            return inst

    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        with self._lock:
            self._count("terminate_instances")
            self._maybe_raise("terminate_instances")
            done = []
            for iid in ids:
                inst = self._instances.get(iid)
                if inst is not None and inst.state == "running":
                    inst.state = "terminated"
                    done.append(iid)
            return done

    def describe_subnets(self) -> List["SubnetInfo"]:
        with self._lock:
            self._count("describe_subnets")
            self._maybe_raise("describe_subnets")
            return list(self.subnets)

    def describe_security_groups(self) -> List["SecurityGroupInfo"]:
        with self._lock:
            self._count("describe_security_groups")
            self._maybe_raise("describe_security_groups")
            return list(self.security_groups)

    def describe_images(self, ids: Optional[Sequence[str]] = None) -> List["ImageInfo"]:
        with self._lock:
            self._count("describe_images")
            self._maybe_raise("describe_images")
            if ids is None:
                return list(self.images)
            want = set(ids)
            return [i for i in self.images if i.id in want]

    def create_launch_template(self, lt: "LaunchTemplateInfo") -> "LaunchTemplateInfo":
        with self._lock:
            self._count("create_launch_template")
            self._maybe_raise("create_launch_template")
            if lt.name in self.launch_templates:
                raise CloudError("InvalidLaunchTemplateName.AlreadyExistsException",
                                 lt.name)
            self.launch_templates[lt.name] = lt
            return lt

    def describe_launch_templates(self, tag_filter: Optional[Dict[str, str]] = None
                                  ) -> List["LaunchTemplateInfo"]:
        with self._lock:
            self._count("describe_launch_templates")
            self._maybe_raise("describe_launch_templates")
            out = []
            for lt in self.launch_templates.values():
                if tag_filter and any(lt.tags.get(k) != v
                                      for k, v in tag_filter.items()):
                    continue
                out.append(lt)
            return out

    def delete_launch_template(self, name: str) -> None:
        with self._lock:
            self._count("delete_launch_template")
            self._maybe_raise("delete_launch_template")
            if name not in self.launch_templates:
                raise CloudError("InvalidLaunchTemplateId.NotFound", name)
            del self.launch_templates[name]

    def describe_spot_price_history(self) -> Dict[Tuple[str, str], float]:
        """(type, zone) → latest spot price
        (/root/reference/pkg/providers/pricing/pricing.go:308+)."""
        with self._lock:
            self._count("describe_spot_price_history")
            self._maybe_raise("describe_spot_price_history")
            return dict(self.spot_prices)

    def create_tags(self, iid: str, tags: Dict[str, str]) -> None:
        with self._lock:
            self._count("create_tags")
            self._maybe_raise("create_tags")
            inst = self._instances.get(iid)
            if inst is None:
                raise CloudError("InstanceNotFound", iid)
            inst.tags.update(tags)

    # ---- chaos helpers ----
    def _publish(self, kind: str, ids, state: str = ""):
        if self.queue is not None:
            from .queue import make_event_body
            self.queue.send(make_event_body(kind, ids, state=state,
                                            ts=self.clock()))

    def interrupt(self, iid: str, at: Optional[float] = None,
                  warning_s: float = 120.0) -> CloudInstance:
        """Spot-interrupt an instance.

        With ``at`` given, the whole pipeline is clock-scheduled: the
        2-minute warning publishes at ``at - warning_s`` (clamped to now)
        and the capacity is pulled at ``at`` — both fire from
        `deliver_due()` when the injected clock reaches them, so virtual
        time drives delivery.  Without ``at`` and with a queue attached,
        the warning publishes immediately and the reclaim deadline is
        scheduled ``warning_s`` out (drained by `deliver_due()`; callers
        that never drain keep the old warn-only behavior and may still
        `reclaim()` manually).  Without a queue there is nobody to warn,
        so the capacity is reclaimed immediately (pre-queue behavior)."""
        with self._lock:
            inst = self._instances.get(iid)
            if inst is None:
                raise CloudError("InstanceNotFound", iid)
            if self.queue is None:
                inst.state = "terminated"
                return inst
            if at is not None:
                now = self.clock()
                heapq.heappush(self._scheduled,
                               (max(now, at - warning_s),
                                next(self._sched_seq), "warn", iid))
                heapq.heappush(self._scheduled,
                               (at, next(self._sched_seq), "reclaim", iid))
                return inst
            heapq.heappush(self._scheduled,
                           (self.clock() + warning_s,
                            next(self._sched_seq), "reclaim", iid))
        from .queue import SPOT_INTERRUPTION
        self._publish(SPOT_INTERRUPTION, [iid])
        return inst

    def next_due(self) -> Optional[float]:
        """Earliest clock-scheduled delivery, or None."""
        with self._lock:
            return self._scheduled[0][0] if self._scheduled else None

    def deliver_due(self) -> List[Dict]:
        """Fire every scheduled delivery whose time has come.

        Returns one record per firing:  ``spot_warning`` publishes the
        interruption warning for a still-running instance;
        ``spot_reclaim`` pulls the capacity — ``honored=True`` means the
        controllers drained the node before the deadline (the instance was
        already gone), ``False`` means the reclaim had to kill it."""
        fired: List[Dict] = []
        publish: List[Tuple[str, str, str]] = []
        with self._lock:
            now = self.clock()
            while self._scheduled and self._scheduled[0][0] <= now:
                at, _, action, iid = heapq.heappop(self._scheduled)
                inst = self._instances.get(iid)
                running = inst is not None and inst.state == "running"
                if action == "warn":
                    if running:
                        publish.append(("spot_interruption", iid, ""))
                        fired.append({"at": at, "action": "spot_warning",
                                      "instance": iid})
                    continue
                honored = not running
                if running:
                    inst.state = "terminated"
                    publish.append(("state_change", iid, "terminated"))
                fired.append({"at": at, "action": "spot_reclaim",
                              "instance": iid, "honored": honored})
        if publish:
            from .queue import SPOT_INTERRUPTION, STATE_CHANGE
            kinds = {"spot_interruption": SPOT_INTERRUPTION,
                     "state_change": STATE_CHANGE}
            for kind, iid, state in publish:
                self._publish(kinds[kind], [iid], state=state)
        return fired

    def reclaim(self, iid: str) -> None:
        """The interruption deadline passed: capacity is pulled and a
        state-change event fires."""
        with self._lock:
            inst = self._instances.get(iid)
            if inst is not None:
                inst.state = "terminated"
        from .queue import STATE_CHANGE
        self._publish(STATE_CHANGE, [iid], state="terminated")

    def running(self) -> List[CloudInstance]:
        return self.describe_instances()

    # ---- warm restart (state/snapshot.py) ----
    def snapshot_state(self) -> Dict:
        """Round-trippable export of the whole fake-cloud world — the
        kill-9 parity test replays the exact same launches after restore,
        so instance/sequence counters transfer via probe-and-reset (read
        the next value, recreate the counter at it: net zero draws)."""
        with self._lock:
            next_id = next(self._ids)
            self._ids = itertools.count(next_id)
            next_seq = next(self._sched_seq)
            self._sched_seq = itertools.count(next_seq)
            return {
                "instances": dict(self._instances),
                "ice_pools": set(self.insufficient_capacity_pools),
                "calls": dict(self.calls),
                "subnets": list(self.subnets),
                "security_groups": list(self.security_groups),
                "images": list(self.images),
                "launch_templates": dict(self.launch_templates),
                "spot_prices": dict(self.spot_prices),
                "scheduled": list(self._scheduled),
                "throttle_until": self.throttle_until,
                "next_id": next_id,
                "next_sched_seq": next_seq,
            }

    def restore_state(self, data: Dict) -> None:
        with self._lock:
            self._instances = dict(data["instances"])
            self.insufficient_capacity_pools = set(data["ice_pools"])
            self.calls = dict(data["calls"])
            self.subnets = list(data["subnets"])
            self.security_groups = list(data["security_groups"])
            self.images = list(data["images"])
            self.launch_templates = dict(data["launch_templates"])
            self.spot_prices = dict(data["spot_prices"])
            self._scheduled = list(data["scheduled"])
            heapq.heapify(self._scheduled)
            self.throttle_until = float(data["throttle_until"])
            self._ids = itertools.count(int(data["next_id"]))
            self._sched_seq = itertools.count(int(data["next_sched_seq"]))
