"""In-memory fake cloud.

Behavior-port of the reference's test backend
(/root/reference/pkg/fake/ec2api.go:40-120: recordable behaviors, a
thread-safe instance store, a stateful CreateFleet that launches in-memory
instances, and an `InsufficientCapacityPools` knob injecting ICE per
(type, zone, capacityType)) — here promoted to a first-class substrate the
end-to-end slice and benchmarks run against (SURVEY.md §7.4)."""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

ICE_CODE = "InsufficientInstanceCapacity"

_fleet_ids = itertools.count(1)


class CloudError(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}")
        self.code = code


@dataclass
class CloudInstance:
    id: str
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    tags: Dict[str, str] = field(default_factory=dict)
    state: str = "running"
    launched_at: float = field(default_factory=time.time)
    subnet_id: str = ""
    image_id: str = ""
    launch_template: str = ""


@dataclass
class FleetOverride:
    """One (instanceType × zone × capacityType) launch candidate, price-ordered
    — the CreateFleet override list
    (/root/reference/pkg/providers/instance/instance.go:327-367)."""
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    subnet_id: str = ""
    launch_template: str = ""
    image_id: str = ""


@dataclass
class FleetError:
    override: FleetOverride
    code: str


@dataclass
class FleetResult:
    instances: List[CloudInstance]
    errors: List[FleetError]


@dataclass
class SubnetInfo:
    """A network placement target — subnet analog with free-IP accounting
    (/root/reference/pkg/providers/subnet/subnet.go:59,110-147)."""
    id: str
    zone: str
    available_ip_count: int
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class SecurityGroupInfo:
    """A firewall group discoverable by id/name/tags
    (/root/reference/pkg/providers/securitygroup/securitygroup.go:54-76)."""
    id: str
    name: str
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class ImageInfo:
    """A bootable node image — AMI analog
    (/root/reference/pkg/providers/amifamily/ami.go:116-136)."""
    id: str
    name: str
    architecture: str = "amd64"
    creation_ts: float = 0.0
    deprecated: bool = False
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class LaunchTemplateInfo:
    """A stored launch template
    (/root/reference/pkg/providers/launchtemplate/launchtemplate.go:233)."""
    name: str
    image_id: str
    user_data: str = ""
    security_group_ids: Tuple[str, ...] = ()
    block_device_gib: int = 20
    block_device_mappings: Tuple[str, ...] = ()   # canonical JSON strings
    metadata_options: Tuple = ()                  # sorted (key, value) pairs
    detailed_monitoring: bool = False
    instance_store_policy: str = ""
    associate_public_ip: object = None            # None == subnet default
    instance_profile: str = ""
    tags: Dict[str, str] = field(default_factory=dict)


class FakeCloud:
    """The cloud API the provider talks to. Thread-safe; failure injection via
    `insufficient_capacity_pools` and `next_error`."""

    def __init__(self, clock: Callable[[], float] = time.time,
                 queue: Optional["FakeQueue"] = None):
        self.clock = clock
        self._lock = threading.RLock()
        self._instances: Dict[str, CloudInstance] = {}
        self._ids = itertools.count(1)
        # (capacity_type, instance_type, zone) pools that ICE
        self.insufficient_capacity_pools: Set[Tuple[str, str, str]] = set()
        self.next_error: Optional[Exception] = None
        self.calls: Dict[str, int] = {}
        self.queue = queue  # interruption events published here when attached
        # network inventory (seeded by tests / the operator)
        self.subnets: List[SubnetInfo] = []
        self.security_groups: List[SecurityGroupInfo] = []
        self.images: List[ImageInfo] = []
        self.launch_templates: Dict[str, LaunchTemplateInfo] = {}
        # (instance_type, zone) → spot price history, newest wins
        self.spot_prices: Dict[Tuple[str, str], float] = {}

    # ---- test knobs ----
    def reset(self):
        with self._lock:
            self._instances.clear()
            self.insufficient_capacity_pools.clear()
            self.next_error = None
            self.calls.clear()

    def _count(self, api: str):
        self.calls[api] = self.calls.get(api, 0) + 1

    def _maybe_raise(self):
        if self.next_error is not None:
            err, self.next_error = self.next_error, None
            raise err

    # ---- APIs ----
    def create_fleet(self, overrides: Sequence[FleetOverride], count: int = 1,
                     tags: Optional[Dict[str, str]] = None) -> FleetResult:
        """Launch `count` instances from the cheapest non-ICE'd override —
        CreateFleet(instant) semantics incl. partial-failure reporting
        (/root/reference/pkg/providers/instance/instance.go:369-375,522-536)."""
        with self._lock:
            self._count("create_fleet")
            self._maybe_raise()
            errors: List[FleetError] = []
            usable: List[FleetOverride] = []
            seen_ice: Set[Tuple[str, str, str]] = set()
            for ov in sorted(overrides, key=lambda o: (o.price, o.instance_type, o.zone)):
                pool = (ov.capacity_type, ov.instance_type, ov.zone)
                if pool in self.insufficient_capacity_pools:
                    if pool not in seen_ice:
                        errors.append(FleetError(ov, ICE_CODE))
                        seen_ice.add(pool)
                    continue
                usable.append(ov)
            instances = []
            if usable:
                ov = usable[0]
                for _ in range(count):
                    iid = f"i-{next(self._ids):017x}"
                    inst = CloudInstance(
                        id=iid, instance_type=ov.instance_type, zone=ov.zone,
                        capacity_type=ov.capacity_type, price=ov.price,
                        tags=dict(tags or {}), launched_at=self.clock(),
                        subnet_id=ov.subnet_id, image_id=ov.image_id,
                        launch_template=ov.launch_template)
                    self._instances[iid] = inst
                    instances.append(inst)
            return FleetResult(instances=instances, errors=errors)

    def describe_instances(self, ids: Optional[Sequence[str]] = None,
                           tag_filter: Optional[Dict[str, str]] = None,
                           include_terminated: bool = False) -> List[CloudInstance]:
        with self._lock:
            self._count("describe_instances")
            self._maybe_raise()
            out = []
            for inst in self._instances.values():
                if ids is not None and inst.id not in ids:
                    continue
                if not include_terminated and inst.state != "running":
                    continue
                if tag_filter and any(inst.tags.get(k) != v for k, v in tag_filter.items()):
                    continue
                out.append(inst)
            return out

    def get_instance(self, iid: str) -> CloudInstance:
        with self._lock:
            self._count("get_instance")
            inst = self._instances.get(iid)
            if inst is None or inst.state != "running":
                raise CloudError("InstanceNotFound", iid)
            return inst

    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        with self._lock:
            self._count("terminate_instances")
            self._maybe_raise()
            done = []
            for iid in ids:
                inst = self._instances.get(iid)
                if inst is not None and inst.state == "running":
                    inst.state = "terminated"
                    done.append(iid)
            return done

    def describe_subnets(self) -> List["SubnetInfo"]:
        with self._lock:
            self._count("describe_subnets")
            self._maybe_raise()
            return list(self.subnets)

    def describe_security_groups(self) -> List["SecurityGroupInfo"]:
        with self._lock:
            self._count("describe_security_groups")
            self._maybe_raise()
            return list(self.security_groups)

    def describe_images(self, ids: Optional[Sequence[str]] = None) -> List["ImageInfo"]:
        with self._lock:
            self._count("describe_images")
            self._maybe_raise()
            if ids is None:
                return list(self.images)
            want = set(ids)
            return [i for i in self.images if i.id in want]

    def create_launch_template(self, lt: "LaunchTemplateInfo") -> "LaunchTemplateInfo":
        with self._lock:
            self._count("create_launch_template")
            self._maybe_raise()
            if lt.name in self.launch_templates:
                raise CloudError("InvalidLaunchTemplateName.AlreadyExistsException",
                                 lt.name)
            self.launch_templates[lt.name] = lt
            return lt

    def describe_launch_templates(self, tag_filter: Optional[Dict[str, str]] = None
                                  ) -> List["LaunchTemplateInfo"]:
        with self._lock:
            self._count("describe_launch_templates")
            self._maybe_raise()
            out = []
            for lt in self.launch_templates.values():
                if tag_filter and any(lt.tags.get(k) != v
                                      for k, v in tag_filter.items()):
                    continue
                out.append(lt)
            return out

    def delete_launch_template(self, name: str) -> None:
        with self._lock:
            self._count("delete_launch_template")
            self._maybe_raise()
            if name not in self.launch_templates:
                raise CloudError("InvalidLaunchTemplateId.NotFound", name)
            del self.launch_templates[name]

    def describe_spot_price_history(self) -> Dict[Tuple[str, str], float]:
        """(type, zone) → latest spot price
        (/root/reference/pkg/providers/pricing/pricing.go:308+)."""
        with self._lock:
            self._count("describe_spot_price_history")
            self._maybe_raise()
            return dict(self.spot_prices)

    def create_tags(self, iid: str, tags: Dict[str, str]) -> None:
        with self._lock:
            self._count("create_tags")
            self._maybe_raise()
            inst = self._instances.get(iid)
            if inst is None:
                raise CloudError("InstanceNotFound", iid)
            inst.tags.update(tags)

    # ---- chaos helpers ----
    def _publish(self, kind: str, ids, state: str = ""):
        if self.queue is not None:
            from .queue import make_event_body
            self.queue.send(make_event_body(kind, ids, state=state,
                                            ts=self.clock()))

    def interrupt(self, iid: str) -> CloudInstance:
        """Spot-interrupt an instance. With a queue attached this publishes
        the 2-minute warning and leaves the capacity up for the controller
        to drain; without one there is nobody to warn, so the capacity is
        reclaimed immediately (pre-queue behavior)."""
        with self._lock:
            inst = self._instances.get(iid)
            if inst is None:
                raise CloudError("InstanceNotFound", iid)
            if self.queue is None:
                inst.state = "terminated"
                return inst
        from .queue import SPOT_INTERRUPTION
        self._publish(SPOT_INTERRUPTION, [iid])
        return inst

    def reclaim(self, iid: str) -> None:
        """The interruption deadline passed: capacity is pulled and a
        state-change event fires."""
        with self._lock:
            inst = self._instances.get(iid)
            if inst is not None:
                inst.state = "terminated"
        from .queue import STATE_CHANGE
        self._publish(STATE_CHANGE, [iid], state="terminated")

    def running(self) -> List[CloudInstance]:
        return self.describe_instances()
