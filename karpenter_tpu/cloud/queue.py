"""Interruption event queue — the SQS/EventBridge substrate.

Behavior-port of the reference's queue provider and message model
(/root/reference/pkg/providers/sqs/sqs.go:52-72 — long-poll receive capped
at 10, explicit delete; message kinds under
/root/reference/pkg/controllers/interruption/messages/{spotinterruption,
rebalancerecommendation,scheduledchange,statechange}/model.go).

The fake cloud publishes events here when instances are interrupted or
change state, so the interruption controller's input looks exactly like the
EventBridge→SQS pipeline the reference consumes.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

# Message kinds (the parser registry's output domain).
SPOT_INTERRUPTION = "spot_interruption"
REBALANCE_RECOMMENDATION = "rebalance_recommendation"
SCHEDULED_CHANGE = "scheduled_change"
STATE_CHANGE = "state_change"
NOOP = "noop"

MAX_RECEIVE = 10  # reference long-poll batch cap (sqs.go:52-72)

_msg_ids = itertools.count(1)


@dataclass
class Message:
    """One queue message: raw EventBridge-style JSON body + receipt handle."""
    body: str
    id: str = field(default_factory=lambda: f"msg-{next(_msg_ids):08d}")
    receipt: str = ""
    sent_at: float = 0.0

    def __post_init__(self):
        if not self.receipt:
            self.receipt = f"rcpt-{self.id}"


@dataclass
class ParsedEvent:
    kind: str
    instance_ids: List[str]
    start_time: float = 0.0
    detail: Dict = field(default_factory=dict)


def make_event_body(kind: str, instance_ids: Sequence[str],
                    state: str = "", ts: float = 0.0) -> str:
    """Compose an EventBridge-style body for `kind` (the shapes the
    reference's per-kind models parse)."""
    source, detail_type, detail = "cloud.compute", "", {}
    ids = list(instance_ids)
    if kind == SPOT_INTERRUPTION:
        detail_type = "Spot Instance Interruption Warning"
        detail = {"instance-id": ids[0], "instance-action": "terminate"}
    elif kind == REBALANCE_RECOMMENDATION:
        detail_type = "Instance Rebalance Recommendation"
        detail = {"instance-id": ids[0]}
    elif kind == SCHEDULED_CHANGE:
        source = "cloud.health"
        detail_type = "Scheduled Change"
        detail = {"affected-entities": [{"entity-value": i} for i in ids]}
    elif kind == STATE_CHANGE:
        detail_type = "Instance State-change Notification"
        detail = {"instance-id": ids[0], "state": state or "terminated"}
    else:
        detail_type = "Unknown"
    return json.dumps({"source": source, "detail-type": detail_type,
                       "detail": detail, "time": ts})


def parse_event(body: str) -> ParsedEvent:
    """Parser registry: detail-type → kind → instance ids
    (/root/reference/pkg/controllers/interruption/parser.go:54-80; unknown
    events become explicit noops, not errors)."""
    try:
        doc = json.loads(body)
    except (ValueError, TypeError):
        return ParsedEvent(kind=NOOP, instance_ids=[])
    detail_type = doc.get("detail-type", "")
    detail = doc.get("detail", {}) or {}
    ts = doc.get("time", 0.0) or 0.0
    if detail_type == "Spot Instance Interruption Warning":
        return ParsedEvent(SPOT_INTERRUPTION, [detail.get("instance-id", "")],
                           ts, detail)
    if detail_type == "Instance Rebalance Recommendation":
        return ParsedEvent(REBALANCE_RECOMMENDATION,
                           [detail.get("instance-id", "")], ts, detail)
    if detail_type == "Scheduled Change":
        ids = [e.get("entity-value", "")
               for e in detail.get("affected-entities", [])]
        return ParsedEvent(SCHEDULED_CHANGE, [i for i in ids if i], ts, detail)
    if detail_type == "Instance State-change Notification":
        return ParsedEvent(STATE_CHANGE, [detail.get("instance-id", "")],
                           ts, detail)
    return ParsedEvent(NOOP, [], ts, detail)


class FakeQueue:
    """In-memory interruption queue with SQS visibility semantics: received
    messages stay in flight until deleted; undeleted messages reappear."""

    def __init__(self, clock: Callable[[], float] = time.time):
        from ..analysis.lockorder import named_lock
        self.clock = clock
        self._lock = named_lock("queue")
        self._messages: List[Message] = []      # guarded-by: _lock
        self._inflight: Dict[str, Message] = {}  # guarded-by: _lock
        self.sent_count = 0                     # guarded-by: _lock

    def send(self, body: str) -> Message:
        msg = Message(body=body, sent_at=self.clock())
        with self._lock:
            self._messages.append(msg)
            self.sent_count += 1
        return msg

    def receive(self, max_messages: int = MAX_RECEIVE) -> List[Message]:
        with self._lock:
            batch = self._messages[:max_messages]
            self._messages = self._messages[len(batch):]
            for m in batch:
                self._inflight[m.receipt] = m
            return batch

    def delete(self, receipt: str) -> bool:
        with self._lock:
            return self._inflight.pop(receipt, None) is not None

    def release_inflight(self):
        """Visibility timeout lapse: undeleted messages become receivable."""
        with self._lock:
            self._messages = list(self._inflight.values()) + self._messages
            self._inflight.clear()

    def __len__(self):
        with self._lock:
            return len(self._messages)

    # ---- warm restart (state/snapshot.py) ----
    def snapshot_state(self) -> Dict:
        with self._lock:
            return {"messages": list(self._messages),
                    "inflight": dict(self._inflight),
                    "sent_count": self.sent_count}

    def restore_state(self, data: Dict) -> None:
        with self._lock:
            self._messages = list(data["messages"])
            self._inflight = dict(data["inflight"])
            self.sent_count = int(data["sent_count"])
