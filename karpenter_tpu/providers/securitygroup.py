"""Security-group provider: discovery by id/name/tag selector, TTL-cached
(/root/reference/pkg/providers/securitygroup/securitygroup.go:54-76)."""

from __future__ import annotations

from typing import List

from ..api.objects import NodeClass
from ..cloud.cache import TTLCache
from ..cloud.fake import SecurityGroupInfo
from . import matches_selector

SECURITY_GROUP_CACHE_TTL = 60.0


class SecurityGroupProvider:
    def __init__(self, cloud, clock=None):
        self.cloud = cloud
        self._cache = TTLCache(SECURITY_GROUP_CACHE_TTL,
                               **({"clock": clock} if clock else {}))

    def list(self, nodeclass: NodeClass) -> List[SecurityGroupInfo]:
        if not nodeclass.security_group_selector:
            return []  # reference requires an explicit selector
        key = tuple(sorted(nodeclass.security_group_selector.items()))
        cached = self._cache.get(key)
        if cached is not None:
            return list(cached)
        groups = [g for g in self.cloud.describe_security_groups()
                  if matches_selector(g.id, g.tags,
                                      nodeclass.security_group_selector,
                                      obj_name=g.name)]
        self._cache.set(key, groups)
        return list(groups)

    def reset_cache(self):
        self._cache.flush()
