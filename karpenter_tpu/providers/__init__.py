"""L2 providers: the capacity-provider data plane.

Each module mirrors one package of the reference's pkg/providers/ tree,
re-expressed over the fake cloud substrate:

  pricing          on-demand/spot price store + refresh controller
  subnet           placement-target discovery w/ in-flight IP accounting
  securitygroup    firewall-group discovery
  instanceprofile  identity-profile lifecycle
  version          control-plane version cache
  imagefamily      image resolution + per-family bootstrap userdata
  launchtemplate   launch-template ensure/cache/invalidate
"""

from typing import Dict


def matches_selector(obj_id: str, obj_tags: Dict[str, str],
                     selector: Dict[str, str], obj_name: str = "") -> bool:
    """Selector-term semantics (AND within a term): special keys `id` and
    `name` match identity, everything else matches tags; `"*"` is a tag-exists
    wildcard (/root/reference/pkg/apis/v1beta1/ec2nodeclass.go selector terms)."""
    for k, v in selector.items():
        if k == "id":
            if obj_id != v:
                return False
        elif k == "name":
            if obj_name != v:
                return False
        elif v == "*":
            if k not in obj_tags:
                return False
        elif obj_tags.get(k) != v:
            return False
    return True
