"""Control-plane version provider, TTL-cached
(/root/reference/pkg/providers/version/version.go:56)."""

from __future__ import annotations

from ..cloud.cache import TTLCache
from ..cloud.services import FakeControlPlane

VERSION_CACHE_TTL = 15 * 60.0
_KEY = "version"


class VersionProvider:
    def __init__(self, control_plane: FakeControlPlane, clock=None):
        self.control_plane = control_plane
        self._cache = TTLCache(VERSION_CACHE_TTL, **({"clock": clock} if clock else {}))

    def get(self) -> str:
        v = self._cache.get(_KEY)
        if v is None:
            v = self.control_plane.server_version()
            self._cache.set(_KEY, v)
        return v
