"""Image-family resolver + per-family bootstrap userdata.

Re-implements /root/reference/pkg/providers/amifamily/:
  * image resolution — explicit selector terms, else the family's published
    parameter-store path for the control-plane version
    (`Provider.Get` ami.go:116-136, SSM paths in al2.go/bottlerocket.go);
  * newest-image-per-architecture mapping of images → compatible instance
    types (`MapToInstanceTypes` ami.go:92);
  * `Resolver.resolve` — group a launch's instance types by image and
    produce per-group LaunchSpecs with generated bootstrap userdata
    (resolver.go:118-177);
  * bootstrap generators per family: the `script` family merges custom
    userdata as MIME multipart ahead of the bootstrap script
    (bootstrap/eksbootstrap.go:40-123), the `config` family merges TOML-style
    key=value settings (bottlerocket.go), `custom` passes userdata through
    untouched (custom.go).
"""

from __future__ import annotations

import email
import json
from dataclasses import dataclass, field
from email.mime.multipart import MIMEMultipart
from email.mime.text import MIMEText
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import labels as wk
from ..api.objects import KubeletConfiguration, NodeClass
from ..catalog.instancetype import InstanceType
from ..cloud.fake import CloudError, ImageInfo
from . import matches_selector
from .version import VersionProvider

FAMILIES = ("standard", "config", "custom")
# published parameter paths per (family, arch) — SSM path analog
# (al2.go: /aws/service/eks/optimized-ami/$version/amazon-linux-2/...)
PARAM_PATH = "/karpenter-tpu/images/{family}/{version}/{arch}/latest"


@dataclass
class LaunchSpec:
    """One resolved (image × userdata × instance-type-group) launch shape —
    the reference's amifamily.LaunchTemplate options (resolver.go:118-177)."""
    image: ImageInfo
    user_data: str
    instance_types: List[InstanceType]
    security_group_ids: Tuple[str, ...] = ()
    instance_profile: str = ""
    block_device_gib: int = 20
    block_device_mappings: tuple = ()
    metadata_options: tuple = ()         # sorted (key, value) pairs
    detailed_monitoring: bool = False
    instance_store_policy: str = ""
    associate_public_ip: Optional[bool] = None
    tags: Dict[str, str] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Bootstrap userdata generation (bootstrap/ package analog)
# ---------------------------------------------------------------------------

def _resolve_dns(kubelet: Optional[KubeletConfiguration],
                 cluster_dns: str) -> str:
    """Pool kubelet config wins; else the cluster's discovered kube-dns IP
    (v4 or v6 — IPv6 clusters bootstrap with their v6 service address).
    The ONE copy of the precedence rule for every userdata family."""
    if kubelet is not None and kubelet.cluster_dns:
        return kubelet.cluster_dns[0]
    return cluster_dns


def _bootstrap_script(cluster_name: str, endpoint: str, labels: Dict[str, str],
                      taints: Sequence, max_pods: Optional[int],
                      dns: str = "") -> str:
    """The family's node-join script (eksbootstrap.go bootstrap flags)."""
    args = [f"--cluster {cluster_name}", f"--endpoint {endpoint}"]
    if labels:
        kv = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        args.append(f"--node-labels {kv}")
    if taints:
        ts = ",".join(f"{t.key}={t.value}:{t.effect}" for t in taints)
        args.append(f"--register-with-taints {ts}")
    if max_pods is not None:
        args.append(f"--max-pods {max_pods}")
    if dns:
        args.append(f"--cluster-dns {dns}")
    joined = " \\\n  ".join(args)
    return f"#!/bin/bash\nset -euo pipefail\n/opt/node/bootstrap.sh \\\n  {joined}\n"


def merge_mime(custom: str, bootstrap: str) -> str:
    """MIME-multipart merge: custom part(s) first, bootstrap last, so user
    hooks run before node join (eksbootstrap.go:40-123 mergeCustomUserData)."""
    mm = MIMEMultipart("mixed", boundary="//KARPENTER-TPU//")
    parts: List[Tuple[str, str]] = []
    if custom.strip():
        head = "\n".join(custom.splitlines()[:3])
        if "MIME-Version" in head or "Content-Type: multipart" in head:
            msg = email.message_from_string(custom)
            for part in msg.walk():
                if part.get_content_maintype() == "multipart":
                    continue
                parts.append((part.get_content_type(),
                              part.get_payload(decode=False)))
        else:
            parts.append(("text/x-shellscript", custom))
    parts.append(("text/x-shellscript", bootstrap))
    for ctype, payload in parts:
        sub = MIMEText(payload, ctype.split("/", 1)[1])  # 7bit, human-readable
        sub.replace_header("Content-Type", f'{ctype}; charset="us-ascii"')
        mm.attach(sub)
    return mm.as_string()


def merge_config(custom: str, settings: Dict[str, str]) -> str:
    """TOML-style `key = "value"` merge where generated settings win on
    conflict (bottlerocket.go userdata merge)."""
    out: Dict[str, str] = {}
    for line in custom.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        k, v = line.split("=", 1)
        out[k.strip()] = v.strip().strip('"')
    out.update(settings)
    return "\n".join(f'{k} = "{v}"' for k, v in sorted(out.items())) + "\n"


def generate_user_data(family: str, cluster_name: str, endpoint: str,
                       custom: str = "", labels: Optional[Dict[str, str]] = None,
                       taints: Sequence = (), kubelet=None,
                       max_pods: Optional[int] = None,
                       cluster_dns: str = "") -> str:
    if family == "custom":
        return custom  # verbatim; operator owns the whole bootstrap (custom.go)
    if family == "config":
        settings = {"cluster.name": cluster_name, "cluster.endpoint": endpoint}
        for k, v in sorted((labels or {}).items()):
            settings[f"node.labels.{k}"] = v
        for t in taints:
            settings[f"node.taints.{t.key}"] = f"{t.value}:{t.effect}"
        if max_pods is not None:
            settings["node.max-pods"] = str(max_pods)
        dns = _resolve_dns(kubelet, cluster_dns)
        if dns:
            settings["node.cluster-dns-ip"] = dns
        return merge_config(custom, settings)
    script = _bootstrap_script(cluster_name, endpoint, labels or {}, taints,
                               max_pods, _resolve_dns(kubelet, cluster_dns))
    return merge_mime(custom, script)


# ---------------------------------------------------------------------------
# Image resolution
# ---------------------------------------------------------------------------

class ImageProvider:
    """Resolves a nodeclass to concrete images (ami.go Provider.Get:116-136),
    TTL-cached per (family, version, selector) so per-launch resolution stays
    off the I/O path (the reference caches AMI resolution the same way)."""

    IMAGE_CACHE_TTL = 60.0

    def __init__(self, cloud, params, version_provider: VersionProvider,
                 clock=None):
        self.cloud = cloud
        self.params = params
        self.version_provider = version_provider
        from ..cloud.cache import TTLCache
        self._cache = TTLCache(self.IMAGE_CACHE_TTL,
                               **({"clock": clock} if clock else {}))

    def get(self, nodeclass: NodeClass, archs: Sequence[str] = ("amd64", "arm64")
            ) -> List[ImageInfo]:
        # the control-plane version is part of the published path, so it is
        # part of the key; empty resolutions are NOT cached (a transient
        # failure must not block launches for a whole TTL)
        key = (nodeclass.image_family, self.version_provider.get(),
               tuple(archs), tuple(sorted(nodeclass.image_selector.items())))
        cached = self._cache.get(key)
        if cached is not None:
            return list(cached)
        out = self._resolve(nodeclass, archs)
        if out:
            self._cache.set(key, out)
        return list(out)

    def reset_cache(self):
        self._cache.flush()

    def _resolve(self, nodeclass: NodeClass, archs: Sequence[str]
                 ) -> List[ImageInfo]:
        if nodeclass.image_selector:
            images = [i for i in self.cloud.describe_images()
                      if matches_selector(i.id, i.tags, nodeclass.image_selector,
                                          obj_name=i.name) and not i.deprecated]
            return sorted(images, key=lambda i: (-i.creation_ts, i.id))
        version = self.version_provider.get()
        out = []
        for arch in archs:
            path = PARAM_PATH.format(family=nodeclass.image_family,
                                     version=version, arch=arch)
            try:
                image_id = self.params.get_parameter(path)
            except CloudError:
                continue
            found = self.cloud.describe_images(ids=[image_id])
            out.extend(i for i in found if not i.deprecated)
        return sorted(out, key=lambda i: (-i.creation_ts, i.id))


def map_to_instance_types(images: Sequence[ImageInfo],
                          instance_types: Sequence[InstanceType]
                          ) -> Dict[str, List[InstanceType]]:
    """image id → compatible instance types; newest image per architecture
    wins (ami.go MapToInstanceTypes:92)."""
    newest_per_arch: Dict[str, ImageInfo] = {}
    for img in images:  # images arrive newest-first
        newest_per_arch.setdefault(img.architecture, img)
    out: Dict[str, List[InstanceType]] = {}
    for it in instance_types:
        arch_req = it.requirements.get(wk.ARCH)
        for arch, img in newest_per_arch.items():
            if arch_req is None or arch_req.has(arch):
                out.setdefault(img.id, []).append(it)
                break
    return out


class Resolver:
    """amifamily.Resolver (resolver.go:118-177): nodeclass + claim context →
    LaunchSpecs grouped by image."""

    def __init__(self, image_provider: ImageProvider, cluster_name: str,
                 endpoint: str, cluster_dns: str = ""):
        self.image_provider = image_provider
        self.cluster_name = cluster_name
        self.endpoint = endpoint
        # discovered kube-dns service IP (v4 or v6) — the bootstrap default
        # when a pool's kubelet config doesn't pin its own cluster-dns
        # (reference kubeDNSIP discovery, operator.go:248-261)
        self.cluster_dns = cluster_dns

    def resolve(self, nodeclass: NodeClass, instance_types: Sequence[InstanceType],
                labels: Optional[Dict[str, str]] = None, taints: Sequence = (),
                kubelet=None, max_pods: Optional[int] = None,
                security_group_ids: Tuple[str, ...] = (),
                instance_profile: str = "") -> List[LaunchSpec]:
        images = self.image_provider.get(nodeclass)
        if not images:
            raise CloudError("ImageNotFound",
                             f"no images for family {nodeclass.image_family}")
        by_image = map_to_instance_types(images, instance_types)
        img_index = {i.id: i for i in images}
        specs = []
        for image_id, its in by_image.items():
            user_data = generate_user_data(
                nodeclass.image_family, self.cluster_name, self.endpoint,
                custom=nodeclass.user_data, labels=labels, taints=taints,
                kubelet=kubelet, max_pods=max_pods,
                cluster_dns=self.cluster_dns)
            specs.append(LaunchSpec(
                image=img_index[image_id], user_data=user_data,
                instance_types=its, security_group_ids=security_group_ids,
                instance_profile=instance_profile,
                block_device_gib=nodeclass.block_device_gib,
                block_device_mappings=tuple(
                    json.dumps(m, sort_keys=True)
                    for m in nodeclass.block_device_mappings),
                metadata_options=tuple(
                    sorted(nodeclass.metadata_options.items())),
                detailed_monitoring=nodeclass.detailed_monitoring,
                instance_store_policy=nodeclass.instance_store_policy,
                associate_public_ip=nodeclass.associate_public_ip,
                tags=dict(nodeclass.tags)))
        return specs
