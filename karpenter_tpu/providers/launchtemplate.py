"""Launch-template provider: ensure/cache/invalidate/hydrate.

Re-implements /root/reference/pkg/providers/launchtemplate/launchtemplate.go:
  * `ensure_all` — resolve the launch into per-image LaunchSpecs and make
    sure a stored launch template exists for each, returning
    (template, instance-types) pairs for the fleet call (EnsureAll:106-135);
  * templates are content-addressed: the name is a hash of every field that
    affects the boot, so config drift naturally creates new templates
    (ensureLaunchTemplate:200-286);
  * a TTL cache avoids re-describing; `invalidate` drops an entry when the
    cloud 404s it (Invalidate:137-146); `hydrate_cache` pre-warms from the
    cloud's stored templates at startup (hydrateCache:336).
"""

from __future__ import annotations

import hashlib
import json
import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.objects import NodeClass
from ..catalog.instancetype import InstanceType
from ..cloud.cache import TTLCache
from ..cloud.fake import CloudError, LaunchTemplateInfo
from .imagefamily import LaunchSpec, Resolver

log = logging.getLogger("karpenter_tpu.launchtemplate")

LAUNCH_TEMPLATE_CACHE_TTL = 10 * 60.0
NAME_PREFIX = "karpenter-tpu/"


@dataclass
class ResolvedTemplate:
    template: LaunchTemplateInfo
    instance_types: List[InstanceType]


def template_name(spec: LaunchSpec, cluster_name: str,
                  nodeclass_name: str = "") -> str:
    """Content-addressed template name — hash of every boot-affecting field
    (launchtemplate.go launchTemplateName).  The owning nodeclass is part of
    the identity so per-nodeclass GC (delete_all) can never collect a
    template another nodeclass still references."""
    payload = json.dumps({
        "image": spec.image.id,
        "user_data": spec.user_data,
        "sgs": sorted(spec.security_group_ids),
        "profile": spec.instance_profile,
        "bdm": spec.block_device_gib,
        "bdms": list(spec.block_device_mappings),
        "imds": list(spec.metadata_options),
        "monitoring": spec.detailed_monitoring,
        "store_policy": spec.instance_store_policy,
        "public_ip": spec.associate_public_ip,
        "tags": sorted(spec.tags.items()),
        "cluster": cluster_name,
        "nodeclass": nodeclass_name,
    }, sort_keys=True)
    return NAME_PREFIX + hashlib.sha256(payload.encode()).hexdigest()[:16]


class LaunchTemplateProvider:
    def __init__(self, cloud, resolver: Resolver, cluster_name: str, clock=None):
        self.cloud = cloud
        self.resolver = resolver
        self.cluster_name = cluster_name
        self._cache = TTLCache(LAUNCH_TEMPLATE_CACHE_TTL,
                               **({"clock": clock} if clock else {}))

    def ensure_all(self, nodeclass: NodeClass,
                   instance_types: Sequence[InstanceType],
                   labels: Optional[Dict[str, str]] = None, taints: Sequence = (),
                   kubelet=None, max_pods: Optional[int] = None,
                   security_group_ids: Tuple[str, ...] = (),
                   instance_profile: str = "") -> List[ResolvedTemplate]:
        specs = self.resolver.resolve(
            nodeclass, instance_types, labels=labels, taints=taints,
            kubelet=kubelet, max_pods=max_pods,
            security_group_ids=security_group_ids,
            instance_profile=instance_profile)
        return [ResolvedTemplate(self._ensure(spec, nodeclass), spec.instance_types)
                for spec in specs]

    def _ensure(self, spec: LaunchSpec, nodeclass: NodeClass) -> LaunchTemplateInfo:
        name = template_name(spec, self.cluster_name, nodeclass.name)
        cached = self._cache.get(name)
        if cached is not None:
            return cached
        lt = LaunchTemplateInfo(
            name=name, image_id=spec.image.id, user_data=spec.user_data,
            security_group_ids=tuple(spec.security_group_ids),
            block_device_gib=spec.block_device_gib,
            block_device_mappings=tuple(spec.block_device_mappings),
            metadata_options=tuple(spec.metadata_options),
            detailed_monitoring=spec.detailed_monitoring,
            instance_store_policy=spec.instance_store_policy,
            associate_public_ip=spec.associate_public_ip,
            instance_profile=spec.instance_profile,
            tags={**spec.tags, "karpenter.sh/cluster": self.cluster_name,
                  "karpenter.sh/nodeclass": nodeclass.name})
        try:
            self.cloud.create_launch_template(lt)
        except CloudError as e:
            from ..cloud.errors import is_already_exists
            if not is_already_exists(e):   # create raced: template is there
                raise
            lt = self.cloud.launch_templates[name]
        self._cache.set(name, lt)
        return lt

    def invalidate(self, name: str) -> None:
        """Drop a template the cloud no longer knows — the launch path
        retries with a fresh create (Invalidate:137-146)."""
        self._cache.delete(name)

    def hydrate_cache(self) -> int:
        """Pre-warm from stored templates tagged to this cluster
        (hydrateCache:336)."""
        n = 0
        for lt in self.cloud.describe_launch_templates(
                tag_filter={"karpenter.sh/cluster": self.cluster_name}):
            self._cache.set(lt.name, lt)
            n += 1
        return n

    def delete_all(self, nodeclass: NodeClass) -> int:
        """GC this nodeclass's stored templates (nodeclass finalize path);
        other nodeclasses' templates in the same cluster are untouched."""
        n = 0
        for lt in self.cloud.describe_launch_templates(
                tag_filter={"karpenter.sh/cluster": self.cluster_name,
                            "karpenter.sh/nodeclass": nodeclass.name}):
            try:
                self.cloud.delete_launch_template(lt.name)
                self._cache.delete(lt.name)
                n += 1
            except CloudError:
                pass
        return n
