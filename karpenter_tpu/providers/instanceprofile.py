"""Instance-profile provider: identity-profile lifecycle from `spec.role`
(/root/reference/pkg/providers/instanceprofile/instanceprofile.go:49-131)."""

from __future__ import annotations

import hashlib
from typing import Dict

from ..api.objects import NodeClass
from ..cloud.cache import TTLCache
from ..cloud.fake import CloudError
from ..cloud.services import FakeIAM

PROFILE_CACHE_TTL = 15 * 60.0


class InstanceProfileProvider:
    def __init__(self, iam: FakeIAM, cluster_name: str, region: str = "local",
                 clock=None):
        self.iam = iam
        self.cluster_name = cluster_name
        self.region = region
        self._cache = TTLCache(PROFILE_CACHE_TTL, **({"clock": clock} if clock else {}))

    def profile_name(self, nodeclass: NodeClass) -> str:
        """Deterministic name from cluster + nodeclass
        (instanceprofile.go GetProfileName:131)."""
        h = hashlib.sha256(f"{self.region}{nodeclass.name}".encode()).hexdigest()[:20]
        return f"{self.cluster_name}_{h}"

    def create(self, nodeclass: NodeClass, tags: Dict[str, str] = None) -> str:
        """Idempotently ensure the profile exists with the nodeclass role
        attached (instanceprofile.go Create:49-101)."""
        name = self.profile_name(nodeclass)
        if self._cache.get(name):
            return name
        try:
            profile = self.iam.get_instance_profile(name)
        except CloudError as e:
            if e.code != "NoSuchEntity":
                raise
            self.iam.create_instance_profile(name, tags or {})
            profile = self.iam.get_instance_profile(name)
        attached = profile.get("_roles", "")
        if attached and attached != nodeclass.role:
            self.iam.remove_role_from_instance_profile(name, attached)
            attached = ""
        if not attached and nodeclass.role:
            self.iam.add_role_to_instance_profile(name, nodeclass.role)
        self._cache.set(name, True)
        return name

    def delete(self, nodeclass: NodeClass) -> None:
        name = self.profile_name(nodeclass)
        try:
            profile = self.iam.get_instance_profile(name)
            if profile.get("_roles"):
                self.iam.remove_role_from_instance_profile(name, profile["_roles"])
            self.iam.delete_instance_profile(name)
        except CloudError as e:
            if e.code != "NoSuchEntity":
                raise
        self._cache.delete(name)
