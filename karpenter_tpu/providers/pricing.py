"""Pricing provider: on-demand + spot price store with static fallback and a
12h refresh controller.

Re-implements /root/reference/pkg/providers/pricing/pricing.go:
  * `on_demand_price` / `spot_price` lookups (:118-143);
  * `update_on_demand_pricing` from the price-list API (:145) and
    `update_spot_pricing` from spot price history (:308) — each keeps the
    previous table on API failure;
  * static fallback tables baked in at construction
    (zz_generated.pricing_aws*.go analog: here derived from the generated
    catalog's list prices);
  * a controller requeueing every 12h
    (/root/reference/pkg/providers/pricing/controller.go:40).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

from ..catalog.instancetype import InstanceType
from ..cloud.fake import CloudError
from ..utils import metrics
from ..utils.events import ChangeMonitor

log = logging.getLogger("karpenter_tpu.pricing")

PRICING_REFRESH_INTERVAL = 12 * 3600.0  # controller.go:40
SPOT_DISCOUNT_FALLBACK = 0.30  # spot ≈ 30% of OD when no history exists


def static_price_table(catalog: Sequence[InstanceType]) -> Dict[str, float]:
    """Fallback table: cheapest on-demand offering per type from the
    generated catalog (the reference bakes scraped price tables in)."""
    out: Dict[str, float] = {}
    for it in catalog:
        od = [o.price for o in it.offerings if o.capacity_type == "on-demand"]
        if od:
            out[it.name] = min(od)
    return out


class PricingProvider:
    def __init__(self, pricing_api=None, cloud=None,
                 static_fallback: Optional[Dict[str, float]] = None,
                 clock: Callable[[], float] = time.time):
        self.pricing_api = pricing_api
        self.cloud = cloud
        self.clock = clock
        self._lock = threading.Lock()
        self._od: Dict[str, float] = dict(static_fallback or {})
        self._static = dict(static_fallback or {})
        self._spot: Dict[Tuple[str, str], float] = {}
        self._od_updated: float = 0.0
        self._spot_updated: float = 0.0
        # per-table refresh counters: liveness is PER TABLE, so an OD-only
        # refresh never degrades catalog spot prices to the synthetic
        # discount (and vice versa); the pair keys catalog memoization
        self._od_seq = 0
        self._spot_seq = 0
        self._monitor = ChangeMonitor()

    @property
    def seq_num(self) -> Tuple[int, int]:
        with self._lock:
            return (self._od_seq, self._spot_seq)

    # ---- lookups (pricing.go:118-143) ----
    def on_demand_price(self, instance_type: str) -> Optional[float]:
        with self._lock:
            return self._od.get(instance_type)

    def spot_price(self, instance_type: str, zone: str) -> Optional[float]:
        """Zonal spot price; falls back to a discount off on-demand when no
        history exists (the reference initializes spot=OD until history
        arrives, pricing.go:136-143)."""
        with self._lock:
            p = self._spot.get((instance_type, zone))
            if p is not None:
                return p
            od = self._od.get(instance_type)
            return od * SPOT_DISCOUNT_FALLBACK if od is not None else None

    def instance_types(self) -> int:
        with self._lock:
            return len(self._od)

    # ---- refresh (pricing.go:145,308) ----
    def update_on_demand_pricing(self) -> bool:
        if self.pricing_api is None:
            return False
        try:
            prices = self.pricing_api.list_prices()
        except CloudError as e:
            log.warning("on-demand price refresh failed, keeping stale table: %s", e)
            return False
        if not prices:
            return False
        with self._lock:
            self._od = {**self._static, **prices}
            self._od_updated = self.clock()
            self._od_seq += 1
        if self._monitor.has_changed("od-prices", tuple(sorted(prices.items()))):
            log.info("refreshed %d on-demand prices", len(prices))
        gauge = metrics.instance_price_estimate()
        for itype, price in prices.items():
            gauge.set(price, {"instance_type": itype, "capacity_type": "on-demand",
                              "zone": ""})
        return True

    def update_spot_pricing(self) -> bool:
        if self.cloud is None:
            return False
        try:
            history = self.cloud.describe_spot_price_history()
        except CloudError as e:
            log.warning("spot price refresh failed, keeping stale table: %s", e)
            return False
        if not history:
            return False  # no data is not a refresh (matches the OD guard)
        with self._lock:
            self._spot.update(history)
            self._spot_updated = self.clock()
            self._spot_seq += 1
        gauge = metrics.instance_price_estimate()
        for (itype, zone), price in history.items():
            gauge.set(price, {"instance_type": itype, "capacity_type": "spot",
                              "zone": zone})
        return True

    def liveness_stale(self) -> bool:
        with self._lock:
            return self.clock() - max(self._od_updated, self._spot_updated) \
                > 2 * PRICING_REFRESH_INTERVAL


class PricingController:
    """Requeue-every-12h refresh loop (pricing/controller.go:40)."""

    def __init__(self, provider: PricingProvider,
                 interval: float = PRICING_REFRESH_INTERVAL,
                 clock: Callable[[], float] = time.time):
        self.provider = provider
        self.interval = interval
        self.clock = clock
        self._next_run = 0.0

    def reconcile(self) -> bool:
        """Refresh if due; returns whether a refresh ran."""
        now = self.clock()
        if now < self._next_run:
            return False
        self.provider.update_on_demand_pricing()
        self.provider.update_spot_pricing()
        self._next_run = now + self.interval
        return True
