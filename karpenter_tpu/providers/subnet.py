"""Subnet provider: placement-target discovery + in-flight IP accounting.

Re-implements /root/reference/pkg/providers/subnet/subnet.go:
  * `list(nodeclass)` — discovery by selector terms, TTL-cached (:59);
  * `zonal_subnets_for_launch` — per-zone pick of the subnet with the most
    free IPs, predicting the IP draw of the pending launch so parallel
    launches don't oversubscribe a zone (:110-147);
  * `update_inflight_ips` — refund/settle predictions from the fleet
    response (:149).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from ..api.objects import NodeClass
from ..cloud.cache import TTLCache
from ..cloud.fake import SubnetInfo
from . import matches_selector

SUBNET_CACHE_TTL = 60.0  # reference caches subnet describes ~1m


class SubnetProvider:
    def __init__(self, cloud, clock=None):
        self.cloud = cloud
        self._cache = TTLCache(SUBNET_CACHE_TTL, **({"clock": clock} if clock else {}))
        self._lock = threading.Lock()
        # subnet id → IPs predicted-consumed by launches still in flight
        self._inflight: Dict[str, int] = {}

    def list(self, nodeclass: NodeClass) -> List[SubnetInfo]:
        """Subnets matching the nodeclass selector (empty selector ∧ no zone
        filter == all), cached per selector."""
        key = (tuple(sorted(nodeclass.subnet_selector.items())),
               tuple(nodeclass.zone_selector))
        cached = self._cache.get(key)
        if cached is not None:
            return list(cached)
        subnets = [
            s for s in self.cloud.describe_subnets()
            if matches_selector(s.id, s.tags, nodeclass.subnet_selector)
            and (not nodeclass.zone_selector or s.zone in nodeclass.zone_selector)
        ]
        self._cache.set(key, subnets)
        return list(subnets)

    def zonal_subnets_for_launch(self, nodeclass: NodeClass,
                                 zones: Optional[Sequence[str]] = None,
                                 ips_per_launch: int = 1) -> Dict[str, SubnetInfo]:
        """zone → chosen subnet (most effective free IPs), charging the
        in-flight prediction so concurrent launches spread instead of all
        landing on one nearly-full subnet (subnet.go:110-147)."""
        with self._lock:
            out: Dict[str, SubnetInfo] = {}
            for s in self.list(nodeclass):
                if zones is not None and s.zone not in zones:
                    continue
                best = out.get(s.zone)
                if best is None or self._effective_free(s) > self._effective_free(best):
                    out[s.zone] = s
            for s in out.values():
                self._inflight[s.id] = self._inflight.get(s.id, 0) + ips_per_launch
            return out

    def _effective_free(self, s: SubnetInfo) -> int:
        return s.available_ip_count - self._inflight.get(s.id, 0)

    def update_inflight_ips(self, launched_subnet_ids: Sequence[str],
                            requested: Dict[str, SubnetInfo],
                            ips_per_launch: int = 1) -> None:
        """Settle predictions after the fleet response: refund every requested
        subnet the launch did NOT land in (subnet.go UpdateInflightIPs:149)."""
        with self._lock:
            landed = set(launched_subnet_ids)
            for s in requested.values():
                if s.id not in landed:
                    self._inflight[s.id] = max(
                        0, self._inflight.get(s.id, 0) - ips_per_launch)

    def inflight(self, subnet_id: str) -> int:
        with self._lock:
            return self._inflight.get(subnet_id, 0)

    def reset_cache(self):
        self._cache.flush()
        with self._lock:
            self._inflight.clear()
