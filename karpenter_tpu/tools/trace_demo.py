"""`make trace-demo`: end-to-end tracing walkthrough on the in-memory
substrate.

Builds an operator against a generated catalog, drives one provisioning
tick (including a deliberately unschedulable pod) and one disruption
reconcile through the controller manager, then fetches `/debug/traces`
over HTTP — the same JSON a production scrape would see — and
pretty-prints each trace tree with durations and annotations, plus the
stuck pod's provenance record from `/debug/pods/<name>`.

Runs with the FlightRecorder gate ON: after the reconciles it trips the
solver degradation ladder once, then fetches the incident index from
`/debug/incidents` and pretty-prints the newest forensic bundle — the
`make incident-smoke` walkthrough (docs/observability.md).
"""

from __future__ import annotations

import json
import sys
import urllib.request

from ..api import labels as wk
from ..api.objects import Pod
from ..api.resources import CPU, MEMORY, ResourceList
from ..catalog.generate import generate_catalog
from ..cloud.fake import ImageInfo, SecurityGroupInfo, SubnetInfo
from ..operator import ControllerManager, Operator, Options, build_controllers


def pod(name="", cpu_m=500, mem_mib=512, selector=None):
    return Pod(name=name,
               requests=ResourceList({CPU: cpu_m, MEMORY: mem_mib * 2**20}),
               node_selector=dict(selector or {}))


def render(span, depth=0, lines=None):
    lines = [] if lines is None else lines
    ann = " ".join(f"{k}={v}" for k, v in sorted(span["annotations"].items()))
    lines.append(f"{'  ' * depth}{span['name']:<{max(30 - 2 * depth, 1)}} "
                 f"{span['duration_ms']:9.2f}ms"
                 + (f"  [{ann}]" if ann else ""))
    for child in span["children"]:
        render(child, depth + 1, lines)
    return lines


def main() -> int:
    clock = [1000.0]
    opts = Options(batch_idle_duration=1.0, batch_max_duration=10.0)
    opts.feature_gates["FlightRecorder"] = True
    op = Operator(opts,
                  catalog=generate_catalog(20), clock=lambda: clock[0])
    op.cloud.subnets = [SubnetInfo("s-a", "zone-a", 100, {}),
                        SubnetInfo("s-b", "zone-b", 100, {})]
    op.cloud.security_groups = [SecurityGroupInfo("sg", "nodes", {})]
    op.cloud.images = [ImageInfo("img-1", "std", "amd64", 1.0)]
    op.params.parameters = {
        "/karpenter-tpu/images/standard/1.28/amd64/latest": "img-1"}
    mgr = ControllerManager(op, build_controllers(op), clock=lambda: clock[0])
    port = mgr.serve_endpoints(metrics_port=0)
    try:
        # one provisioning tick: 12 schedulable pods + one pinned to a zone
        # no offering serves (it gets a provenance record, not a node)
        pods = [pod(name=f"demo-{i}", cpu_m=300 + 137 * i) for i in range(12)]
        stuck = pod(name="stuck-pod", selector={wk.ZONE: "zone-nowhere"})
        op.cluster.add_pods(pods + [stuck])
        mgr.tick()                    # opens the batch window
        clock[0] += 1.1               # idle elapses
        mgr.tick()                    # provisions

        # underutilize every node (keep one pod each so emptiness can't
        # short-circuit the consolidation sweep), wait out node
        # stabilization, then run disruption on its next interval
        keep = set()
        for p in list(op.cluster.pods.values()):
            if p.node_name and p.node_name in keep:
                op.cluster.delete_pod(p)
            elif p.node_name:
                keep.add(p.node_name)
        clock[0] += 600
        mgr.tick()

        traces = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/traces", timeout=10).read())

        print(f"# /debug/traces — {len(traces['traces'])} trace(s), "
              "newest first\n")
        for t in reversed(traces["traces"]):   # oldest first reads better
            print("\n".join(render(t)))
            print()

        prov = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pods/stuck-pod",
            timeout=10).read())
        print("# /debug/pods/stuck-pod — decision provenance")
        print(f"  constraint: {prov['constraint']}"
              + (f" ({prov['dimension']})" if prov["dimension"] else ""))
        print(f"  message:    {prov['message']}")

        # ?span= prefix filter: only the disruption family of roots
        filtered = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/traces?span=disruption.",
            timeout=10).read())
        print(f"\n# /debug/traces?span=disruption. — "
              f"{len(filtered['traces'])} of {len(traces['traces'])} trace(s)")

        # trip the solver degradation ladder (a watchdog-style timeout
        # demotes immediately) so the flight recorder captures a bundle
        health = getattr(mgr.controllers["provisioning"], "health", None)
        if health is not None and mgr.flight is not None:
            health.report_failure("jax", reason="timeout")
            index = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/incidents",
                timeout=10).read())
            print(f"\n# /debug/incidents — {len(index['bundles'])} "
                  f"bundle(s), by kind {index['by_kind']}")
            newest = index["bundles"][-1]
            bundle = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/incidents/{newest['id']}",
                timeout=10).read())
            print(f"\n# /debug/incidents/{newest['id']} — newest bundle")
            print(f"  kind:    {bundle['kind']}  (detail: "
                  f"{json.dumps(bundle['detail'], sort_keys=True)})")
            print(f"  window:  [{bundle['window'][0]:.0f}, "
                  f"{bundle['window'][1]:.0f}]  "
                  f"ring entries: {bundle['ring_entries']}")
            changed = bundle["metrics"].get("changed", {})
            print(f"  metric deltas over the window: {len(changed)} series")
            for key in sorted(changed)[:8]:
                print(f"    {key:<58} {changed[key]:+g}")
            print(f"  traces captured: {len(bundle['traces'])}; health "
                  f"rungs: "
                  f"{sorted(bundle['health']['solver']['rungs'])}")
        return 0
    finally:
        mgr.stop()


if __name__ == "__main__":
    sys.exit(main())
