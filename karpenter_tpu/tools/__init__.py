"""Operational helper tools (`python -m karpenter_tpu.tools.trace_demo`)."""
