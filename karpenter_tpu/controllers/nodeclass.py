"""NodeClass controller: selector config → resolved status.

Re-implements /root/reference/pkg/controllers/nodeclass/controller.go:
  * `reconcile` (:73-99) — resolve subnets (sorted by free IPs, most first),
    security groups, images, and the instance profile into `.status`;
    compute the static hash annotation drift detection keys off
    (`utils/nodeclass.HashAnnotation` via cloudprovider.go:116);
  * `finalize` (:100-126) — deletion is blocked while NodeClaims still
    reference the class; once unreferenced, the instance profile and this
    cluster's launch templates are garbage-collected.
"""

from __future__ import annotations

import hashlib
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..api.objects import NodeClass
from ..state.cluster import Cluster

log = logging.getLogger("karpenter_tpu.nodeclass")

REQUEUE_INTERVAL = 5 * 60.0  # controller.go requeues ~5m


def static_hash(nodeclass: NodeClass) -> str:
    """Hash of the launch-affecting spec fields; a change means every node
    launched from the old spec is drifted (drift.go static drift)."""
    payload = json.dumps({
        "image_family": nodeclass.image_family,
        "image_selector": sorted(nodeclass.image_selector.items()),
        "subnet_selector": sorted(nodeclass.subnet_selector.items()),
        "security_group_selector": sorted(nodeclass.security_group_selector.items()),
        "zone_selector": sorted(nodeclass.zone_selector),
        "role": nodeclass.role,
        "user_data": nodeclass.user_data,
        "tags": sorted(nodeclass.tags.items()),
        "block_device_gib": nodeclass.block_device_gib,
        "block_device_mappings": nodeclass.block_device_mappings,
        "metadata_options": sorted(nodeclass.metadata_options.items()),
        "detailed_monitoring": nodeclass.detailed_monitoring,
        "instance_store_policy": nodeclass.instance_store_policy,
        "associate_public_ip": nodeclass.associate_public_ip,
    }, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


@dataclass
class NodeClassResult:
    resolved: bool = False
    requeue_after: float = REQUEUE_INTERVAL
    errors: List[str] = field(default_factory=list)


class NodeClassController:
    def __init__(self, subnets, security_groups, images, instance_profiles,
                 cluster: Optional[Cluster] = None,
                 clock: Callable[[], float] = time.time):
        self.subnets = subnets
        self.security_groups = security_groups
        self.images = images
        self.instance_profiles = instance_profiles
        self.cluster = cluster

    def reconcile(self, nodeclass: NodeClass) -> NodeClassResult:
        out = NodeClassResult()
        subnets = self.subnets.list(nodeclass)
        if not subnets:
            out.errors.append("no subnets resolved")
        # most free IPs first: the launch path prefers roomy subnets
        # (controller.go resolveSubnets sorts by available IPs)
        subnets = sorted(subnets, key=lambda s: (-s.available_ip_count, s.id))
        nodeclass.status_subnets = [s.id for s in subnets]
        nodeclass.status_zones = sorted({s.zone for s in subnets})

        groups = self.security_groups.list(nodeclass)
        if nodeclass.security_group_selector and not groups:
            out.errors.append("no security groups resolved")
        nodeclass.status_security_groups = sorted(g.id for g in groups)

        images = self.images.get(nodeclass)
        if not images:
            out.errors.append("no images resolved")
        nodeclass.status_images = [i.id for i in images]

        if nodeclass.role:
            nodeclass.status_instance_profile = \
                self.instance_profiles.create(nodeclass, tags=nodeclass.tags)

        nodeclass.hash_annotation = static_hash(nodeclass)
        out.resolved = not out.errors
        if out.errors:
            log.warning("nodeclass %s unresolved: %s", nodeclass.name, out.errors)
        return out

    def finalize(self, nodeclass: NodeClass,
                 launch_templates=None) -> bool:
        """Deletion path: refuse while any NodeClaim references the class;
        then GC the instance profile (+ this cluster's launch templates when
        a provider is passed). Returns whether finalization completed."""
        if self.cluster is not None:
            still = [c.name for c in self.cluster.nodeclaims.values()
                     if c.node_class_ref == nodeclass.name and not c.terminating]
            if still:
                log.info("nodeclass %s blocked on %d nodeclaims",
                         nodeclass.name, len(still))
                return False
        if nodeclass.role:
            self.instance_profiles.delete(nodeclass)
            nodeclass.status_instance_profile = ""
        if launch_templates is not None:
            launch_templates.delete_all(nodeclass)
        return True


# ---------------------------------------------------------------------------
# Admission: defaulting + validation moved to karpenter_tpu.api.admission
# (webhook analogs, /root/reference/pkg/webhooks/webhooks.go:44-63).
# Re-exported here for compatibility with existing imports.
# ---------------------------------------------------------------------------

from ..api.admission import (ValidationError, default_nodeclass,  # noqa: E402,F401
                             default_nodepool, validate_nodeclass,
                             validate_nodeclass_update, validate_nodepool)
