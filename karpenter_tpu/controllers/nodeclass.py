"""NodeClass controller: selector config → resolved status.

Re-implements /root/reference/pkg/controllers/nodeclass/controller.go:
  * `reconcile` (:73-99) — resolve subnets (sorted by free IPs, most first),
    security groups, images, and the instance profile into `.status`;
    compute the static hash annotation drift detection keys off
    (`utils/nodeclass.HashAnnotation` via cloudprovider.go:116);
  * `finalize` (:100-126) — deletion is blocked while NodeClaims still
    reference the class; once unreferenced, the instance profile and this
    cluster's launch templates are garbage-collected.
"""

from __future__ import annotations

import hashlib
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..api.objects import NodeClass
from ..state.cluster import Cluster

log = logging.getLogger("karpenter_tpu.nodeclass")

REQUEUE_INTERVAL = 5 * 60.0  # controller.go requeues ~5m


def static_hash(nodeclass: NodeClass) -> str:
    """Hash of the launch-affecting spec fields; a change means every node
    launched from the old spec is drifted (drift.go static drift)."""
    payload = json.dumps({
        "image_family": nodeclass.image_family,
        "image_selector": sorted(nodeclass.image_selector.items()),
        "subnet_selector": sorted(nodeclass.subnet_selector.items()),
        "security_group_selector": sorted(nodeclass.security_group_selector.items()),
        "zone_selector": sorted(nodeclass.zone_selector),
        "role": nodeclass.role,
        "user_data": nodeclass.user_data,
        "tags": sorted(nodeclass.tags.items()),
        "block_device_gib": nodeclass.block_device_gib,
    }, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


@dataclass
class NodeClassResult:
    resolved: bool = False
    requeue_after: float = REQUEUE_INTERVAL
    errors: List[str] = field(default_factory=list)


class NodeClassController:
    def __init__(self, subnets, security_groups, images, instance_profiles,
                 cluster: Optional[Cluster] = None,
                 clock: Callable[[], float] = time.time):
        self.subnets = subnets
        self.security_groups = security_groups
        self.images = images
        self.instance_profiles = instance_profiles
        self.cluster = cluster

    def reconcile(self, nodeclass: NodeClass) -> NodeClassResult:
        out = NodeClassResult()
        subnets = self.subnets.list(nodeclass)
        if not subnets:
            out.errors.append("no subnets resolved")
        # most free IPs first: the launch path prefers roomy subnets
        # (controller.go resolveSubnets sorts by available IPs)
        subnets = sorted(subnets, key=lambda s: (-s.available_ip_count, s.id))
        nodeclass.status_subnets = [s.id for s in subnets]
        nodeclass.status_zones = sorted({s.zone for s in subnets})

        groups = self.security_groups.list(nodeclass)
        if nodeclass.security_group_selector and not groups:
            out.errors.append("no security groups resolved")
        nodeclass.status_security_groups = sorted(g.id for g in groups)

        images = self.images.get(nodeclass)
        if not images:
            out.errors.append("no images resolved")
        nodeclass.status_images = [i.id for i in images]

        if nodeclass.role:
            nodeclass.status_instance_profile = \
                self.instance_profiles.create(nodeclass, tags=nodeclass.tags)

        nodeclass.hash_annotation = static_hash(nodeclass)
        out.resolved = not out.errors
        if out.errors:
            log.warning("nodeclass %s unresolved: %s", nodeclass.name, out.errors)
        return out

    def finalize(self, nodeclass: NodeClass,
                 launch_templates=None) -> bool:
        """Deletion path: refuse while any NodeClaim references the class;
        then GC the instance profile (+ this cluster's launch templates when
        a provider is passed). Returns whether finalization completed."""
        if self.cluster is not None:
            still = [c.name for c in self.cluster.nodeclaims.values()
                     if c.node_class_ref == nodeclass.name and not c.terminating]
            if still:
                log.info("nodeclass %s blocked on %d nodeclaims",
                         nodeclass.name, len(still))
                return False
        if nodeclass.role:
            self.instance_profiles.delete(nodeclass)
            nodeclass.status_instance_profile = ""
        if launch_templates is not None:
            launch_templates.delete_all(nodeclass)
        return True


# ---------------------------------------------------------------------------
# Admission: defaulting + validation (webhook analogs,
# /root/reference/pkg/webhooks/webhooks.go:44-63 +
# /root/reference/pkg/apis/v1beta1/ec2nodeclass_validation.go)
# ---------------------------------------------------------------------------

class ValidationError(ValueError):
    pass


def default_nodeclass(nodeclass: NodeClass) -> NodeClass:
    """Defaulting webhook analog: fill family and block-device defaults."""
    if not nodeclass.image_family:
        nodeclass.image_family = "standard"
    if nodeclass.block_device_gib <= 0:
        nodeclass.block_device_gib = 20
    return nodeclass


def validate_nodeclass(nodeclass: NodeClass) -> None:
    """Validation webhook analog (ec2nodeclass_validation.go): reject specs
    that cannot launch."""
    from ..providers.imagefamily import FAMILIES
    errs = []
    if nodeclass.image_family not in FAMILIES:
        errs.append(f"unknown image family {nodeclass.image_family!r} "
                    f"(want one of {FAMILIES})")
    if nodeclass.image_family == "custom" and not nodeclass.image_selector:
        errs.append("custom image family requires an image selector")
    if nodeclass.image_family == "config" and \
            nodeclass.user_data.lstrip().startswith("MIME-Version"):
        errs.append("config family user data must be key=value settings, "
                    "not MIME")
    if nodeclass.block_device_gib < 1:
        errs.append("block device must be >= 1 GiB")
    for sel_name, sel in (("subnet_selector", nodeclass.subnet_selector),
                          ("security_group_selector",
                           nodeclass.security_group_selector),
                          ("image_selector", nodeclass.image_selector)):
        for k in sel:
            if not k:
                errs.append(f"{sel_name} has an empty key")
    if errs:
        raise ValidationError("; ".join(errs))


def validate_nodepool(nodepool) -> None:
    """NodePool validation analog (karpenter.sh_nodepools.yaml CEL rules):
    restricted-domain labels, sane disruption config, weight bounds."""
    from ..api import labels as wk
    from ..api.requirements import Requirements
    errs = []
    if nodepool.weight < 0 or nodepool.weight > 100:
        errs.append(f"weight {nodepool.weight} outside [0, 100]")
    d = nodepool.disruption
    if d.consolidation_policy not in ("WhenUnderutilized", "WhenEmpty"):
        errs.append(f"unknown consolidation policy {d.consolidation_policy!r}")
    if d.consolidation_policy == "WhenEmpty" and d.consolidate_after_s is None:
        errs.append("WhenEmpty requires consolidate_after_s")
    if d.expire_after_s is not None and d.expire_after_s <= 0:
        errs.append("expire_after_s must be positive")
    restricted = (wk.NODEPOOL, wk.NODE_INITIALIZED)
    for k in list(nodepool.template.labels) + list(nodepool.template.requirements):
        if k in restricted:
            errs.append(f"label {k} is restricted")
    if errs:
        raise ValidationError("; ".join(errs))
