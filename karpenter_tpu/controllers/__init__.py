from .provisioning import Provisioner, ProvisioningResult, claim_from_decision
from .disruption import DisruptionController, DisruptionResult
from .termination import TerminationController, TerminationResult
from .interruption import InterruptionController, InterruptionResult
from .garbagecollection import (GarbageCollectionController, GCResult,
                                TaggingController)
