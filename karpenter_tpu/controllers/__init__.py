from .provisioning import Provisioner, ProvisioningResult, claim_from_decision
