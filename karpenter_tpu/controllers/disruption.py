"""Disruption controller: consolidation, emptiness, expiration, drift.

Re-implements karpenter-core's disruption (née deprovisioning) engine as
reconstructed in SURVEY.md §2.2 from the reference's in-tree design docs:

  * candidate discovery with blockers — do-not-disrupt pods, PDB budgets,
    ownerless pods, recently-created nodes, in-flight nominations
    (/root/reference/designs/consolidation.md:44-52);
  * method ordering expiration → drift → emptiness → consolidation, ONE
    action executed per reconcile tick
    (/root/reference/designs/deprovisioning.md:11-31);
  * consolidation's two actions: node *deletion* (pods fit on the remaining
    nodes) and node *replacement* (pods fit on remaining nodes + one cheaper
    node), decided by simulated scheduling
    (/root/reference/designs/consolidation.md:7-21);
  * disruption-cost candidate ranking weighted by remaining node lifetime
    (/root/reference/designs/consolidation.md:25-42);
  * the `karpenter.sh/disruption:NoSchedule` taint, replacement pre-spin,
    and rollback on failed launches
    (/root/reference/website/content/en/docs/concepts/disruption.md:9-35).

TPU-first re-design: where the reference replays its object-graph scheduler
once per candidate, the simulation here is the same batched packing kernel
used for provisioning — a candidate's pods + the surviving nodes' dense
slots + a price-masked option set — so multi-node consolidation evaluates a
whole candidate prefix in one solve (SURVEY.md §7.6).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api import labels as wk
from ..api.objects import Node, NodeClaim, NodePool, Pod, pool_view
from ..api.resources import ResourceList
from ..api.taints import NO_SCHEDULE, Taint
from ..catalog.instancetype import InstanceType, effective_instance_type
from ..cloud.fake import CloudError
from ..cloud.provider import CloudProvider, InsufficientCapacityError
from ..forecast.headroom import headroom_expiry, is_headroom
from ..ops.classpack import solve_classpack
from ..ops.constraints import (LEVEL_REQUIRED_ONLY,
                               find_batch_topology_violations, lower_pods,
                               make_zone_feasibility)
from ..ops.ffd import PackingResult, solve_ffd
from ..ops.tensorize import Problem, tensorize
from ..parallel.driver import maybe_solve_partitioned
from ..state.cluster import Cluster
from ..utils import metrics, tracing
from ..utils.chaos import CHAOS
from ..utils.events import Event
from ..utils.watchdog import WatchdogTimeout, run_with_deadline

log = logging.getLogger("karpenter_tpu.disruption")

DISRUPTION_TAINT = Taint(wk.DISRUPTION_TAINT_KEY, NO_SCHEDULE, "disrupting")

# Tunables (/root/reference/designs/consolidation.md:61-67,
# /root/reference/designs/deprovisioning.md:27-33).
DEFAULT_STABILIZATION_S = 5 * 60.0   # min node lifetime before disruption
# spot→spot replacement keeps this many cheaper launch alternatives so the
# new node retains fleet flexibility (reference consolidation docs: ≥15
# cheaper offerings required for spot-to-spot consolidation)
SPOT_TO_SPOT_MIN_ALTERNATIVES = 15

# the reference's multi-node consolidation abandons an evaluation pass at
# this budget (karpenter-core MultiNodeConsolidation timeout)
CONSOLIDATION_TIMEOUT_S = 60.0


@dataclass
class Candidate:
    node: Node
    claim: Optional[NodeClaim]
    pool: NodePool
    reschedulable: List[Pod]
    disruption_cost: float
    price: float

    @property
    def name(self) -> str:
        return self.node.name


@dataclass
class Action:
    """One disruption decision: delete `candidates`, optionally launching
    `replacements` first (named {delete,replace}{Consolidation,Emptiness,
    Expiration,Drift} like the reference's action strings,
    /root/reference/designs/deprovisioning.md:11-31)."""
    kind: str                       # "delete" | "replace"
    reason: str                     # "consolidation" | "emptiness" | ...
    candidates: List[Candidate]
    simulation: Optional[PackingResult] = None
    problem: Optional[Problem] = None
    surviving_nodes: List[Node] = field(default_factory=list)

    @property
    def name(self) -> str:
        return f"{self.kind}/{self.reason}"


@dataclass
class DisruptionResult:
    action: Optional[Action] = None
    launched: List[NodeClaim] = field(default_factory=list)
    deleted: List[str] = field(default_factory=list)
    error: str = ""


def pod_disruption_cost(pod: Pod) -> float:
    """Per-pod eviction cost: more pods, higher priority, and explicit
    pod-deletion-cost all make a node more expensive to disrupt
    (/root/reference/designs/consolidation.md:25-42)."""
    return 1.0 + max(pod.priority, 0) / 1e4 + pod.deletion_cost / 1e3


def node_disruption_cost(node: Node, pool: NodePool, now: float) -> float:
    cost = sum(pod_disruption_cost(p) for p in node.pods)
    expire = pool.disruption.expire_after_s
    if expire:
        # nodes close to expiry are cheap to disrupt (lifetime weighting)
        remaining = max(0.0, 1.0 - (now - node.created_at) / expire)
        cost *= remaining
    return cost


def _search_frontier(lo: int, hi: int, cap: int = 31) -> List[int]:
    """Every mid the binary search over [lo, hi] can reach in its next few
    levels — whole levels of the mid decision tree while they fit in `cap`
    rows (one sweep bucket), always at least the first level.  Sibling
    subtrees cover disjoint ranges, so the mids are distinct and the tree
    over [1, N] has depth ~log₂N: cap=31 covers 5 levels per round, ≤2
    rounds at any realistic candidate count."""
    out: List[int] = []
    level = [(lo, hi)]
    while level:
        mids = [(l + h) // 2 for l, h in level if l <= h]
        if not mids or (out and len(out) + len(mids) > cap):
            break
        out.extend(mids)
        level = [iv for l, h in level if l <= h
                 for iv in ((l, (l + h) // 2 - 1), ((l + h) // 2 + 1, h))]
    return out


def _cands_match(old: List["Candidate"], new: List["Candidate"]) -> bool:
    """Cheap candidate-list equivalence for the lazy re-fingerprint: same
    nodes, prices, and reschedulable pod identities in the same order —
    O(candidate pods), never O(cluster)."""
    if len(old) != len(new):
        return False
    for a, b in zip(old, new):
        if (a.name != b.name or a.price != b.price or a.node is not b.node
                or len(a.reschedulable) != len(b.reschedulable)
                or any(x is not y for x, y in zip(a.reschedulable,
                                                  b.reschedulable))):
            return False
    return True


class DisruptionController:
    """Single-action disruption loop over cluster state."""

    def __init__(self, provider: CloudProvider, cluster: Cluster,
                 nodepools,
                 clock: Callable[[], float] = time.time,
                 stabilization_s: float = DEFAULT_STABILIZATION_S,
                 drift_enabled: bool = True,
                 # the reference's multi-node consolidation considers at
                 # most 100 candidates per pass (karpenter-core
                 # MultiNodeConsolidation.firstNConsolidationOption)
                 max_candidates: int = 100,
                 terminator: Optional["TerminationController"] = None,
                 spot_min_flexibility: int = SPOT_TO_SPOT_MIN_ALTERNATIVES,
                 recorder=None,
                 lp_guide: bool = True,
                 # batched prefix/candidate probing on the cached
                 # simulation arena (≤3 aggregate device calls per tick);
                 # False = the original sequential binary-search +
                 # per-candidate screen loop
                 batched_sweep: bool = True,
                 sharded_solve: bool = False,
                 health=None,
                 watchdog_timeout_s: float = 0.0,
                 gang_source: Optional[Callable] = None):
        from ..utils.events import Recorder
        self.provider = provider
        self.cluster = cluster
        self.nodepools = pool_view(nodepools)
        self.clock = clock
        self.terminator = terminator
        self.recorder = recorder or Recorder(log=False)
        self.stabilization_s = stabilization_s
        self.drift_enabled = drift_enabled
        self.max_candidates = max_candidates
        self.spot_min_flexibility = spot_min_flexibility
        self.lp_guide = lp_guide
        self.batched_sweep = batched_sweep
        # ShardedSolve feature gate: fleet-scale decoded simulations go
        # through the partitioned driver (parallel/driver.py); probes
        # (decode=False) stay on the aggregate kernel — they are already
        # cheap and batched.
        self.sharded_solve = sharded_solve
        # shared degradation ladder (ops/health.py) + per-simulate hard
        # deadline (utils/watchdog.py); None/0 keep the legacy direct path
        self.health = health
        self.watchdog_timeout_s = watchdog_timeout_s
        # GangScheduling: callable draining the provisioner's queued
        # preemption plans (Provisioner.take_preemption_plan); one plan
        # executes per tick, victims unbinding to pending exactly like
        # consolidation reschedules.  None == gate off.
        self.gang_source = gang_source
        self._empty_since: Dict[str, float] = {}  # node → first seen empty
        self._arena_cache = None  # (fingerprint, SimulationArena)
        # (mutation_epoch, catalog_key, candidates, fingerprint) — skips the
        # O(nodes+pods) arena_fingerprint walk while the cluster is unchanged
        self._fingerprint_cache = None

    # ------------------------------------------------------------------
    # candidate discovery
    # ------------------------------------------------------------------
    def candidates(self) -> List[Candidate]:
        """Disruptable nodes, cheapest disruption first. Blockers per
        /root/reference/designs/consolidation.md:44-52."""
        now = self.clock()
        budgets = self.cluster.pdb_budgets()
        out: List[Candidate] = []
        for node in self.cluster.nodes.values():
            pool = self.nodepools.get(node.nodepool)
            if pool is None or node.marked_for_deletion:
                continue
            if now - node.created_at < self.stabilization_s:
                continue  # min node lifetime
            if node.nominated_until > now:
                continue  # in-flight pod nomination
            blocked = ""
            # live headroom is protected by TTL: consolidating a node that
            # carries an unexpired placeholder would strand capacity the
            # forecaster just bought (placeholders are ownerless — they die
            # with the node — so the controller would re-buy, boot a fresh
            # node, and the sweep would eat it again: a launch-churn loop).
            # The freeze is bounded by the TTL; once demand is gone the
            # forecaster stops renewing and the node drains normally.
            # Expired headroom neither blocks nor reschedules.
            real = [p for p in node.pods
                    if not p.is_daemon and not is_headroom(p)]
            ttl_max = max((headroom_expiry(p) or 0.0
                           for p in node.pods if is_headroom(p)),
                          default=0.0)
            if ttl_max > now:
                blocked = "live headroom (protected by ttl)"
            for p in real:
                if p.do_not_disrupt:
                    blocked = f"pod {p.name} has do-not-disrupt"
                    break
                if not p.owner_kind:
                    blocked = f"pod {p.name} is ownerless"
                    break
            if blocked:
                # reference emits Unconsolidatable events so operators see
                # why capacity stays up; the recorder's dedupe window keeps
                # the per-tick republish quiet
                self.recorder.publish(Event(
                    "Node", node.name, "Unconsolidatable", blocked))
                continue
            resched = real
            if not self.cluster.evictable(resched, budgets):
                self.recorder.publish(Event(
                    "Node", node.name, "Unconsolidatable",
                    "pod disruption budget exhausted"))
                continue  # PDB budget exhausted
            claim = self.cluster.claim_for_provider_id(node.provider_id)
            out.append(Candidate(
                node=node, claim=claim, pool=pool, reschedulable=resched,
                disruption_cost=node_disruption_cost(node, pool, now),
                price=node.price))
        out.sort(key=lambda c: (c.disruption_cost, c.name))
        if len(out) > self.max_candidates:
            # no silent caps: a truncated discovery pass means this tick did
            # NOT sweep everything — say so and count it
            dropped = len(out) - self.max_candidates
            log.info("candidate discovery truncated: %d of %d kept "
                     "(max_candidates=%d), %d dropped",
                     self.max_candidates, len(out), self.max_candidates,
                     dropped)
            metrics.disruption_candidates_truncated().inc(by=dropped)
            out = out[:self.max_candidates]
        return out

    # ------------------------------------------------------------------
    # simulation: the scheduler re-used as the consolidation simulator
    # ------------------------------------------------------------------
    def _filtered_catalog(self, max_total_price: Optional[float]) -> List[InstanceType]:
        """Launch options for replacement simulations. `max_total_price`
        strictly bounds offering price — replacement must be cheaper
        (/root/reference/designs/consolidation.md:15-21).

        Memoized per (catalog object, price cap): candidates are simulated
        one per reconcile and often share prices, and returning the SAME
        filtered list object lets the tensorize catalog-side cache hit
        instead of rebuilding its option tables every simulation."""
        catalog = self.provider.get_instance_types()
        if max_total_price is None:
            return catalog
        memo_cat, memo = getattr(self, "_filtcat_memo", (None, None))
        if memo_cat is not catalog:
            memo = {}
            self._filtcat_memo = (catalog, memo)
        hit = memo.get(max_total_price)
        if hit is not None:
            return hit
        out = []
        for it in catalog:
            offerings = [o for o in it.offerings
                         if o.available and o.price < max_total_price]
            if offerings:
                out.append(InstanceType(
                    name=it.name, requirements=it.requirements,
                    offerings=offerings, capacity=it.capacity,
                    kube_reserved=it.kube_reserved,
                    system_reserved=it.system_reserved,
                    eviction_threshold=it.eviction_threshold, info=it.info))
        if len(memo) >= 64:  # bound growth across many distinct price caps
            memo.clear()
        memo[max_total_price] = out
        return out

    def _orig(self, p: Pod) -> Pod:
        return self.cluster.original(p)

    def simulate(self, excluded: Sequence[Candidate],
                 allow_new: bool = False,
                 max_total_price: Optional[float] = None,
                 decode: bool = True
                 ) -> Tuple[Problem, PackingResult, List[Node]]:
        """Would the excluded candidates' pods schedule on the surviving
        nodes [+ cheaper new capacity]?  One batched solve over dense arrays
        (SURVEY.md §7.6) instead of the reference's per-candidate replay.

        ``decode=False`` is the feasibility-probe mode (aggregate kernel, no
        per-pod binding, no batch-topology audit): a 10s-cadence controller
        doing dozens of binary-search probes can't afford per-probe decode —
        only the ONE accepted action needs real assignments
        (/root/reference/designs/consolidation.md:61-67's 15s/node budget
        implies probes must be cheap)."""
        pods = [p for c in excluded for p in c.reschedulable]
        catalog = self._filtered_catalog(max_total_price) if allow_new else []
        pools = list(self.nodepools.values())
        exclude_names = [c.name for c in excluded]
        # required-only lowering: preferences never block consolidation, but
        # spread/anti-affinity must hold on the post-disruption cluster
        zones = sorted({o.zone for it in catalog for o in it.offerings
                        if o.available}
                       | {n.zone for n in self.cluster.nodes.values()
                          if n.name not in exclude_names and n.zone})
        pods = lower_pods(pods, nodes=self.cluster.nodes.values(),
                          option_zones=zones, exclude_nodes=exclude_names,
                          level=LEVEL_REQUIRED_ONLY,
                          zone_feasible=make_zone_feasibility(
                              catalog, self.cluster.nodes.values(),
                              exclude_nodes=exclude_names))
        problem = tensorize(pods, catalog, pools,
                            node_classes=getattr(self.provider,
                                                 "node_classes", None))
        node_list, alloc, used, compat = self.cluster.tensorize_nodes(
            problem.class_reps, problem.axes, exclude=exclude_names,
            scales=problem.scales)
        if len(node_list) == 0 and problem.num_options == 0:
            result = PackingResult(
                nodes=[], unschedulable=list(range(len(pods))),
                existing_assignments={}, total_price=0.0)
            return problem, result, node_list
        result = self._simulate_pack(problem, node_list, alloc, used,
                                     compat, decode)
        if decode:
            # intra-batch anti-affinity/spread the masks can't express: a
            # violated placement disqualifies the whole action (the
            # reference's simulation would simply fail to schedule the pod),
            # so count the violating pods as unschedulable rather than
            # executing a bad bind
            violations = find_batch_topology_violations(problem, result,
                                                        node_list)
            if violations:
                result.unschedulable = sorted(
                    set(result.unschedulable) | violations)
        return problem, result, node_list

    def _simulate_pack(self, problem: Problem, node_list, alloc, used,
                       compat, decode: bool) -> PackingResult:
        """Simulation solve under the degradation ladder, mirroring
        Provisioner._pack_supervised: healthy = legacy direct path
        (sharded gate → classpack), failures fall one rung per attempt and
        are booked in the shared SolverHealth; greedy is deadline-free and
        re-raises — it is the floor."""
        requested = "sharded" if (decode and self.sharded_solve) else "jax"
        if self.health is None:
            return self._simulate_rung(requested, problem, node_list,
                                       alloc, used, compat, decode)
        rung = self.health.active_rung(requested)
        while True:
            timeout = 0.0 if rung == "greedy" else self.watchdog_timeout_s
            try:
                result = run_with_deadline(
                    lambda: self._simulate_rung(rung, problem, node_list,
                                                alloc, used, compat, decode),
                    timeout, "disruption.simulate")
                self.health.report_success(rung)
                return result
            except WatchdogTimeout:
                self.health.report_failure(rung, reason="timeout")
            except Exception:
                self.health.report_failure(rung, reason="error")
                if rung == "greedy":
                    raise
            rung = self.health.active_rung(
                self.health.next_rung(rung) or "greedy")

    def _simulate_rung(self, rung: str, problem: Problem, node_list,
                       alloc, used, compat, decode: bool) -> PackingResult:
        """One simulation attempt on one rung.  A sharded refusal falls
        through to the jax rung inline (routing, not failure).  The
        native/greedy rungs run the pod-granular FFD — it always decodes,
        which a decode=False probe tolerates (the caller only reads
        aggregate fields of the PackingResult)."""
        CHAOS.inject("solver.pack", key=rung)
        ekw = dict(existing_alloc=alloc if len(node_list) else None,
                   existing_used=used if len(node_list) else None,
                   existing_compat=compat if len(node_list) else None)
        if rung == "sharded":
            result = maybe_solve_partitioned(
                problem, path="disruption", max_nodes=2048,
                node_list=node_list, **ekw)
            if result is not None:
                return result
            rung = "jax"
        if rung == "jax":
            return solve_classpack(
                problem, decode=decode,
                # the LPGuide gate covers THIS path too: a fresh replacement
                # solve (all candidates excluded, no survivors) would
                # otherwise run the guide despite the escape hatch
                guide="lp" if self.lp_guide else None, **ekw)
        if rung == "native":
            from .. import native
            if not native.available():
                raise RuntimeError("native packer unavailable on this host")
            return solve_ffd(problem, max_nodes=2048, backend="native", **ekw)
        return solve_ffd(problem, max_nodes=2048, backend="numpy", **ekw)

    # ------------------------------------------------------------------
    # methods, in reference order
    # ------------------------------------------------------------------
    def find_expired(self, cands: List[Candidate]) -> List[Candidate]:
        now = self.clock()
        return [c for c in cands
                if c.pool.disruption.expire_after_s
                and now - c.node.created_at > c.pool.disruption.expire_after_s]

    def find_drifted(self, cands: List[Candidate]) -> List[Candidate]:
        if not self.drift_enabled:
            return []
        out = []
        counted = self.__dict__.setdefault("_drift_counted", set())
        for c in cands:
            if c.claim is not None and self.provider.is_drifted(c.claim, c.pool):
                out.append(c)
                # transition counter: first detection only, not every tick
                # (reference karpenter_nodeclaims_drifted)
                if c.name not in counted:
                    counted.add(c.name)
                    metrics.nodeclaims_drifted().inc(
                        {"nodepool": c.node.nodepool or ""})
        # prune only nodes GONE from the cluster: a drifted node that
        # transiently leaves candidacy (nomination, PDB, truncation) stays
        # counted so its return doesn't inflate the transition counter
        counted.intersection_update(set(self.cluster.nodes))
        return out

    def find_empty(self, cands: List[Candidate]) -> List[Candidate]:
        """Emptiness: nodes with no reschedulable pods that have STAYED empty
        for consolidate_after_s (time-since-empty, not node age — a node that
        just lost its last pod gets the full delay)."""
        now = self.clock()
        empty_names = set()
        out = []
        for c in cands:
            if c.reschedulable:
                continue
            empty_names.add(c.name)
            since = self._empty_since.setdefault(c.name, now)
            after = c.pool.disruption.consolidate_after_s or 0.0
            if now - since < after:
                continue
            out.append(c)
        # nodes that regained pods (or vanished) reset their empty timer
        for name in list(self._empty_since):
            if name not in empty_names:
                del self._empty_since[name]
        return out

    # ------------------------------------------------------------------
    # the single-action reconcile
    # ------------------------------------------------------------------
    def reconcile(self) -> DisruptionResult:
        with tracing.span("disruption.reconcile") as sp:
            out = self._reconcile()
            sp.annotate(
                action=getattr(out.action, "name", "") if out.action else "",
                deleted=len(out.deleted), launched=len(out.launched))
            return out

    def _reconcile(self) -> DisruptionResult:
        eval_hist = metrics.disruption_evaluation_duration()
        eligible = metrics.disruption_eligible_nodes()
        with tracing.span("disruption.candidates") as csp:
            cands = self.candidates()
            # per-method eligibility gauges, all computed up-front so no
            # series goes stale when an earlier method short-circuits the
            # tick (calling find_empty every tick also keeps its empty-since
            # timers fresh)
            expired = self.find_expired(cands)
            drifted = self.find_drifted(cands)
            empty = self.find_empty(cands)
            underutil = [c for c in cands
                         if c.pool.disruption.consolidation_policy ==
                         "WhenUnderutilized"]
            eligible.set(len(expired), {"method": "expiration"})
            eligible.set(len(drifted), {"method": "drift"})
            eligible.set(len(empty), {"method": "emptiness"})
            eligible.set(len(underutil), {"method": "consolidation"})
            csp.annotate(candidates=len(cands), expired=len(expired),
                         drifted=len(drifted), empty=len(empty))

        # 0. gang preemption (GangScheduling): a waiting higher-tier gang
        #    outranks bound lower-tier pods; one queued plan executes per
        #    tick, ahead of every other method — admission latency for
        #    tiered gangs is the whole point of the cascade
        if self.gang_source is not None:
            plan = self.gang_source()
            if plan is not None:
                return self._execute_preemption(plan)

        if not cands:
            return DisruptionResult()

        def timed(method, fn):
            # span names come from one registry (graftlint OB005):
            # registered() asserts disruption.<method> is in SPAN_NAMES
            with tracing.span(tracing.registered(f"disruption.{method}")):
                t0 = time.perf_counter()
                try:
                    return fn()
                finally:
                    dt = time.perf_counter() - t0
                    eval_hist.observe(dt, {"method": method})
                    # the reference aborts a consolidation pass at its
                    # 1-minute budget and counts it; the batched simulator
                    # stays ~3 orders of magnitude under that, so the
                    # counter exists to prove the budget is honored, not
                    # because it ever fires
                    if dt > CONSOLIDATION_TIMEOUT_S:
                        metrics.consolidation_timeouts().inc({"method": method})

        # 1. expiration (graceful replace: pods rescheduled, new capacity allowed)
        if expired:
            action = timed("expiration",
                           lambda: self._replace_or_delete(expired[:1],
                                                           "expiration"))
            if action:
                return self.execute(action)

        # 2. drift
        if drifted:
            action = timed("drift",
                           lambda: self._replace_or_delete(drifted[:1],
                                                           "drift"))
            if action:
                return self.execute(action)

        # 3. emptiness — all empty candidates in one shot (reference's
        #    emptiness batch delete)
        if empty:
            return self.execute(Action(kind="delete", reason="emptiness",
                                       candidates=empty))

        # 4. consolidation (WhenUnderutilized pools only)
        action = timed("consolidation",
                       lambda: self.consolidation_action(underutil))
        if action:
            return self.execute(action)
        return DisruptionResult()

    def _execute_preemption(self, plan) -> DisruptionResult:
        """Evict one gang preemption plan's victims: each unbinds to
        pending (the consolidation-reschedule motion — the pod re-solves
        next provisioning round, the node keeps running for its other
        pods).  Victims that moved or exited since planning are skipped;
        if the freed room proves insufficient the next solve queues a
        deeper plan down the cascade."""
        evicted = 0
        for v in plan.victims:
            node = self.cluster.nodes.get(v.node)
            if node is None:
                continue
            pod = next((p for p in node.pods if p.uid == v.uid), None)
            if pod is None:
                continue
            self.cluster.unbind_pod(pod)
            metrics.gang_preemptions().inc({"tier": str(v.tier)})
            self.recorder.publish(Event(
                kind="Pod", name=pod.name, reason="GangPreempted",
                message=(f"evicted for gang {plan.gang}: tier {v.tier} "
                         f"yields to tier {plan.tier}"),
                type="Warning"))
            evicted += 1
        log.info("gang preemption for %s: evicted %d/%d victims in %s %r",
                 plan.gang, evicted, len(plan.victims), plan.topology,
                 plan.domain)
        return DisruptionResult(action=Action(kind="preempt", reason="gang",
                                              candidates=[]))

    def _replace_or_delete(self, targets: List[Candidate], reason: str) -> Optional[Action]:
        """Expiration/drift disruption: pods must land somewhere — on the
        surviving nodes or on replacement capacity at any price."""
        problem, result, survivors = self.simulate(targets, allow_new=True)
        if result.unschedulable:
            log.info("%s of %s blocked: %d pods would be unschedulable",
                     reason, [c.name for c in targets], len(result.unschedulable))
            return None
        kind = "replace" if result.nodes else "delete"
        return Action(kind=kind, reason=reason, candidates=targets,
                      simulation=result, problem=problem,
                      surviving_nodes=survivors)

    def consolidation_action(self, cands: List[Candidate]) -> Optional[Action]:
        """Multi-node delete first (largest feasible prefix of the
        cost-sorted candidates), then single-node delete-or-replace.

        The batched path answers every probe the sequential algorithm would
        ask from AT MOST THREE aggregate device calls on a cached
        `SimulationArena`: the delete binary search's reachable mids as
        1-2 batched frontier probes, then (only if no delete wins) one
        all-candidate replacement screen.  Fully-decoded solves remain only
        for the winning action — the decode-audit fallback is unchanged."""
        cands = [c for c in cands if self._consolidatable(c)]
        if not cands:
            return None
        if not self.batched_sweep:
            return self._consolidation_action_sequential(cands)
        timeout = self.watchdog_timeout_s if self.health is not None else 0.0
        try:
            return run_with_deadline(
                lambda: self._consolidation_action_batched(cands),
                timeout, "disruption.sweep")
        except WatchdogTimeout:
            # hung device mid-sweep: book it against the jax rung (the
            # arena kernels live there) and finish THIS tick on the
            # sequential path, whose simulate() probes consult the
            # now-demoted ladder
            self.health.report_failure("jax", reason="timeout")
            return self._consolidation_action_sequential(cands)

    def _consolidation_action_batched(self,
                                      cands: List[Candidate]
                                      ) -> Optional[Action]:
        CHAOS.inject("solver.sweep")
        sweep_hist = metrics.disruption_sweep_duration()
        t0 = time.perf_counter()
        with tracing.span("sweep.arena", candidates=len(cands)):
            arena = self._arena_for(cands)
        # PDB composition over prefix unions, computed incrementally on the
        # host in ONE pass (the sequential path rebuilt the union and
        # rescanned every PDB per binary-search step)
        evict_ok = self._prefix_evictable(cands)
        # replay the sequential binary search exactly, but evaluate its
        # probes in batched rounds: each round solves EVERY prefix the
        # search could still reach in its next few levels (whole levels of
        # the mid decision tree, ≤31 rows ⇒ ≤2 rounds at any N), then
        # walks the real outcomes.  The search only ever reads mids we
        # evaluated with the same oracle, so best_mid is identical to the
        # sequential result even when feasibility is non-monotone in the
        # prefix length
        device_calls = 0
        feas: Dict[int, bool] = {}
        lo, hi, best_mid = 1, len(cands), 0
        with tracing.span("sweep.prefix") as psp:
            while lo <= hi:
                mids = _search_frontier(lo, hi)
                need = [k for k in mids if k not in feas]
                if need:
                    sweep = arena.sweep_prefix_subset(need)
                    device_calls += sweep.device_calls
                    for i, k in enumerate(need):
                        feas[k] = evict_ok[k] and sweep.feasible_delete(i)
                while lo <= hi:
                    mid = (lo + hi) // 2
                    if mid not in feas:
                        break
                    if feas[mid]:
                        best_mid = mid
                        lo = mid + 1
                    else:
                        hi = mid - 1
            psp.annotate(device_calls=device_calls, best_mid=best_mid)
        sweep_hist.observe(time.perf_counter() - t0, {"phase": "prefix"})
        # the aggregate probe is optimistic about intra-batch topology
        # (spread/anti-affinity audits need assignments): decode the winner
        # — common case, ONE decoded solve total.  If the audit rejects it,
        # rerun the binary search with decoded probes over the remaining
        # range: the pre-probe algorithm, paid only when audits bite.
        with tracing.span("sweep.decode", best_mid=best_mid) as dsp:
            best = self._decoded_delete_action(cands[:best_mid]) if best_mid else None
            if best is None and best_mid > 1:
                dsp.annotate(audit_rejected=True)
                lo, hi = 1, best_mid - 1
                while lo <= hi:
                    mid = (lo + hi) // 2
                    a = self._decoded_delete_action(cands[:mid])
                    if a is not None:
                        best = a
                        lo = mid + 1
                    else:
                        hi = mid - 1
        if best is not None:
            metrics.disruption_sweep_probes().set(device_calls)
            return best

        # single-node pass: one batched screen over ALL candidates (the
        # sequential loop paid one aggregate solve per candidate), then the
        # decoded accept path candidate-by-candidate in discovery order —
        # first acceptance wins, exactly like the sequential loop.
        t1 = time.perf_counter()
        with tracing.span("sweep.single") as ssp:
            screen = arena.sweep_singles()
            sweep_hist.observe(time.perf_counter() - t1, {"phase": "single"})
            device_calls += screen.device_calls
            ssp.annotate(device_calls=screen.device_calls)
            metrics.disruption_sweep_probes().set(device_calls)
            for i, c in enumerate(cands):
                if not c.reschedulable:
                    continue
                if screen.unschedulable[i] or screen.new_nodes[i] > 1:
                    continue
                if screen.new_nodes[i] and screen.total_price[i] >= c.price:
                    continue
                action = self._decoded_single_action(c)
                if action is not None:
                    return action
            return None

    def _arena_for(self, cands: List[Candidate]):
        """Size-1 simulation-arena cache keyed on the cluster-state
        fingerprint: repeat probes within a tick and unchanged clusters
        across ticks reuse the tensorized arrays and swap only masks."""
        from ..api.resources import DEFAULT_AXES
        from ..ops.tensorize import (SimulationArena, _catside_fingerprint,
                                     arena_fingerprint)
        catalog = self.provider.get_instance_types()
        pools = list(self.nodepools.values())
        ncs = getattr(self.provider, "node_classes", None)
        cat_key = _catside_fingerprint(catalog, pools, DEFAULT_AXES,
                                       node_classes=ncs)
        # lazy re-fingerprint: arena_fingerprint walks every node and bound
        # pod (O(E+P) — 50k tuples at scale); the cluster's mutation_epoch
        # is bumped by every mutator, so an unchanged epoch + identical
        # candidate list proves the O(E+P) walk would produce the same key
        epoch = getattr(self.cluster, "mutation_epoch", None)
        fp = self._fingerprint_cache
        if (fp is not None and epoch is not None and fp[0] == epoch
                and fp[1] == cat_key and _cands_match(fp[2], cands)):
            key = fp[3]
        else:
            key = arena_fingerprint(cands, self.cluster.nodes.values(),
                                    cat_key)
            self._fingerprint_cache = (epoch, cat_key, list(cands), key)
        cached = self._arena_cache
        if cached is not None and cached[0] == key:
            metrics.disruption_arena_requests().inc({"outcome": "hit"})
            tracing.annotate(arena="hit")
            return cached[1]
        arena = SimulationArena(cands, self.cluster, catalog, pools,
                                node_classes=ncs)
        self._arena_cache = (key, arena)
        metrics.disruption_arena_requests().inc({"outcome": "build"})
        tracing.annotate(arena="build")
        return arena

    def _prefix_evictable(self, cands: List[Candidate]) -> List[bool]:
        """evict_ok[k] ⇔ evicting the union of cands[:k] clears every PDB
        budget — `cluster.evictable` over growing prefixes in one
        incremental pass (draws only grow, so the first failing prefix
        poisons all larger ones)."""
        n = len(cands)
        if not self.cluster.pdbs:
            return [True] * (n + 1)
        budgets = self.cluster.pdb_budgets()
        ok = [True]
        draw: Dict[str, int] = {}
        good = True
        for c in cands:
            if good:
                for p in c.reschedulable:
                    for pdb in self.cluster.pdbs.values():
                        if pdb.matches(p):
                            draw[pdb.name] = draw.get(pdb.name, 0) + 1
                good = all(budgets[name] >= v for name, v in draw.items())
            ok.append(good)
        return ok

    def _consolidation_action_sequential(self, cands: List[Candidate]
                                         ) -> Optional[Action]:
        """The pre-arena algorithm (binary search + per-candidate screen
        loop, one tensorize + aggregate solve per probe): the oracle the
        batched sweep's parity tests run against, and the escape hatch."""
        # multi-node / single-node DELETE: pods fit on surviving nodes alone.
        # The union of a subset's evictions must clear the PDB budgets too —
        # per-node checks in candidates() don't compose.  Probes run the
        # aggregate kernel (decode=False); only the winning prefix pays for
        # per-pod decode + the batch-topology audit.
        lo, hi, best_mid = 1, len(cands), 0
        while lo <= hi:
            mid = (lo + hi) // 2
            subset = cands[:mid]
            union = [p for c in subset for p in c.reschedulable]
            if not self.cluster.evictable(union):
                hi = mid - 1
                continue
            _, result, _ = self.simulate(subset, allow_new=False, decode=False)
            if not result.unschedulable and not result.nodes:
                best_mid = mid
                lo = mid + 1
            else:
                hi = mid - 1
        best = self._decoded_delete_action(cands[:best_mid]) if best_mid else None
        if best is None and best_mid > 1:
            lo, hi = 1, best_mid - 1
            while lo <= hi:
                mid = (lo + hi) // 2
                a = self._decoded_delete_action(cands[:mid])
                if a is not None:
                    best = a
                    lo = mid + 1
                else:
                    hi = mid - 1
        if best is not None:
            return best

        # single-node pass (non-prefix candidates the binary search missed):
        # DELETE if the solver lands every pod on survivors, else REPLACE
        # with ONE strictly-cheaper node.  Aggregate screen first; decode
        # only accepted candidates.
        for c in cands:
            if not c.reschedulable:
                continue
            _, screen, _ = self.simulate(
                [c], allow_new=True, max_total_price=c.price, decode=False)
            if screen.unschedulable or len(screen.nodes) > 1:
                continue
            if screen.nodes and screen.total_price >= c.price:
                continue
            action = self._decoded_single_action(c)
            if action is not None:
                return action
        return None

    def _decoded_single_action(self, c: Candidate) -> Optional[Action]:
        """Fully-decoded single-candidate delete-or-replace: the accept path
        both the batched screen and the sequential screen feed into."""
        problem, result, survivors = self.simulate(
            [c], allow_new=True, max_total_price=c.price)
        if result.unschedulable or len(result.nodes) > 1:
            return None
        if not result.nodes:   # pure delete — survivors absorb everything
            return Action(kind="delete", reason="consolidation",
                          candidates=[c], simulation=result,
                          problem=problem, surviving_nodes=survivors)
        if result.total_price >= c.price:
            return None
        # spot→spot replacement needs flexibility (the reference's ≥15
        # cheaper-offerings floor): count only SPOT alternatives strictly
        # cheaper than the replaced node — on-demand options don't keep a
        # spot launch flexible. Clamped to how many cheaper spot options
        # the pool's catalog has at all, so small catalogs still
        # exercise the path while catalog-scale runs enforce the full 15.
        chosen = result.nodes[0]
        if (c.node.capacity_type == wk.CAPACITY_TYPE_SPOT
                and chosen.option.capacity_type == wk.CAPACITY_TYPE_SPOT):
            # distinct cheaper spot TYPES, matching spot_alts' dedup —
            # counting zone-expanded options would inflate the clamp and
            # permanently block spot→spot moves on multi-zone catalogs
            pool_spot_cheaper = len({
                o.instance_type for o in problem.options
                if o.capacity_type == wk.CAPACITY_TYPE_SPOT
                and o.pool == chosen.option.pool and o.price < c.price})
            floor = min(self.spot_min_flexibility, pool_spot_cheaper)
            spot_alts = {a.instance_type for a in chosen.alternatives
                         if a.capacity_type == wk.CAPACITY_TYPE_SPOT
                         and a.price < c.price}
            spot_alts.add(chosen.option.instance_type)
            if len(spot_alts) < floor:
                return None
        return Action(kind="replace", reason="consolidation",
                      candidates=[c], simulation=result, problem=problem,
                      surviving_nodes=survivors)

    def _decoded_delete_action(self, subset: List[Candidate]) -> Optional[Action]:
        """Fully-decoded delete feasibility (incl. the batch-topology audit)
        for one candidate prefix; None if the subset can't be deleted."""
        union = [p for c in subset for p in c.reschedulable]
        if not self.cluster.evictable(union):
            return None
        problem, result, survivors = self.simulate(subset, allow_new=False)
        if result.unschedulable or result.nodes:
            return None
        return Action(kind="delete", reason="consolidation", candidates=subset,
                      simulation=result, problem=problem,
                      surviving_nodes=survivors)

    def _consolidatable(self, c: Candidate) -> bool:
        now = self.clock()
        after = c.pool.disruption.consolidate_after_s
        if after is not None and now - c.node.created_at < after:
            return False
        return True

    # ------------------------------------------------------------------
    # execution: taint → pre-spin replacements → rebind → terminate
    # ------------------------------------------------------------------
    def execute(self, action: Action) -> DisruptionResult:
        # cost-ledger attribution: every launch/terminate inside this
        # actuation funnel is tagged with the disruption reason (free
        # when the SLOEngine gate is off — the context is a thread-local
        # set/clear and the hooks behind it check LEDGER.enabled first)
        from ..obs.ledger import DECISION_SOURCES, LEDGER
        src = action.reason if action.reason in DECISION_SOURCES \
            else "consolidation"
        with tracing.span("disruption.execute", kind=action.kind,
                          reason=action.reason) as sp, LEDGER.decision(src):
            out = self._execute(action)
            sp.annotate(deleted=len(out.deleted), launched=len(out.launched))
            return out

    def _execute(self, action: Action) -> DisruptionResult:
        out = DisruptionResult(action=action)
        # taint first so nothing new schedules onto doomed nodes
        # (website/.../concepts/disruption.md:9-14)
        for c in action.candidates:
            c.node.marked_for_deletion = True
            if DISRUPTION_TAINT not in c.node.taints:
                c.node.taints.append(DISRUPTION_TAINT)
            self.cluster.touch_node(c.node)

        new_nodes: List[Node] = []
        catalog_by_name = {it.name: it for it in self.provider.get_instance_types()}
        if action.simulation is not None and action.simulation.nodes:
            from .provisioning import claim_from_decision
            for decision in action.simulation.nodes:
                t_launch = time.perf_counter()
                dpods = [self._orig(action.problem.pods[i])
                         for i in decision.pod_indices]
                claim = claim_from_decision(decision, dpods, self.nodepools)
                try:
                    claim = self.provider.create(claim)
                except InsufficientCapacityError as e:
                    # rollback: untaint, unmark, abandon the action
                    # (website/.../concepts/disruption.md:12-14)
                    metrics.disruption_replacement_failures().inc(
                        {"method": action.reason})
                    self.recorder.publish(Event(
                        "Node", action.candidates[0].name, "DisruptionFailed",
                        f"replacement launch failed: {e}", type="Warning"))
                    log.warning("disruption rollback, launch failed: %s", e)
                    self._rollback(action, new_nodes, out)
                    out.error = str(e)
                    return out
                it = catalog_by_name.get(claim.instance_type)
                if it is not None:
                    ncs = getattr(self.provider, "node_classes", None) or {}
                    it = effective_instance_type(
                        it, self.nodepools.get(claim.nodepool),
                        ncs.get(claim.node_class_ref))
                node = self.cluster.register_nodeclaim(
                    claim, it.allocatable if it else claim.requests,
                    it.capacity if it else None)
                node._decision = decision
                new_nodes.append(node)
                out.launched.append(claim)
                # replacement goes live at registration in this substrate:
                # create-call → registered is its initialization span
                metrics.disruption_replacement_initialized().observe(
                    time.perf_counter() - t_launch)

        # rebind evicted pods per the simulation's placement
        if action.simulation is not None:
            sim = action.simulation
            for pod_i, slot in sim.existing_assignments.items():
                self.cluster.bind_pod(self._orig(action.problem.pods[pod_i]),
                                      action.surviving_nodes[slot].name)
            for node in new_nodes:
                for pod_i in node._decision.pod_indices:
                    self.cluster.bind_pod(self._orig(action.problem.pods[pod_i]),
                                          node.name)

        # terminate candidates — through the finalizer-drain flow when a
        # terminator is wired, else the inline state-level equivalent
        for c in action.candidates:
            if self.terminator is not None:
                tres = self.terminator.drain_sync(c.node, reason=action.reason)
                out.deleted.extend(tres.terminated)
                if tres.errors:
                    out.error = "; ".join(tres.errors)
                else:
                    # count only ACTUAL disruptions — a failed drain retries
                    # next tick and must not double-count
                    metrics.nodeclaims_disrupted().inc(
                        {"type": action.reason,
                         "nodepool": c.node.nodepool or ""})
                continue
            # daemonset pods die with their node — they must NOT be requeued
            # as pending (a fresh node would be provisioned just for them)
            for p in list(c.node.pods):
                if p.is_daemon:
                    self.cluster.delete_pod(p)
            try:
                if c.claim is not None:
                    self.provider.delete(c.claim)
                    self.cluster.nodeclaims.pop(c.claim.name, None)
            except Exception as e:
                from ..cloud import errors as cloud_errors
                already_gone = isinstance(e, CloudError) and cloud_errors.is_not_found(e)
                if not already_gone:
                    # transient cloud failure (typed or not): untaint so the
                    # next reconcile retries this (now-empty) node instead of
                    # stranding a billed zombie behind marked_for_deletion
                    c.node.marked_for_deletion = False
                    c.node.taints = [t for t in c.node.taints
                                     if t.key != DISRUPTION_TAINT.key]
                    self.cluster.touch_node(c.node)
                    out.error = str(e)
                    continue
                self.cluster.nodeclaims.pop(c.claim.name, None)
            self.cluster.remove_node(c.name)
            out.deleted.append(c.name)
            metrics.nodeclaims_disrupted().inc(
                {"type": action.reason, "nodepool": c.node.nodepool or ""})
            self.recorder.publish(Event(
                "Node", c.name, "DisruptionTerminating",
                f"{action.kind} via {action.reason}"))
        log.info("disruption %s: deleted %s, launched %s", action.name,
                 out.deleted, [c.name for c in out.launched])
        return out

    def _rollback(self, action: Action, new_nodes: List[Node],
                  out: DisruptionResult):
        for c in action.candidates:
            c.node.marked_for_deletion = False
            c.node.taints = [t for t in c.node.taints
                             if t.key != DISRUPTION_TAINT.key]
            self.cluster.touch_node(c.node)
        for node in new_nodes:
            claim = self.cluster.claim_for_provider_id(node.provider_id)
            if claim is not None:
                self.provider.delete(claim)
                self.cluster.nodeclaims.pop(claim.name, None)
            self.cluster.remove_node(node.name)
        out.launched.clear()
