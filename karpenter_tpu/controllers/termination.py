"""Termination controller: graceful node teardown.

Re-implements the reference's termination flow (SURVEY.md §2.2; node
finalizer → taint → evict via the Eviction API respecting PDBs → delete the
cloud instance → remove the finalizer,
/root/reference/website/content/en/docs/concepts/disruption.md:27-35,
/root/reference/designs/termination.md):

  * a termination *request* puts the node behind the finalizer analog
    (`Node.marked_for_deletion`) and taints it NoSchedule so nothing new
    lands;
  * each reconcile tick drains as many pods as PDB budgets allow — pods
    whose eviction would violate a budget stay put and the node requeues
    (the Eviction-API retry loop);
  * daemonset pods are not evicted — they die with the node;
  * only once every reschedulable pod is gone does the cloud instance get
    terminated and the node object released (finalizer removed).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..api.objects import Node, Pod
from ..api.taints import NO_SCHEDULE, Taint
from ..api import labels as wk
from ..cloud.fake import CloudError
from ..cloud.provider import CloudProvider
from ..state.cluster import Cluster
from ..utils import metrics

log = logging.getLogger("karpenter_tpu.termination")

TERMINATION_TAINT = Taint(wk.DISRUPTION_TAINT_KEY, NO_SCHEDULE, "terminating")


@dataclass
class TerminationResult:
    evicted: List[str] = field(default_factory=list)    # pod uids
    terminated: List[str] = field(default_factory=list)  # node names
    requeued: List[str] = field(default_factory=list)   # nodes still draining
    errors: List[str] = field(default_factory=list)


class TerminationController:
    """Finalizer-style drain loop over termination requests."""

    def __init__(self, provider: CloudProvider, cluster: Cluster,
                 clock: Callable[[], float] = time.time):
        self.provider = provider
        self.cluster = cluster
        self.clock = clock
        self._queue: Dict[str, str] = {}   # node name → reason
        self._requested_at: Dict[str, float] = {}  # drain-start stamps

    # ------------------------------------------------------------------
    def request(self, node: Node, reason: str = "") -> None:
        """Begin terminating `node`: finalizer + taint, drain happens on
        subsequent reconciles."""
        node.marked_for_deletion = True
        # replace any same-key taint (e.g. the disruption controller's
        # 'disrupting') — duplicate keys are invalid node state
        node.taints = [t for t in node.taints
                       if t.key != TERMINATION_TAINT.key] + [TERMINATION_TAINT]
        self.cluster.touch_node(node)
        self._queue.setdefault(node.name, reason)
        self._requested_at.setdefault(node.name, self.clock())

    @property
    def pending(self) -> List[str]:
        return sorted(self._queue)

    # ------------------------------------------------------------------
    def reconcile(self) -> TerminationResult:
        """One drain pass over every in-flight termination."""
        out = TerminationResult()
        for name in sorted(self._queue):
            node = self.cluster.nodes.get(name)
            if node is None:           # already gone — drop the finalizer
                del self._queue[name]
                self._requested_at.pop(name, None)
                continue
            self._drain_one(node, out)
        return out

    def drain_sync(self, node: Node, reason: str = "",
                   max_rounds: int = 100) -> TerminationResult:
        """Request + drain to completion (or until PDBs stall the drain).
        The synchronous entry disruption/interruption flows use."""
        self.request(node, reason)
        out = TerminationResult()
        for _ in range(max_rounds):
            before = len(out.evicted)
            self._drain_one(node, out)
            if node.name not in self._queue:
                break
            if len(out.evicted) == before:
                break  # stalled on PDBs — caller retries later
        return out

    # ------------------------------------------------------------------
    def _drain_one(self, node: Node, out: TerminationResult) -> None:
        budgets = self.cluster.pdb_budgets()
        # evict pod-by-pod, re-debiting budgets as we go (Eviction API
        # semantics: each eviction is checked against the live budget)
        for pod in sorted([p for p in node.pods if not p.is_daemon],
                          key=lambda p: p.uid):
            draw = [name for name, pdb in self.cluster.pdbs.items()
                    if pdb.matches(pod)]
            if any(budgets[n] <= 0 for n in draw):
                continue  # blocked this round; PDB may free up later
            for n in draw:
                budgets[n] -= 1
            self._evict(pod)
            out.evicted.append(pod.uid)

        if any(not p.is_daemon for p in node.pods):
            out.requeued.append(node.name)
            return

        # fully drained: daemon pods die with the node, instance goes away,
        # finalizer is removed
        claim = self.cluster.claim_for_provider_id(node.provider_id)
        if claim is not None:
            try:
                self.provider.delete(claim)
            except CloudError as e:
                from ..cloud.errors import is_not_found
                if not is_not_found(e):  # already gone == success
                    out.errors.append(f"{node.name}: {e}")
                    out.requeued.append(node.name)
                    return
            except Exception as e:  # noqa: BLE001 — cloud errors surface in result
                out.errors.append(f"{node.name}: {e}")
                out.requeued.append(node.name)
                return
            self.cluster.nodeclaims.pop(claim.name, None)
        for p in list(node.pods):
            self.cluster.delete_pod(p)
        self.cluster.remove_node(node.name)
        self._queue.pop(node.name, None)
        started = self._requested_at.pop(node.name, None)
        if started is not None:
            metrics.termination_duration().observe(
                max(0.0, self.clock() - started))
        out.terminated.append(node.name)
        log.info("terminated node %s", node.name)

    def _evict(self, pod: Pod) -> None:
        """Eviction: owned pods are recreated pending by their controller;
        ownerless pods are gone for good."""
        self.cluster.unbind_pod(pod)
        if not pod.owner_kind:
            if self.cluster.pods.pop(pod.uid, None) is not None and \
                    self.cluster.observer is not None:
                self.cluster.observer.pod_removed(pod)
        else:
            # the replacement pod is a fresh arrival — without this, its
            # re-bind would record the pod's whole lifetime as bind latency
            pod.created_at = self.clock()
