"""Garbage collection: leaked cloud capacity and orphaned node objects.

Re-implements the reference's nodeclaim GC
(/root/reference/pkg/controllers/nodeclaim/garbagecollection/controller.go:57-115):
list all cluster-owned cloud instances, terminate any running longer than
the registration grace period with no matching NodeClaim (a "leak" — e.g. a
crash between CreateFleet and claim persistence), and delete Node objects
whose backing instance is gone.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, List

from ..cloud.provider import CloudProvider
from ..utils import metrics
from ..state.cluster import Cluster

log = logging.getLogger("karpenter_tpu.gc")

# Instances younger than this may simply not have registered yet
# (reference: 30s, garbagecollection/controller.go:94-115).
REGISTRATION_GRACE_S = 30.0


@dataclass
class GCResult:
    leaked_instances: List[str] = field(default_factory=list)
    orphaned_nodes: List[str] = field(default_factory=list)


class GarbageCollectionController:
    """Singleton sweep comparing cloud ground truth with cluster state."""

    def __init__(self, provider: CloudProvider, cluster: Cluster,
                 clock: Callable[[], float] = time.time,
                 grace_s: float = REGISTRATION_GRACE_S):
        self.provider = provider
        self.cluster = cluster
        self.clock = clock
        self.grace_s = grace_s

    def reconcile(self) -> GCResult:
        out = GCResult()
        now = self.clock()
        known_ids = {c.provider_id for c in self.cluster.nodeclaims.values()
                     if c.provider_id}
        cloud_claims = self.provider.list()
        cloud_ids = {c.provider_id for c in cloud_claims}

        # leaked instances: cloud capacity nobody claims past the grace period
        for claim in cloud_claims:
            if claim.provider_id in known_ids:
                continue
            if now - claim.launched_at < self.grace_s:
                continue
            try:
                self.provider.delete(claim)
            except Exception:  # noqa: BLE001 — already-gone is success
                pass
            node = self.cluster.node_for_provider_id(claim.provider_id)
            if node is not None:
                self.cluster.remove_node(node.name)
            out.leaked_instances.append(claim.provider_id)
            metrics.consistency_errors().inc({"check": "leaked_instance"})
            log.info("GC: terminated leaked instance %s", claim.provider_id)

        # orphaned nodes: node object outlived its instance (e.g. reclaimed
        # spot capacity) — evict state so pods requeue
        for node in list(self.cluster.nodes.values()):
            if node.provider_id and node.provider_id not in cloud_ids:
                claim = self.cluster.claim_for_provider_id(node.provider_id)
                if claim is not None:
                    self.cluster.nodeclaims.pop(claim.name, None)
                self.cluster.remove_node(node.name)
                out.orphaned_nodes.append(node.name)
                metrics.consistency_errors().inc({"check": "orphaned_node"})
                log.info("GC: removed orphaned node %s", node.name)
        return out


class TaggingController:
    """Post-registration instance tagging
    (/root/reference/pkg/controllers/nodeclaim/tagging/controller.go):
    stamps the node name onto the backing instance once it registers."""

    NODE_NAME_TAG = "karpenter.sh/node-name"

    def __init__(self, provider: CloudProvider, cluster: Cluster):
        self.provider = provider
        self.cluster = cluster

    def reconcile(self) -> List[str]:
        tagged = []
        claim_by_pid = {c.provider_id: c
                        for c in self.cluster.nodeclaims.values()
                        if c.provider_id}
        for node in self.cluster.nodes.values():
            if not node.provider_id:
                continue
            try:
                inst = self.provider.cloud.get_instance(node.provider_id)
            except Exception:  # noqa: BLE001 — instance gone; GC's problem
                continue
            want = {self.NODE_NAME_TAG: node.name}
            # claim identity rides post-launch (fleet tags are pool-scoped
            # so the batcher can merge); re-assert it here in case the
            # launch-path create_tags failed
            claim = claim_by_pid.get(node.provider_id)
            if claim is not None:
                want["karpenter.sh/nodeclaim"] = claim.name
                want["Name"] = f"{claim.nodepool}/{claim.name}"
            missing = {k: v for k, v in want.items()
                       if inst.tags.get(k) != v}
            if missing:
                self.provider.cloud.create_tags(node.provider_id, missing)
                tagged.append(node.provider_id)
        return tagged
