"""NodeClaim lifecycle: launch → registered → initialized, with liveness GC.

Re-implements karpenter-core's nodeclaim lifecycle state machine
(SURVEY.md §2.2 "NodeClaim lifecycle"; observed in-tree via the
registered/initialized status the AWS half consumes at
/root/reference/pkg/cloudprovider/cloudprovider.go:307-339 and the
`karpenter.sh/initialized` label):

  * **launch** — the cloud provider fulfilled the claim (`provider_id` set);
  * **registration** — the node's kubelet joined the cluster.  In this
    substrate the join is signalled by `FakeCloud` instance state plus a
    configurable join delay; a claim that never registers within
    `registration_ttl` (15m, core's liveness default) is terminated and its
    capacity released;
  * **initialization** — a registered node becomes schedulable for
    disruption purposes once its startup taints are cleared and extended
    resources are reported; the node then carries the initialized label.

The provisioner's default path registers synchronously (the fake kubelet
joins instantly); this controller is the asynchronous path the operator
runs, and the one the chaos/liveness tests drive.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..api import labels as wk
from ..api.objects import Node, NodeClaim
from ..catalog.instancetype import effective_instance_type
from ..cloud.provider import CloudProvider
from ..state.cluster import Cluster
from ..utils import metrics
from ..utils.events import Event, Recorder

log = logging.getLogger("karpenter_tpu.lifecycle")

REGISTRATION_TTL = 15 * 60.0  # core liveness: unregistered claims die at 15m


@dataclass
class LifecycleResult:
    registered: List[str] = field(default_factory=list)     # claim names
    initialized: List[str] = field(default_factory=list)    # node names
    liveness_terminated: List[str] = field(default_factory=list)


class LifecycleController:
    """Tracks launched-but-unregistered claims and un-initialized nodes."""

    def __init__(self, provider: CloudProvider, cluster: Cluster,
                 nodepools: Optional[Dict[str, object]] = None,
                 recorder: Optional[Recorder] = None,
                 registration_ttl: float = REGISTRATION_TTL,
                 join_delay: float = 0.0,
                 clock: Callable[[], float] = time.time):
        self.provider = provider
        self.cluster = cluster
        self.nodepools = nodepools or {}
        self.recorder = recorder or Recorder(log=False)
        self.registration_ttl = registration_ttl
        self.join_delay = join_delay  # inf == kubelet never joins (chaos)
        self.clock = clock
        self._pending: Dict[str, NodeClaim] = {}   # claim name → claim
        # instance-type info for allocatable at registration
        self._catalog = {it.name: it for it in provider.instance_types.base_catalog}

    def track(self, claim: NodeClaim) -> None:
        """Adopt a launched claim for asynchronous registration."""
        if claim.launched and not claim.registered:
            self._pending[claim.name] = claim
            self.cluster.nodeclaims[claim.name] = claim

    def reconcile(self) -> LifecycleResult:
        out = LifecycleResult()
        now = self.clock()
        for claim in list(self._pending.values()):
            inst = None
            try:
                inst = self.provider.cloud.get_instance(claim.provider_id)
            except Exception:
                pass
            if inst is None or inst.state != "running":
                # instance died before registering: claim is unrecoverable
                self._liveness_fail(claim, "InstanceTerminated", out)
                continue
            if now - claim.launched_at > self.registration_ttl:
                self._liveness_fail(claim, "RegistrationTimeout", out)
            elif now - claim.launched_at >= self.join_delay:
                self._register(claim, out)
        # initialization pass over registered, un-initialized nodes
        for node in self.cluster.nodes.values():
            claim = self.cluster.claim_for_provider_id(node.provider_id)
            if claim is None or not claim.registered or claim.initialized:
                continue
            self._try_initialize(node, claim, out)
        return out

    # ------------------------------------------------------------------
    def _register(self, claim: NodeClaim, out: LifecycleResult) -> None:
        it = self._catalog.get(claim.instance_type)
        if it is not None:
            ncs = getattr(self.provider, "node_classes", None) or {}
            it = effective_instance_type(
                it, self.nodepools.get(claim.nodepool),
                ncs.get(claim.node_class_ref))
        allocatable = it.allocatable if it else claim.requests
        node = self.cluster.register_nodeclaim(
            claim, allocatable, it.capacity if it else None, initialized=False)
        # registration leaves startup taints in place; initialization clears
        # them (claim was created with pool startup taints included)
        self._pending.pop(claim.name, None)
        out.registered.append(claim.name)
        self.recorder.publish(Event("NodeClaim", claim.name, "Registered",
                                    f"node {node.name} joined"))

    # boot-time taints whose owners (kubelet / cloud-controller) remove them
    # as part of normal startup; the substrate simulation may clear these.
    # Condition taints like node.kubernetes.io/unreachable are NOT listed:
    # auto-clearing them would mask genuine node conditions — initialization
    # simply waits for their owners instead (core initialization semantics).
    EPHEMERAL_STARTUP_TAINTS = frozenset({
        "node.kubernetes.io/not-ready",
        "node.cloudprovider.kubernetes.io/uninitialized",
    })

    def _try_initialize(self, node: Node, claim: NodeClaim,
                        out: LifecycleResult) -> None:
        """Initialized == startup taints cleared ∧ capacity reported
        (core initialization semantics)."""
        pool = self.nodepools.get(claim.nodepool)
        clearable = {t.key for t in pool.template.startup_taints} \
            if pool is not None else set()
        clearable |= {t.key for t in node.taints
                      if t.key in self.EPHEMERAL_STARTUP_TAINTS}
        present = [t for t in node.taints if t.key in clearable]
        if present:
            # the (fake) kubelet/daemons clear declared startup + known
            # ephemeral taints on this pass; initialization completes on the
            # next one (taint clearance and readiness are separate
            # observations in the reference too)
            node.taints = [t for t in node.taints if t.key not in clearable]
            self.cluster.touch_node(node)
            return
        # any remaining node.kubernetes.io/* taint is a live condition
        # (unreachable, disk-pressure…) owned by the node controller — wait,
        # never clear
        if any(t.key.startswith("node.kubernetes.io/") for t in node.taints):
            return
        if not node.allocatable:
            return  # capacity not reported yet
        claim.initialized = True
        claim.initialized_at = self.clock()
        if claim.registered_at:
            metrics.nodeclaim_initialization_duration().observe(
                max(0.0, claim.initialized_at - claim.registered_at))
        metrics.nodeclaims_initialized().inc({"nodepool": claim.nodepool})
        node.labels[wk.NODE_INITIALIZED] = "true"
        self.cluster.touch_node(node)
        # pods that bound while the node was still coming up reach
        # "running on a ready node" now (karpenter_pods_startup_time_seconds)
        for p_ in node.pods:
            if not p_.__dict__.get("_startup_observed"):
                p_.__dict__["_startup_observed"] = True
                metrics.pods_startup_time().observe(
                    max(0.0, self.clock() - p_.created_at))
        out.initialized.append(node.name)
        self.recorder.publish(Event("Node", node.name, "Initialized", ""))

    def _liveness_fail(self, claim: NodeClaim, reason: str,
                       out: LifecycleResult) -> None:
        log.warning("nodeclaim %s liveness failure: %s", claim.name, reason)
        try:
            self.provider.delete(claim)
        except Exception:
            pass
        self.cluster.nodeclaims.pop(claim.name, None)
        self._pending.pop(claim.name, None)
        out.liveness_terminated.append(claim.name)
        metrics.nodeclaims_terminated().inc(
            {"nodepool": claim.nodepool, "reason": reason})
        self.recorder.publish(Event("NodeClaim", claim.name, reason,
                                    "liveness failure", type="Warning"))
