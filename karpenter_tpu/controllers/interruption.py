"""Interruption controller: queue events → offering blacklist + node recycle.

Re-implements the reference's interruption loop
(/root/reference/pkg/controllers/interruption/controller.go:82-121):
receive ≤10 messages, parse via the kind registry
(parser.go:54-80), map instance-id → node/claim, then

  * spot-interruption → mark the offering unavailable (spot ICE,
    controller.go:194-200) AND terminate the node (cordon & drain,
    handleNodeClaim controller.go:181-205);
  * scheduled-change / state-change(stopping|terminated) → terminate;
  * rebalance-recommendation → event only, no action (reference default);
  * noop / unmatched instances → just delete the message.

Messages are deleted only after successful handling, so failures retry on
the next receive (SQS visibility semantics in cloud/queue.py).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..api import labels as wk
from ..api.objects import Node, NodeClaim
from ..cloud.provider import CloudProvider
from ..cloud.queue import (FakeQueue, Message, NOOP, ParsedEvent,
                           REBALANCE_RECOMMENDATION, SCHEDULED_CHANGE,
                           SPOT_INTERRUPTION, STATE_CHANGE, parse_event)
from ..state.cluster import Cluster
from ..utils import metrics
from .termination import TerminationController

log = logging.getLogger("karpenter_tpu.interruption")

# state-change states that mean the instance is going/gone
_DEAD_STATES = {"stopping", "stopped", "shutting-down", "terminated"}


@dataclass
class InterruptionResult:
    received: int = 0
    handled: Dict[str, int] = field(default_factory=dict)   # kind → count
    recycled: List[str] = field(default_factory=list)       # node names
    deleted_messages: int = 0

    def bump(self, kind: str):
        self.handled[kind] = self.handled.get(kind, 0) + 1


class InterruptionController:
    """Singleton poll loop over the interruption queue."""

    def __init__(self, queue: FakeQueue, provider: CloudProvider,
                 cluster: Cluster, terminator: TerminationController,
                 clock: Callable[[], float] = time.time):
        self.queue = queue
        self.provider = provider
        self.cluster = cluster
        self.terminator = terminator
        self.clock = clock
        # optional hook(node_or_claim) fired on each observed spot reclaim
        # — the forecast spot-risk prior subscribes here (operator wiring)
        self.on_spot_reclaim: Optional[Callable] = None

    # ------------------------------------------------------------------
    def reconcile(self, max_batches: int = 1) -> InterruptionResult:
        out = InterruptionResult()
        # visibility timeout: messages whose handling failed last tick are
        # redelivered now so stalled drains (PDBs) eventually complete
        self.queue.release_inflight()
        for _ in range(max_batches):
            messages = self.queue.receive()
            if not messages:
                break
            out.received += len(messages)
            now = self.clock()
            for msg in messages:
                # message-age latency histogram (interruption/metrics.go:53)
                metrics.interruption_message_latency().observe(
                    max(0.0, now - msg.sent_at))
            # instance-id → (node, claim) map built once per batch
            # (makeNodeClaimInstanceIDMap, controller.go:94-101)
            by_id = self._instance_map()
            for msg in messages:
                event = parse_event(msg.body)
                metrics.interruption_received().inc({"message_type": event.kind})
                if self._handle(event, by_id, out):
                    self.queue.delete(msg.receipt)
                    out.deleted_messages += 1
                    metrics.interruption_deleted().inc()
        return out

    def _instance_map(self) -> Dict[str, Tuple[Optional[Node], Optional[NodeClaim]]]:
        out: Dict[str, Tuple[Optional[Node], Optional[NodeClaim]]] = {}
        for claim in self.cluster.nodeclaims.values():
            if claim.provider_id:
                out[claim.provider_id] = (None, claim)
        for node in self.cluster.nodes.values():
            if node.provider_id:
                claim = out.get(node.provider_id, (None, None))[1]
                out[node.provider_id] = (node, claim)
        return out

    # ------------------------------------------------------------------
    def _handle(self, event: ParsedEvent, by_id, out: InterruptionResult) -> bool:
        """Returns True when the message is fully handled (safe to delete)."""
        out.bump(event.kind)
        if event.kind == NOOP:
            return True
        ok = True
        for iid in event.instance_ids:
            node, claim = by_id.get(iid, (None, None))
            if node is None and claim is None:
                continue  # not ours / already gone
            if event.kind == SPOT_INTERRUPTION:
                self._mark_spot_unavailable(node, claim)
                src = node or claim
                if src is not None and self.on_spot_reclaim is not None:
                    self.on_spot_reclaim(src)
            if event.kind == REBALANCE_RECOMMENDATION:
                continue  # observability only, no action (reference default)
            if event.kind == STATE_CHANGE and \
                    event.detail.get("state", "") not in _DEAD_STATES:
                continue
            done = self._recycle(node, claim, event.kind, out)
            ok = done and ok
            if done:
                # count COMPLETED actions only: a PDB-blocked drain leaves
                # the message for redelivery, and counting each retry would
                # inflate one interruption into thousands of "actions"
                metrics.interruption_actions().inc(
                    {"action": f"CordonAndDrain/{event.kind}"})
        return ok

    def _mark_spot_unavailable(self, node: Optional[Node],
                               claim: Optional[NodeClaim]) -> None:
        """An interrupted spot offering is exhausted capacity: blacklist it
        so the next solve avoids relaunching into the same pool
        (controller.go:194-200)."""
        src = node or claim
        if src is None or src.capacity_type != wk.CAPACITY_TYPE_SPOT:
            return
        if src.instance_type and src.zone:
            self.provider.unavailable.mark_unavailable(
                "interruption", src.instance_type, src.zone, src.capacity_type)

    def _recycle(self, node: Optional[Node], claim: Optional[NodeClaim],
                 reason: str, out: InterruptionResult) -> bool:
        """Cordon & drain through the termination flow; evicted pods go
        pending and the provisioner replaces the capacity."""
        # ledger attribution: terminations inside this funnel are spot
        # interruptions, not voluntary consolidation
        from ..obs.ledger import LEDGER
        with LEDGER.decision("interruption"):
            if node is not None:
                res = self.terminator.drain_sync(node, reason=reason)
                if node.name in res.terminated:
                    out.recycled.append(node.name)
                    return True
                return False  # drain stalled (PDBs) — retry via redelivery
            # claim without a node (never registered): delete directly
            if claim is not None:
                try:
                    self.provider.delete(claim)
                except Exception:  # noqa: BLE001 — vanished instance is success
                    pass
                self.cluster.nodeclaims.pop(claim.name, None)
            return True
