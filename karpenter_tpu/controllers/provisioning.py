"""Provisioning controller: pending pods → solver → NodeClaims → launches.

The in-process equivalent of karpenter-core's provisioning controller
(driven in reference tests via `provisioning.NewProvisioner`,
/root/reference/pkg/cloudprovider/suite_test.go:87-88), re-architected
around the batched TPU solve:

  reference:  per-pod FFD loop over Go object graphs (designs/bin-packing.md)
  here:       one tensorize() + one jit-compiled packing kernel per batch,
              existing cluster capacity entering as pre-opened slots.

Emits NodeClaims whose requirements carry the flexible instance-type/zone
candidate lists, so the cloud layer can do CreateFleet-style flexible
launches and ICE fallback (/root/reference/pkg/providers/instance/instance.go:88-105).
"""

from __future__ import annotations

import functools
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..api import labels as wk
from ..api.objects import Node, NodeClaim, NodePool, Pod, pool_view
from ..api.requirements import IN, Requirement, Requirements
from ..api.resources import PODS, ResourceList
from ..catalog.instancetype import effective_instance_type
from ..cloud.provider import (CloudProvider, InsufficientCapacityError,
                              NodeClassNotFoundError)
from ..ops.constraints import (MAX_LEVEL, find_batch_topology_violations,
                               has_soft_constraints, lower_pods,
                               make_zone_feasibility)
from ..ops.classpack import solve_classpack
from ..ops.ffd import (NATIVE_CUTOVER_ROWS, NodeDecision, PackingResult,
                       solve_ffd)
from ..ops.gang import (INCOMPLETE, PARTIAL, GangRegistry, PreemptionPlan,
                        enforce_gangs, plan_preemption)
from ..ops.tensorize import Problem, tensorize
from ..obs.incidents import publish_incident
from ..parallel.driver import maybe_solve_partitioned
from ..state.cluster import Cluster
from ..utils import metrics, tracing
from ..utils.chaos import CHAOS
from ..utils.events import Event
from ..utils.watchdog import WatchdogTimeout, run_with_deadline
from ..utils.provenance import (CAPACITY, ProvenanceRecord,
                                explain_unschedulable)

log = logging.getLogger("karpenter_tpu.provisioning")


@dataclass
class ProvisioningResult:
    launched: List[NodeClaim] = field(default_factory=list)
    bound_existing: int = 0
    unschedulable: List[Pod] = field(default_factory=list)
    failed_launches: List[str] = field(default_factory=list)
    # carriers of batch-internal anti-affinity violations, deferred to a
    # follow-up solve (ops/constraints.py post-solve repair)
    stranded: List[Pod] = field(default_factory=list)
    solve_seconds: float = 0.0

    bound_new: int = 0

    @property
    def scheduled(self) -> int:
        return self.bound_existing + self.bound_new


def _pod_class_map(problem) -> np.ndarray:
    """pod index → class id, built once per Problem (cached on it)."""
    m = getattr(problem, "_pod_class_map", None)
    if m is None:
        m = np.empty(len(problem.pods), np.int64)
        for ci, arr in enumerate(problem.members_arrays()):
            m[arr] = ci
        problem._pod_class_map = m
    return m


def claim_requests_columnar(problem, pod_indices: Sequence[int]) -> ResourceList:
    """One claim's request total as a CLASS-block sum (the DeviceDecode
    columnar NodeClaim path): pods in a tensorize class share one request
    spec, so the total folds count × value per class instead of allocating
    a ResourceList per pod — O(classes-per-node × keys), not O(pods).

    Matches the legacy sequential merge exactly for integer canonical
    quantities (n × int ≡ n sequential adds) with the legacy first-seen
    key order (every pod of a class carries the same key set, so
    first-seen-over-pods equals first-seen-over-classes)."""
    idx = np.asarray(pod_indices, np.int64)
    cseq = _pod_class_map(problem)[idx]
    _, first, cnt = np.unique(cseq, return_index=True, return_counts=True)
    requests = ResourceList()
    for j in np.argsort(first, kind="stable").tolist():
        rep = problem.pods[int(idx[first[j]])].requests
        n = int(cnt[j])
        for k, v in rep.items():
            requests[k] = requests.get(k, 0) + n * v
    requests[PODS] = requests.get(PODS, 0) + len(idx)
    return requests


def claim_from_decision(decision: NodeDecision, pods: Sequence[Pod],
                        pools: Dict[str, NodePool],
                        requests: Optional[ResourceList] = None) -> NodeClaim:
    """NodeDecision → NodeClaim with flexible candidates encoded as
    requirements (the shape CloudProvider.Create consumes,
    /root/reference/pkg/cloudprovider/cloudprovider.go:92-118).

    `requests` short-circuits the per-pod merge when the caller already
    built the total columnar-wise (claim_requests_columnar)."""
    opt = decision.option
    pool = pools[opt.pool]
    alt_types = [a.instance_type for a in decision.alternatives] or [opt.instance_type]
    alt_zones = sorted({a.zone for a in decision.alternatives} | {opt.zone})
    if requests is None:
        requests = ResourceList()
        for p in pods:
            requests = requests + p.requests
        requests[PODS] = requests.get(PODS, 0) + len(pods)
    claim = NodeClaim(
        nodepool=opt.pool,
        # pool requirements ∩ the decision's flexible candidate lists — a
        # claim always satisfies its NodePool's constraints
        requirements=pool.requirements().union(Requirements.of(
            Requirement(wk.INSTANCE_TYPE, IN, alt_types),
            Requirement(wk.ZONE, IN, alt_zones),
            Requirement(wk.CAPACITY_TYPE, IN, [opt.capacity_type]),
            Requirement(wk.NODEPOOL, IN, [opt.pool]),
        )),
        requests=requests,
        taints=list(pool.template.taints) + list(pool.template.startup_taints),
        node_class_ref=pool.template.node_class_ref,
        labels=dict(pool.template.labels),
    )
    claim._decision_pods = list(pods)  # transient: bound after registration
    return claim


class Provisioner:
    """Batch scheduling loop (pod batching windows live in the controller
    runtime; this is the per-batch solve)."""

    def __init__(self, provider: CloudProvider, cluster: Cluster,
                 nodepools,
                 clock: Callable[[], float] = time.time,
                 max_nodes_per_round: int = 2048,
                 solver: str = "auto",
                 lp_guide: bool = True,
                 refinery=None,
                 recorder=None,
                 provenance=None,
                 sharded_solve: bool = False,
                 health=None,
                 watchdog_timeout_s: float = 0.0,
                 device_decode: bool = False,
                 decode_health=None,
                 device_lp: bool = False,
                 lp_health=None,
                 gang_scheduling: bool = False):
        self.provider = provider
        self.cluster = cluster
        self.nodepools = pool_view(nodepools)
        self.clock = clock
        # decision provenance: Warning events through the recorder plus the
        # queryable store behind /debug/pods/<name> (utils/provenance.py)
        self.recorder = recorder
        self.provenance = provenance
        self.max_nodes_per_round = max_nodes_per_round
        self.solver = solver
        # ShardedSolve feature gate: partition fleet-scale batches across
        # devices (parallel/driver.py); maybe_solve_partitioned returns None
        # for small/unshardable batches and the round falls through to the
        # single-device path below.
        self.sharded_solve = sharded_solve
        # degradation ladder (ops/health.py): shared SolverHealth state
        # machine routing the pack step down sharded→jax→native→greedy as
        # rungs fail; None (unit-test default) keeps the legacy direct
        # path.  watchdog_timeout_s > 0 arms a hard deadline per pack call
        # (utils/watchdog.py); 0 is a plain call.
        self.health = health
        self.watchdog_timeout_s = watchdog_timeout_s
        # the LPGuide feature gate: False routes classpack solves straight
        # to the greedy (guide=None) — the operational escape hatch.
        # With a refinery (LPRefinery gate), guide misses never block the
        # tick: cold solves ship greedy/stale plans and the colgen LP
        # refines in the refinery's worker, upgrading the next tick
        # (ops/refinery.py); the manager consumes refinery.take_upgrade()
        # for the one-shot early re-solve.
        self.lp_guide = lp_guide
        self.refinery = refinery if lp_guide else None
        # DeviceLP feature gate: guide misses refine synchronously on the
        # batched PDHG solver (ops/lpsolve.py) with lp_health as the
        # device_lp→highs degradation ladder — the refined mix lands in
        # the SAME tick instead of greedy-now-refined-next-tick.
        self.device_lp = bool(device_lp) and lp_guide
        self.lp_health = lp_health if self.device_lp else None
        if not lp_guide:
            self._classpack = functools.partial(solve_classpack, guide=None)
        elif self.refinery is not None:
            self._classpack = functools.partial(solve_classpack,
                                                refinery=self.refinery)
        else:
            self._classpack = solve_classpack
        if self.device_lp:
            self._classpack = functools.partial(
                self._classpack, device_lp=True, lp_health=self.lp_health)
        # DeviceDecode feature gate: kernel emits the slot-sorted slab and
        # the host assembles plans/NodeClaims columnar-wise (ops/decode.py).
        # The DecodeHealth breaker demotes a failing slab path back to host
        # assembly with a counted outcome; it is snapshot-registered
        # (state/snapshot.py section "decode").
        self.device_decode = bool(device_decode)
        self.decode_health = decode_health
        if self.device_decode:
            self._classpack = functools.partial(
                self._classpack, device_decode=True,
                decode_health=decode_health)
        # GangScheduling feature gate (ops/gang.py): post-solve
        # all-or-nothing enforcement over every packing, plus the
        # preemption-plan queue the DisruptionController drains one plan
        # per tick.  The registry is the snapshot-carried admission ledger
        # (state/snapshot.py section "gang"); None == gate off.
        self.gang_scheduling = bool(gang_scheduling)
        self.gang_registry = GangRegistry() if self.gang_scheduling else None
        self.gang_preemption_plans: Dict[str, PreemptionPlan] = {}

    def _pick_solver(self, problem: Problem, n_existing: int = 0):
        """The flagship class-granular kernel IS the provisioning hot path —
        the exact call bench.py times (VERDICT r1 weak #1: perf claim and
        product path must be the same code). Tiny batches fall back to the
        pod-granular solve, whose native backend finishes before a device
        kernel launch would (ops/ffd.py backend="auto")."""
        if self.solver == "classpack":
            return self._classpack
        if self.solver == "ffd":
            return solve_ffd
        rows = int(problem.class_counts.sum()) + n_existing
        return solve_ffd if rows <= NATIVE_CUTOVER_ROWS else self._classpack

    def _pack_supervised(self, problem: Problem, psp, existing):
        """Run the pack step down the degradation ladder.  Healthy path is
        byte-identical to the legacy direct call (sharded gate → jax);
        with a SolverHealth wired, a watchdog trip or exception falls to
        the next rung inside the SAME solve while the ladder books the
        failure for future ticks.  The greedy rung is never deadline-
        guarded (it is the guaranteed-terminating floor) and its
        exceptions propagate — there is nothing below it."""
        requested = "sharded" if self.sharded_solve else "jax"
        if self.health is None:
            return self._run_rung(requested, problem, psp, existing)
        rung = self.health.active_rung(requested)
        while True:
            timeout = 0.0 if rung == "greedy" else self.watchdog_timeout_s
            try:
                result = run_with_deadline(
                    lambda: self._run_rung(rung, problem, psp, existing),
                    timeout, "provision.solve")
                self.health.report_success(rung)
                return result
            except WatchdogTimeout:
                self.health.report_failure(rung, reason="timeout")
            except Exception:
                self.health.report_failure(rung, reason="error")
                if rung == "greedy":
                    raise
            rung = self.health.active_rung(
                self.health.next_rung(rung) or "greedy")

    def _run_rung(self, rung: str, problem: Problem, psp, existing):
        """One pack attempt on one ladder rung.  A sharded refusal
        (maybe_solve_partitioned → None: batch too small/unshardable) is
        routing, not failure — it falls through to the jax rung inline,
        exactly the legacy gate behavior."""
        CHAOS.inject("solver.pack", key=rung)
        kw: Dict[str, object] = {}
        n_existing = 0
        if existing is not None:
            node_list, alloc, used, compat = existing
            n_existing = len(node_list)
            kw = dict(existing_alloc=alloc, existing_used=used,
                      existing_compat=compat)
        rows = int(problem.class_counts.sum()) + n_existing
        if rung == "sharded":
            result = maybe_solve_partitioned(
                problem, path="provisioning",
                max_nodes=self.max_nodes_per_round,
                device_decode=self.device_decode,
                decode_health=self.decode_health,
                **(dict(kw, node_list=existing[0])
                   if existing is not None else {}))
            if result is not None:
                psp.annotate(solver="sharded", rows=rows)
                return result
            rung = "jax"
        if rung == "jax":
            solve = self._pick_solver(problem, n_existing=n_existing)
            psp.annotate(solver="ffd" if solve is solve_ffd else "classpack",
                         rows=rows)
            return solve(problem, max_nodes=self.max_nodes_per_round, **kw)
        if rung == "native":
            from .. import native
            if not native.available():
                raise RuntimeError("native packer unavailable on this host")
            psp.annotate(solver="native", rows=rows)
            return solve_ffd(problem, max_nodes=self.max_nodes_per_round,
                             backend="native", **kw)
        psp.annotate(solver="greedy", rows=rows)
        return solve_ffd(problem, max_nodes=self.max_nodes_per_round,
                         backend="numpy", **kw)

    def _pools_within_limits(self) -> List[NodePool]:
        usage = self.cluster.nodepool_usage()
        # usage/limit gauges (reference karpenter_nodepool_usage / _limit
        # families).  Series set last round but absent now (pool drained,
        # resource gone) are deleted so /metrics never reports stale values.
        usage_g, limit_g = metrics.nodepool_usage(), metrics.nodepool_limit()
        prev_u = getattr(self, "_usage_gauge_keys", set())
        prev_l = getattr(self, "_limit_gauge_keys", set())
        cur_u, cur_l = set(), set()
        # usage covers every pool with LIVE capacity — including pools
        # removed from config mid-drain, which still hold launched resources
        # (nodes_total keeps those series too; the two families must agree)
        # sorted: sample emission order must not depend on set hashing
        # (graftlint DT003 — /metrics exposition is byte-compared in tests)
        for pool_name in sorted(set(usage) | set(self.nodepools)):
            for res, qty in usage.get(pool_name, ResourceList()).items():
                usage_g.set(qty, {"nodepool": pool_name, "resource_type": res})
                cur_u.add((pool_name, res))
        pct_g = metrics.nodepool_usage_pct()
        out = []
        for pool in self.nodepools.values():
            pool_usage = usage.get(pool.name, ResourceList())
            for res, qty in (pool.limits or {}).items():
                limit_g.set(qty, {"nodepool": pool.name, "resource_type": res})
                pct_g.set(100.0 * pool_usage.get(res, 0) / qty if qty else 0.0,
                          {"nodepool": pool.name, "resource_type": res})
                cur_l.add((pool.name, res))
            if pool.within_limits(pool_usage):
                out.append(pool)
            else:
                log.info("nodepool %s at limit, excluded from provisioning", pool.name)
        for pool_name, res in sorted(prev_u - cur_u):
            usage_g.delete({"nodepool": pool_name, "resource_type": res})
        for pool_name, res in sorted(prev_l - cur_l):
            limit_g.delete({"nodepool": pool_name, "resource_type": res})
            pct_g.delete({"nodepool": pool_name, "resource_type": res})
        self._usage_gauge_keys = cur_u
        self._limit_gauge_keys = cur_l
        return out

    def solve(self, pods: Sequence[Pod],
              schedule_on_existing: bool = True,
              nodes: Optional[Sequence] = None,
              pools: Optional[List[NodePool]] = None) -> tuple:
        """Tensorize + pack one batch, relaxing soft constraints level by
        level (preferred affinity, ScheduleAnyway spreads) while pods come
        back unschedulable — the batched analog of karpenter-core's
        preference-relaxation loop (see ops/constraints.py).
        Returns (problem, PackingResult).

        `nodes`/`pools` override the live cluster's node set and the
        limit-filtered pool list — a caller holding a point-in-time
        snapshot (`Cluster.snapshot_nodes` + `_pools_within_limits` under
        the state lock) can solve without the lock while the tick loop
        keeps mutating real state (`_pools_within_limits` itself iterates
        live nodes and updates gauge bookkeeping, so it must never run
        off-lock)."""
        if pools is None:
            pools = self._pools_within_limits()  # weight precedence is encoded
        catalog = self.provider.get_instance_types()  # in LaunchOption.weight_rank
        node_view = (list(self.cluster.nodes.values()) if nodes is None
                     else list(nodes))
        zone_rank: Dict[str, float] = {}
        for it in catalog:
            for o in it.offerings:
                if o.available:
                    zone_rank[o.zone] = min(zone_rank.get(o.zone, float("inf")),
                                            o.price)
        # existing-node zones count as spread/affinity domains even when no
        # offering is currently available there (e.g. ICE-blacklisted): a
        # constrained pod can still bind to live capacity in that zone
        zones = sorted(set(zone_rank) | {n.zone for n in node_view if n.zone})
        soft = has_soft_constraints(pods)
        zone_feasible = make_zone_feasibility(catalog, node_view)
        best = None
        for level in range(MAX_LEVEL + 1):
            with tracing.span("solve.tensorize", level=level) as tsp:
                lowered = lower_pods(pods, nodes=node_view,
                                     option_zones=zones, zone_rank=zone_rank,
                                     level=level, zone_feasible=zone_feasible)
                problem = tensorize(lowered, catalog, pools,
                                    node_classes=getattr(self.provider,
                                                         "node_classes", None))
                tsp.annotate(pods=len(pods), classes=problem.num_classes,
                             options=problem.num_options)
            with tracing.span("solve.pack", level=level) as psp:
                existing = None
                if schedule_on_existing and node_view:
                    # warm arena gather only for the LIVE node set (nodes is
                    # None ⇒ node_view IS cluster.nodes.values(), under the
                    # state lock); snapshot solves keep the full path — the
                    # slab mirrors live state, not the caller's snapshot
                    gathered = None
                    if (nodes is None
                            and getattr(self.cluster, "arena", None) is not None):
                        gathered = self.cluster.arena.gather(
                            problem.class_reps, problem.axes,
                            scales=problem.scales)
                    if gathered is None:
                        gathered = self.cluster.tensorize_nodes(
                            problem.class_reps, problem.axes,
                            scales=problem.scales, nodes=node_view)
                    existing = gathered  # (node_list, alloc, used, compat)
                result = self._pack_supervised(problem, psp, existing)
                result._existing_nodes = existing[0] if existing else []
                if self.gang_scheduling and problem.class_gang is not None:
                    # all-or-nothing admission happens HERE, before the
                    # plan is visible to any bind/launch consumer — no
                    # partial gang ever reaches claim_requests
                    self._enforce_gangs(problem, result, node_view)
                psp.annotate(scheduled=result.scheduled_count,
                             unschedulable=len(result.unschedulable))
            if best is None or result.scheduled_count > best[1].scheduled_count:
                best = (problem, result)
            if not result.unschedulable or not soft:
                break
            if level < MAX_LEVEL:
                log.info("relaxing soft constraints to level %d (%d unschedulable)",
                         level + 1, len(result.unschedulable))
        return best

    def _enforce_gangs(self, problem, result, node_view) -> None:
        """Gang admission funnel (GangScheduling): audit + strip rejected
        gangs from the packing, count the verdicts, and queue preemption
        plans for outranked capacity.  Rejections publish a `gang_rejected`
        incident in the same function as the counter inc (graftlint
        OB006)."""
        t0 = self.clock()
        audits = enforce_gangs(problem, result, result._existing_nodes,
                               registry=self.gang_registry,
                               cluster_nodes=node_view)
        partial = 0
        for a in audits:
            if a.admitted:
                metrics.gang_admissions().inc({"tier": str(a.gang.tier)})
                # a gang that now fits no longer needs its queued evictions
                self.gang_preemption_plans.pop(a.gang.name, None)
                continue
            metrics.gang_rejections().inc({"reason": a.reason})
            publish_incident("gang_rejected",
                             {"gang": a.gang.name, "reason": a.reason,
                              "placed": len(a.placed),
                              "arrived": len(a.members),
                              "size": a.gang.size, "tier": a.gang.tier})
            if a.reason == PARTIAL:
                partial += 1
            # priority cascade: a rejected gang with standing (every
            # member present — pending or still bound — and tier > 0)
            # simulates evicting strictly-lower-tier pods; the
            # DisruptionController executes one plan per tick and the
            # REAL solver re-admits the gang on a later round.  Bound
            # residents pin the domain: stragglers must rejoin where the
            # rest of the gang lives, or they'd come back a straddle.
            if (a.gang.tier > 0 and a.reason != INCOMPLETE
                    and a.gang.name not in self.gang_preemption_plans):
                plan = plan_preemption(
                    a.gang, [problem.pods[i].requests for i in a.members],
                    node_view, pin_domains=a.bound_domains)
                if plan is not None and plan.victims:
                    self.gang_preemption_plans[a.gang.name] = plan
        metrics.gang_partial_placeable().set(partial)
        metrics.gang_solve_duration().observe(max(0.0, self.clock() - t0))

    def take_preemption_plan(self) -> Optional[PreemptionPlan]:
        """Pop the oldest queued gang preemption plan (FIFO — insertion
        order is rejection order).  The DisruptionController's per-tick
        drain; None when the queue is empty or the gate is off."""
        if not self.gang_preemption_plans:
            return None
        name = next(iter(self.gang_preemption_plans))
        plan = self.gang_preemption_plans.pop(name)
        if self.gang_registry is not None:
            self.gang_registry.record_preemption(plan.gang,
                                                 len(plan.victims))
        return plan

    def provision(self, pods: Optional[Sequence[Pod]] = None,
                  max_retries: int = 1) -> ProvisioningResult:
        """One provisioning round: solve the batch, launch, register, bind.

        If launches fail on exhausted capacity, the round re-solves once
        against the now-ICE-masked catalog (the reference reaches the same
        fixpoint via its retry-on-next-reconcile plus the launch-path retry
        at /root/reference/pkg/providers/instance/instance.go:96-100)."""
        with tracing.span("provision") as root:
            out = self._provision(pods, max_retries)
            root.annotate(launched=len(out.launched), bound=out.scheduled,
                          unschedulable=len(out.unschedulable),
                          failed_launches=len(out.failed_launches))
            return out

    def _provision(self, pods, max_retries) -> ProvisioningResult:
        out = self._provision_once(pods)
        retries = 0
        while out.failed_launches and out.unschedulable and retries < max_retries:
            retries += 1
            retry = self._provision_once([p for p in out.unschedulable
                                          if not p.node_name])
            out.launched.extend(retry.launched)
            out.bound_existing += retry.bound_existing
            out.bound_new += retry.bound_new
            out.unschedulable = retry.unschedulable
            out.failed_launches.extend(retry.failed_launches)
            out.stranded.extend(retry.stranded)
        # anti-affinity carriers stranded by the post-solve repair: their
        # targets are now bound, so one follow-up solve sees them as
        # existing pods and the NotIn lowering applies
        strand_rounds = 0
        while out.stranded and strand_rounds < 2:
            strand_rounds += 1
            retry = self._provision_once([p for p in out.stranded
                                          if not p.node_name])
            out.launched.extend(retry.launched)
            out.bound_existing += retry.bound_existing
            out.bound_new += retry.bound_new
            out.unschedulable.extend(retry.unschedulable)
            out.failed_launches.extend(retry.failed_launches)
            out.stranded = retry.stranded
        metrics.pods_unschedulable().set(len(out.unschedulable))
        counts: Dict[str, int] = {}
        for node in self.cluster.nodes.values():
            counts[node.nodepool] = counts.get(node.nodepool, 0) + 1
        nodes_g = metrics.nodes_total()
        # every known pool gets a sample (0 after draining — not a stale
        # count); series for pools gone from BOTH config and cluster drop
        cur = set(self.nodepools) | set(counts)
        for pool_name in sorted(cur):   # deterministic sample order (DT003)
            nodes_g.set(counts.get(pool_name, 0), {"nodepool": pool_name})
        for pool_name in sorted(getattr(self, "_nodes_gauge_keys", set()) - cur):
            nodes_g.delete({"nodepool": pool_name})
        self._nodes_gauge_keys = cur
        return out

    def _provision_once(self, pods: Optional[Sequence[Pod]] = None) -> ProvisioningResult:
        with tracing.span("provision.round") as sp:
            out = self._provision_round(pods)
            sp.annotate(bound=out.scheduled,
                        unschedulable=len(out.unschedulable))
            return out

    def _provision_round(self, pods: Optional[Sequence[Pod]] = None) -> ProvisioningResult:
        t0 = self.clock()
        out = ProvisioningResult()
        if pods is None:
            pods = self.cluster.pending_pods()
        if not pods:
            return out
        if not self.nodepools:
            out.unschedulable = list(pods)
            return out
        problem, packing = self.solve(pods)
        out.solve_seconds = self.clock() - t0

        with tracing.span("provision.launch") as lsp:
            catalog_by_name = {it.name: it
                               for it in self.provider.get_instance_types()}

            orig = self.cluster.original

            # batch-internal anti-affinity/spread the masks couldn't see:
            # strand the violating carriers; they re-solve against bound
            # targets
            stranded = find_batch_topology_violations(
                problem, packing, packing._existing_nodes)
            out.stranded = [orig(problem.pods[i]) for i in stranded]

            # pods placed on existing nodes
            for pod_i, slot in packing.existing_assignments.items():
                if pod_i in stranded:
                    continue
                node = packing._existing_nodes[slot]
                pod = orig(problem.pods[pod_i])
                self.cluster.bind_pod(pod, node.name)
                if self.provenance is not None:
                    self.provenance.clear(pod.name)
                out.bound_existing += 1

            # new nodes
            for decision in packing.nodes:
                if stranded:
                    decision.pod_indices = [i for i in decision.pod_indices
                                            if i not in stranded]
                    if not decision.pod_indices:
                        continue
                dpods = [orig(problem.pods[i]) for i in decision.pod_indices]
                creq = (claim_requests_columnar(problem,
                                                decision.pod_indices)
                        if self.device_decode else None)
                claim = claim_from_decision(decision, dpods, self.nodepools,
                                            requests=creq)
                try:
                    claim = self.provider.create(claim)
                except InsufficientCapacityError as e:
                    # leave pods pending; ICE cache updated inside create() so the
                    # next round solves against a corrected catalog. A missing
                    # nodeclass is a persistent config error, not capacity — log
                    # it at error so operators see it isn't self-healing.
                    if isinstance(e, NodeClassNotFoundError):
                        log.error("launch blocked by configuration: %s", e)
                    else:
                        log.warning("launch failed: %s", e)
                    out.failed_launches.append(str(e))
                    out.unschedulable.extend(dpods)
                    self._record_provenance(
                        [ProvenanceRecord(pod=p.name, constraint=CAPACITY,
                                          message=f"launch failed: {e}")
                         for p in dpods])
                    continue
                it = catalog_by_name.get(claim.instance_type)
                if it is not None:
                    ncs = getattr(self.provider, "node_classes", None) or {}
                    it = effective_instance_type(
                        it, self.nodepools.get(claim.nodepool),
                        ncs.get(claim.node_class_ref))
                allocatable = it.allocatable if it else claim.requests
                node = self.cluster.register_nodeclaim(claim, allocatable,
                                                       it.capacity if it else None)
                for p in dpods:
                    self.cluster.bind_pod(p, node.name)
                    if self.provenance is not None:
                        self.provenance.clear(p.name)
                out.bound_new += len(dpods)
                out.launched.append(claim)
            lsp.annotate(launched=len(out.launched),
                         failed=len(out.failed_launches))

        out.unschedulable.extend(orig(problem.pods[i])
                                 for i in packing.unschedulable)
        if packing.unschedulable and (self.provenance is not None
                                      or self.recorder is not None):
            with tracing.span("provision.provenance",
                              pods=len(packing.unschedulable)):
                self._record_provenance(
                    [explain_unschedulable(problem, i)
                     for i in packing.unschedulable])
        # scheduling-duration observability (karpenter_provisioner_* families,
        # metrics.md:146-149); the unschedulable gauge is set once per
        # provision() from the aggregated result, not per sub-round
        metrics.scheduling_duration().observe(out.solve_seconds)
        return out

    def _record_provenance(self, records: Sequence[ProvenanceRecord]) -> None:
        """Land unschedulability records in the queryable store and mirror
        them as Warning events (the reference's FailedScheduling surface)."""
        for rec in records:
            if self.provenance is not None:
                self.provenance.record(rec)
            if self.recorder is not None:
                self.recorder.publish(Event(
                    kind="Pod", name=rec.pod, reason="FailedScheduling",
                    message=(f"{rec.constraint}"
                             + (f"/{rec.dimension}" if rec.dimension else "")
                             + f": {rec.message}"),
                    type="Warning"))
