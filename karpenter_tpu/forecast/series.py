"""Per-pod-class demand series: the forecaster's observation stream.

A `DemandSeries` is the `Cluster.observer` hook target: every pod
admission, deletion, and first bind lands here (headroom placeholders are
excluded — the forecaster must never learn from its own output).  The
series tracks live concurrency per pod class and, on each bucket boundary
of the injectable clock, appends the current concurrency to a bounded ring
— so `values(cls)` is a fixed-cadence concurrency time series the models
in `model.py` consume directly.

Pod classes come from the workload's own identity label when present (the
simulator stamps ``sim.karpenter.sh/wave``; a live deployment can reuse
it) and otherwise from a power-of-two resource-shape bucket, so arbitrary
request mixes collapse into a bounded class set.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from ..utils import metrics
from .headroom import is_headroom

# the simulator's wave identity label doubles as the class key; any live
# workload labelled the same way gets per-stream forecasts for free
WAVE_LABEL = "sim.karpenter.sh/wave"

# classes beyond the cap fold into one bucket so memory stays bounded no
# matter how many distinct shapes arrive
OVERFLOW_CLASS = "other"

# default ring: 24h of 60s buckets
DEFAULT_BUCKET_S = 60.0
DEFAULT_CAPACITY = 1440


def pod_class(pod) -> str:
    """Stable demand-class key for a pod: its wave label when present,
    else a log2 resource-shape bucket (cpu millicores × memory MiB)."""
    wave = pod.labels.get(WAVE_LABEL, "")
    if wave:
        return wave
    cpu = max(1.0, float(pod.requests.get("cpu", 0)))
    mem = max(1.0, float(pod.requests.get("memory", 0)) / 2 ** 20)
    return f"c{int(math.log2(cpu))}m{int(math.log2(mem))}"


class DemandSeries:
    """Bounded ring of per-class concurrency samples on the injectable
    clock.  All mutation happens through the observer interface
    (`pod_added`/`pod_removed`/`pod_bound`), called by `Cluster` under the
    operator's state lock — no locking of its own."""

    def __init__(self, bucket_s: float = DEFAULT_BUCKET_S,
                 capacity: int = DEFAULT_CAPACITY,
                 clock: Callable[[], float] = time.time,
                 max_classes: int = 64):
        self.bucket_s = float(bucket_s)
        self.capacity = int(capacity)
        self.clock = clock
        self.max_classes = int(max_classes)
        self._live: Dict[str, int] = {}          # class → live concurrency
        self._ring: Dict[str, Deque[float]] = {}  # class → closed buckets
        self._req: Dict[str, List[float]] = {}   # class → [cpu_sum, mem_sum, n]
        self._bind_latency: Deque[float] = deque(maxlen=256)
        self._bucket_end: Optional[float] = None

    # ------------------------------------------------------------------
    # bucket bookkeeping
    # ------------------------------------------------------------------
    def advance(self, now: Optional[float] = None) -> None:
        """Roll the ring forward to `now`: every elapsed bucket boundary
        closes with the concurrency that was live at its end.  Catch-up is
        bounded by the ring capacity — older buckets would roll off anyway."""
        now = self.clock() if now is None else now
        if self._bucket_end is None:
            self._bucket_end = \
                (math.floor(now / self.bucket_s) + 1) * self.bucket_s
            return
        steps = 0
        while now >= self._bucket_end and steps < self.capacity:
            for cls, ring in self._ring.items():
                ring.append(float(self._live.get(cls, 0)))
            self._bucket_end += self.bucket_s
            steps += 1
        if now >= self._bucket_end:
            self._bucket_end = \
                (math.floor(now / self.bucket_s) + 1) * self.bucket_s

    def _class_for(self, pod) -> str:
        cls = pod_class(pod)
        if cls not in self._ring and len(self._ring) >= self.max_classes:
            return OVERFLOW_CLASS
        return cls

    def _ensure(self, cls: str) -> None:
        if cls not in self._ring:
            self._ring[cls] = deque(maxlen=self.capacity)
            self._live.setdefault(cls, 0)

    # ------------------------------------------------------------------
    # observer interface (Cluster.observer)
    # ------------------------------------------------------------------
    def pod_added(self, pod) -> None:
        if is_headroom(pod):
            return
        self.advance()
        cls = self._class_for(pod)
        self._ensure(cls)
        self._live[cls] = self._live.get(cls, 0) + 1
        req = self._req.setdefault(cls, [0.0, 0.0, 0.0])
        req[0] += float(pod.requests.get("cpu", 0))
        req[1] += float(pod.requests.get("memory", 0))
        req[2] += 1.0
        metrics.forecast_series_observations().inc({"kind": "arrival"})

    def pod_removed(self, pod) -> None:
        if is_headroom(pod):
            return
        self.advance()
        cls = self._class_for(pod)
        if cls in self._live:
            self._live[cls] = max(0, self._live[cls] - 1)
        metrics.forecast_series_observations().inc({"kind": "departure"})

    def pod_bound(self, pod) -> None:
        if is_headroom(pod):
            return
        self._bind_latency.append(
            max(0.0, self.clock() - pod.created_at))
        metrics.forecast_series_observations().inc({"kind": "bind"})

    # ------------------------------------------------------------------
    # read side (HeadroomController / models)
    # ------------------------------------------------------------------
    def classes(self) -> List[str]:
        return sorted(self._ring)

    def live(self, cls: str) -> int:
        return self._live.get(cls, 0)

    def values(self, cls: str) -> np.ndarray:
        """Closed buckets plus the in-flight bucket's live count as the
        freshest sample, as float64 — the models' input."""
        ring = self._ring.get(cls)
        vals = list(ring) if ring else []
        vals.append(float(self._live.get(cls, 0)))
        return np.asarray(vals, dtype=np.float64)

    def mean_request(self, cls: str) -> Tuple[float, float]:
        """Running mean (cpu millicores, memory bytes) of the class's
        observed requests — the placeholder sizing signal."""
        req = self._req.get(cls)
        if not req or req[2] <= 0:
            return (0.0, 0.0)
        return (req[0] / req[2], req[1] / req[2])

    def recent_bind_latency(self) -> float:
        """Mean of the recent first-bind latencies — how long reactive
        provisioning is currently taking, a diagnostic for lead tuning."""
        if not self._bind_latency:
            return 0.0
        return sum(self._bind_latency) / len(self._bind_latency)

    # ------------------------------------------------------------------
    # warm restart (state/snapshot.py)
    # ------------------------------------------------------------------
    def snapshot_state(self) -> Dict:
        """Plain-data export of the whole observation state — rings as
        lists (deque maxlen re-applies on restore)."""
        return {
            "live": dict(self._live),
            "ring": {cls: list(ring) for cls, ring in self._ring.items()},
            "req": {cls: list(v) for cls, v in self._req.items()},
            "bind_latency": list(self._bind_latency),
            "bucket_end": self._bucket_end,
        }

    def restore_state(self, data: Dict) -> None:
        self._live = dict(data["live"])
        self._ring = {cls: deque(vals, maxlen=self.capacity)
                      for cls, vals in data["ring"].items()}
        self._req = {cls: list(v) for cls, v in data["req"].items()}
        self._bind_latency = deque(data["bind_latency"], maxlen=256)
        self._bucket_end = data["bucket_end"]
