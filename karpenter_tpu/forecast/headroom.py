"""HeadroomController: forecast envelope → low-priority placeholder claims.

The proactive half of the forecast subsystem.  Each reconcile:

  1. expires placeholders whose TTL lapsed (their nodes drain back through
     the normal emptiness sweep);
  2. forecasts each demand class over [lead, lead + horizon] and targets
     the upper confidence band;
  3. materializes the shortfall as *placeholder pods* — ownerless,
     negative-priority, TTL-annotated — sized from the class's observed
     request mean, steered to on-demand capacity when the spot-risk prior
     says the pool's reclaim rate is hot;
  4. budget-checks the batch with a dry-run `Provisioner.solve` against a
     node snapshot (the same batched classpack path real pods take, so
     headroom is cost-optimal) and trims deterministically to the cost cap;
  5. admits the survivors as pending pods — the very next provisioning
     tick places them like any other workload.

Placeholders yield instantly: the manager calls `preempt_for_pending()`
right before every provisioning solve, deleting pending placeholders and
evicting bound ones until the freed capacity covers the real pending
demand.  Unexpired placeholders block the disruption sweep
(protected-by-TTL, see controllers/disruption.py) so consolidation never
reaps capacity the forecaster just bought.
"""

from __future__ import annotations

import itertools
import logging
import math
import re
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..api import labels as wk
from ..api.objects import Pod
from ..api.resources import CPU, MEMORY, ResourceList
from ..utils import metrics, tracing
from ..utils.events import Event

log = logging.getLogger("karpenter_tpu.forecast")

# identity + protection markers on placeholder pods
HEADROOM_LABEL = "karpenter.sh/headroom"
HEADROOM_CLASS_LABEL = "karpenter.sh/headroom-class"
HEADROOM_EXPIRY_ANNOTATION = "karpenter.sh/headroom-expiry"
# below every real workload: anything outranks a placeholder
HEADROOM_PRIORITY = -1000

_SAFE_NAME = re.compile(r"[^a-z0-9-]+")


def is_headroom(pod) -> bool:
    return pod.labels.get(HEADROOM_LABEL, "") == "true"


def headroom_expiry(pod) -> Optional[float]:
    """TTL deadline of a placeholder (virtual-time float), None for real
    pods or malformed annotations."""
    raw = pod.annotations.get(HEADROOM_EXPIRY_ANNOTATION)
    if raw is None:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


@dataclass
class HeadroomConfig:
    """Tuning knobs (docs/forecast.md#tuning); defaults mirror
    operator/options.py so CLI flags and scenario specs agree."""
    horizon_s: float = 900.0        # forecast window length
    lead_s: float = 180.0           # how far ahead the window starts
    ttl_s: float = 600.0            # placeholder lifetime
    bucket_s: float = 60.0          # series bucket (envelope step size)
    confidence: float = 1.64        # z for the upper band (~p95)
    max_cost_frac: float = 0.10     # new-node $/h cap vs current rate
    min_budget_per_h: float = 1.0   # absolute floor so cold clusters warm up
    model: str = "holtwinters"
    season_s: float = 86_400.0      # diurnal by default
    spot_risk_threshold: float = 0.15   # reclaims per spot node-hour
    max_placeholders_per_class: int = 50
    # issuance smoothing: cap placeholders admitted per reconcile so the
    # dry-run solves small batches — small batches pack onto small, cheap,
    # easily-reaped instances instead of tempting the solver into large
    # ones that sit half-empty after the burst passes
    max_issue_per_reconcile: int = 6


@dataclass
class ForecastResult:
    """One reconcile's outcome (the manager's results map entry)."""
    issued: int = 0
    expired: int = 0
    trimmed: int = 0
    targets: Dict[str, float] = field(default_factory=dict)


class SpotRiskPrior:
    """Per-nodepool spot reclaim-rate belief: observed reclaims over
    accrued spot node-hours with a Beta-style prior (a0 reclaims / b0
    hours), so a pool with no history starts at a low rate instead of
    zero or infinity.  Reclaim observations arrive via the interruption
    controller's `on_spot_reclaim` hook; hours accrue each reconcile."""

    def __init__(self, prior_reclaims: float = 1.0,
                 prior_node_hours: float = 20.0):
        self.a0 = float(prior_reclaims)
        self.b0 = float(prior_node_hours)
        self._reclaims: Dict[str, int] = {}
        self._node_hours: Dict[str, float] = {}
        self._last_accrue: Optional[float] = None

    def observe_reclaim(self, src) -> None:
        """Hook target: `src` is the interrupted Node or NodeClaim."""
        pool = getattr(src, "nodepool", "") or "default"
        self._reclaims[pool] = self._reclaims.get(pool, 0) + 1

    def accrue(self, nodes, now: float) -> None:
        if self._last_accrue is None:
            self._last_accrue = now
            return
        dt_h = max(0.0, now - self._last_accrue) / 3600.0
        self._last_accrue = now
        if dt_h <= 0:
            return
        for n in nodes:
            if n.capacity_type == wk.CAPACITY_TYPE_SPOT:
                pool = n.nodepool or "default"
                self._node_hours[pool] = \
                    self._node_hours.get(pool, 0.0) + dt_h

    def rate(self, pool: str) -> float:
        return (self._reclaims.get(pool, 0) + self.a0) / \
            (self._node_hours.get(pool, 0.0) + self.b0)

    def max_rate(self) -> float:
        pools = set(self._reclaims) | set(self._node_hours) | {"default"}
        # commutative max reduction: order-insensitive
        # graftlint: disable=DT003
        return max(self.rate(p) for p in pools)


class HeadroomController:
    """Reconciles forecast demand into placeholder capacity.  Runs on the
    manager's cadence under the shared state lock, like every other
    controller."""

    def __init__(self, provisioner, cluster, nodepools, series, forecaster,
                 clock: Callable[[], float] = time.time,
                 config: Optional[HeadroomConfig] = None,
                 recorder=None):
        from ..utils.events import Recorder
        self.provisioner = provisioner
        self.cluster = cluster
        self.nodepools = nodepools
        self.series = series
        self.forecaster = forecaster
        self.clock = clock
        self.config = config or HeadroomConfig()
        self.recorder = recorder or Recorder(log=False)
        self.spot_prior = SpotRiskPrior()
        # instance-level sequence: fresh per controller, so sim runs that
        # rebuild the stack get deterministic placeholder names
        self._seq = itertools.count(1)
        self.stats = {"issued": 0, "expired": 0, "preempted": 0,
                      "trimmed": 0, "peak_live": 0, "reconciles": 0}

    # ------------------------------------------------------------------
    def headroom_pods(self) -> List[Pod]:
        return sorted((p for p in self.cluster.pods.values()
                       if is_headroom(p)), key=lambda p: p.name)

    # ------------------------------------------------------------------
    def reconcile(self) -> ForecastResult:
        with tracing.span("forecast.reconcile") as sp:
            out = self._reconcile()
            sp.annotate(issued=out.issued, expired=out.expired,
                        trimmed=out.trimmed)
            return out

    def _reconcile(self) -> ForecastResult:
        now = self.clock()
        cfg = self.config
        out = ForecastResult()
        self.stats["reconciles"] += 1
        self.series.advance(now)
        self.spot_prior.accrue(self.cluster.nodes.values(), now)
        for pool in sorted(set(self.spot_prior._reclaims)
                           | set(self.spot_prior._node_hours)):
            metrics.forecast_spot_risk().set(
                self.spot_prior.rate(pool), {"nodepool": pool})

        out.expired = self._expire(now)

        # live placeholders per class (pending + bound, unexpired)
        live_headroom: Dict[str, int] = {}
        for p in self.headroom_pods():
            cls = p.labels.get(HEADROOM_CLASS_LABEL, "")
            live_headroom[cls] = live_headroom.get(cls, 0) + 1

        bucket = max(self.series.bucket_s, 1e-9)
        lead_steps = max(1, int(math.ceil(cfg.lead_s / bucket)))
        steps = lead_steps + max(1, int(math.ceil(cfg.horizon_s / bucket)))
        prefer_on_demand = \
            self.spot_prior.max_rate() > cfg.spot_risk_threshold

        candidates: List[Pod] = []
        with tracing.span("forecast.model", classes=len(
                self.series.classes())) as msp:
            for cls in self.series.classes():
                values = self.series.values(cls)
                env = self.forecaster.forecast(values, steps,
                                               z=cfg.confidence)
                target = float(np.max(env.upper[lead_steps - 1:])) \
                    if env.steps else 0.0
                out.targets[cls] = target
                metrics.forecast_demand_upper().set(
                    target, {"pod_class": cls})
                # residual of the freshest one-step prediction vs reality:
                # |mean[0] - current live| is a cheap online fit signal
                if env.steps:
                    metrics.forecast_model_residual().observe(
                        abs(float(env.mean[0]) - self.series.live(cls)),
                        {"model": getattr(self.forecaster, "name", "?")})
                need = int(math.ceil(target)) - self.series.live(cls) \
                    - live_headroom.get(cls, 0)
                need = min(need, cfg.max_placeholders_per_class)
                if need <= 0:
                    continue
                cpu, mem = self.series.mean_request(cls)
                if cpu <= 0 and mem <= 0:
                    continue
                candidates.extend(
                    self._placeholder(cls, cpu, mem, now, prefer_on_demand)
                    for _ in range(need))
            msp.annotate(candidates=len(candidates))

        if len(candidates) > cfg.max_issue_per_reconcile:
            # deterministic round-robin across classes (candidates are
            # grouped per class in sorted-class order) so one hot class
            # cannot starve the others under the cap
            by_cls: Dict[str, List[Pod]] = {}
            for p in candidates:
                by_cls.setdefault(
                    p.labels[HEADROOM_CLASS_LABEL], []).append(p)
            picked: List[Pod] = []
            while len(picked) < cfg.max_issue_per_reconcile:
                progressed = False
                for cls in sorted(by_cls):
                    if by_cls[cls] and \
                            len(picked) < cfg.max_issue_per_reconcile:
                        picked.append(by_cls[cls].pop(0))
                        progressed = True
                if not progressed:
                    break
            candidates = picked

        if candidates:
            kept = self._within_budget(candidates, out)
            if kept:
                self.cluster.add_pods(kept)
                out.issued = len(kept)
                self.stats["issued"] += len(kept)
                metrics.forecast_placeholders().inc(
                    {"outcome": "issued"}, by=len(kept))
                self.recorder.publish(Event(
                    "Forecast", "headroom", "HeadroomIssued",
                    f"issued {len(kept)} placeholder(s) toward "
                    f"forecast demand"))

        live_now = sum(1 for p in self.cluster.pods.values()
                       if is_headroom(p))
        self.stats["peak_live"] = max(self.stats["peak_live"], live_now)
        metrics.forecast_headroom_pods().set(live_now)
        return out

    # ------------------------------------------------------------------
    def _expire(self, now: float) -> int:
        expired = [p for p in self.headroom_pods()
                   if (headroom_expiry(p) or 0.0) <= now]
        for p in expired:
            self.cluster.delete_pod(p)
        if expired:
            self.stats["expired"] += len(expired)
            metrics.forecast_placeholders().inc(
                {"outcome": "expired"}, by=len(expired))
        return len(expired)

    def _placeholder(self, cls: str, cpu: float, mem: float, now: float,
                     prefer_on_demand: bool) -> Pod:
        safe = _SAFE_NAME.sub("-", cls.lower()).strip("-") or "class"
        name = f"headroom-{safe}-{next(self._seq):06d}"
        selector = {wk.CAPACITY_TYPE: wk.CAPACITY_TYPE_ON_DEMAND} \
            if prefer_on_demand else {}
        return Pod(
            name=name, uid=name,
            requests=ResourceList({CPU: max(1.0, round(cpu)),
                                   MEMORY: max(1.0, round(mem))}),
            labels={HEADROOM_LABEL: "true", HEADROOM_CLASS_LABEL: cls},
            annotations={
                HEADROOM_EXPIRY_ANNOTATION: f"{now + self.config.ttl_s:.3f}"},
            node_selector=selector,
            priority=HEADROOM_PRIORITY,
            owner_kind="")     # placeholders die with their node, never requeue

    def _within_budget(self, placeholders: List[Pod],
                       out: ForecastResult) -> List[Pod]:
        """Dry-run the batch through the real solver off live state and
        keep placeholders in solver order until new-node spend hits the
        cap — placeholders the solver lands on EXISTING capacity are free
        and always kept."""
        cfg = self.config
        nodes = self.cluster.snapshot_nodes()
        pools = self.provisioner._pools_within_limits()
        with tracing.span("forecast.plan",
                          placeholders=len(placeholders)) as psp:
            try:
                problem, packing = self.provisioner.solve(
                    placeholders, nodes=nodes, pools=pools)
            except Exception as e:  # noqa: BLE001 — skip the round, retry next
                log.warning("headroom dry-run solve failed: %s", e)
                return []
            rate = sum(n.price for n in self.cluster.nodes.values())
            budget = max(cfg.max_cost_frac * rate, cfg.min_budget_per_h)
            keep = set()
            for i in packing.existing_assignments:
                keep.add(problem.pods[i].uid)
            spend = 0.0
            planned: List[Tuple[str, float]] = []
            for nd in packing.nodes:
                price = float(getattr(nd.option, "price", 0.0))
                if spend + price > budget:
                    continue
                spend += price
                planned.append((getattr(nd.option, "pool", "") or "", price))
                for i in nd.pod_indices:
                    keep.add(problem.pods[i].uid)
            psp.annotate(budget=round(budget, 4), spend=round(spend, 4),
                         kept=len(keep))
        # cost-ledger annotation (SLOEngine gate): the spend this headroom
        # round PLANS, as reservations — the nodes themselves, if demand
        # materializes, are ledgered by their own launches, so reservations
        # stay out of the per-source capacity sums (no double-count)
        from ..obs.ledger import LEDGER
        if LEDGER.enabled and planned:
            now = self.clock()
            for pool, price in planned:
                LEDGER.record_reservation(
                    nodepool=pool,
                    expected_dh=price * cfg.ttl_s / 3600.0,
                    at=now, ttl_s=cfg.ttl_s)
        kept = [p for p in placeholders if p.uid in keep]
        dropped = len(placeholders) - len(kept)
        if dropped:
            out.trimmed += dropped
            self.stats["trimmed"] += dropped
            metrics.forecast_placeholders().inc(
                {"outcome": "trimmed"}, by=dropped)
        return kept

    # ------------------------------------------------------------------
    def preempt_for_pending(self) -> int:
        """Yield placeholders to real demand: called by the manager right
        before each provisioning solve.  Pending placeholders all step
        aside; bound ones are evicted (earliest expiry first) until the
        freed capacity covers the real pending requests."""
        pending_real = [p for p in self.cluster.pending_pods()
                        if not is_headroom(p)]
        if not pending_real:
            return 0
        with tracing.span("forecast.preempt",
                          pending=len(pending_real)) as sp:
            n = 0
            for p in sorted((q for q in self.cluster.pending_pods()
                             if is_headroom(q)), key=lambda q: q.name):
                self.cluster.delete_pod(p)
                n += 1
            need_cpu = sum(float(p.requests.get("cpu", 0))
                           for p in pending_real)
            need_mem = sum(float(p.requests.get("memory", 0))
                           for p in pending_real)
            freed_cpu = freed_mem = 0.0
            bound = sorted(
                (q for q in self.cluster.pods.values()
                 if is_headroom(q) and q.node_name),
                key=lambda q: (headroom_expiry(q) or 0.0, q.name))
            for p in bound:
                if freed_cpu >= need_cpu and freed_mem >= need_mem:
                    break
                freed_cpu += float(p.requests.get("cpu", 0))
                freed_mem += float(p.requests.get("memory", 0))
                self.cluster.delete_pod(p)
                n += 1
            if n:
                self.stats["preempted"] += n
                metrics.forecast_placeholders().inc(
                    {"outcome": "preempted"}, by=n)
            sp.annotate(preempted=n)
        return n
