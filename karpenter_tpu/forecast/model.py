"""Pluggable demand forecasters: EWMA baseline + Holt-Winters seasonal.

Each forecaster maps a concurrency series (from `series.DemandSeries`) to
a `ForecastEnvelope`: per-step mean plus upper/lower confidence bands.
Pure NumPy, deterministic given the input — same series, same envelope,
byte for byte.  The band grows as sqrt(h) with the forecast step, the
standard random-walk widening, and is clamped at zero (demand counts
cannot go negative).

Holt-Winters needs at least two full seasons to estimate its seasonal
components; until then it degrades gracefully to Holt's linear method
(level + trend), which is what actually predicts a diurnal ramp-up during
the first simulated day — the trend term sees the climb coming before the
seasonal term has any history at all.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np


@dataclass
class ForecastEnvelope:
    """Per-step demand forecast: `mean[h]`, `upper[h]`, `lower[h]` for
    h = 1..steps ahead of the last observation."""
    mean: np.ndarray
    upper: np.ndarray
    lower: np.ndarray

    @property
    def steps(self) -> int:
        return len(self.mean)


def _envelope(mean: np.ndarray, sigma: float, z: float) -> ForecastEnvelope:
    h = np.arange(1, len(mean) + 1, dtype=np.float64)
    band = z * sigma * np.sqrt(h)
    mean = np.maximum(mean, 0.0)
    return ForecastEnvelope(mean=mean,
                            upper=np.maximum(mean + band, 0.0),
                            lower=np.maximum(mean - band, 0.0))


class EWMAForecaster:
    """Exponentially-weighted level with an EW residual variance: the flat
    baseline.  Forecast mean is the level at every step; the band comes
    from the smoothed one-step residual."""

    name = "ewma"

    def __init__(self, alpha: float = 0.3):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)

    def forecast(self, values: np.ndarray, steps: int,
                 z: float = 1.64) -> ForecastEnvelope:
        values = np.asarray(values, dtype=np.float64)
        if len(values) == 0:
            zero = np.zeros(steps, dtype=np.float64)
            return ForecastEnvelope(zero, zero.copy(), zero.copy())
        level = float(values[0])
        var = 0.0
        a = self.alpha
        for v in values[1:]:
            resid = float(v) - level
            var = (1.0 - a) * var + a * resid * resid
            level = (1.0 - a) * level + a * float(v)
        mean = np.full(steps, level, dtype=np.float64)
        return _envelope(mean, math.sqrt(max(var, 0.0)), z)


class HoltWintersForecaster:
    """Additive Holt-Winters (level + trend + seasonal).  With fewer than
    two full seasons of history the seasonal components are unidentifiable,
    so the model falls back to Holt's linear method — the trend term alone
    already anticipates monotone ramps."""

    name = "holtwinters"

    def __init__(self, alpha: float = 0.35, beta: float = 0.1,
                 gamma: float = 0.2, season_length: int = 24):
        for nm, v in (("alpha", alpha), ("beta", beta), ("gamma", gamma)):
            if not 0.0 < v <= 1.0:
                raise ValueError(f"{nm} must be in (0, 1], got {v}")
        if season_length < 1:
            raise ValueError(f"season_length must be >= 1, got {season_length}")
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.gamma = float(gamma)
        self.season_length = int(season_length)

    # ------------------------------------------------------------------
    def forecast(self, values: np.ndarray, steps: int,
                 z: float = 1.64) -> ForecastEnvelope:
        values = np.asarray(values, dtype=np.float64)
        m = self.season_length
        if len(values) >= 2 * m and m >= 2:
            mean, sigma = self._holt_winters(values, steps)
        else:
            mean, sigma = self._holt(values, steps)
        return _envelope(mean, sigma, z)

    # EW weight for the residual variance: the band must track the CURRENT
    # demand regime — a diurnal trough after a busy day would otherwise
    # keep a peak-sized confidence band (and peak-sized headroom) all night
    VAR_DECAY = 0.03

    def _holt(self, values: np.ndarray, steps: int):
        """Level + trend only (the < 2-seasons fallback)."""
        n = len(values)
        if n == 0:
            return np.zeros(steps, dtype=np.float64), 0.0
        level = float(values[0])
        trend = float(values[1] - values[0]) if n > 1 else 0.0
        a, b, d = self.alpha, self.beta, self.VAR_DECAY
        var = 0.0
        for t in range(1, n):
            pred = level + trend
            resid = float(values[t]) - pred
            var = (1.0 - d) * var + d * resid * resid
            last = level
            level = a * float(values[t]) + (1.0 - a) * (level + trend)
            trend = b * (level - last) + (1.0 - b) * trend
        h = np.arange(1, steps + 1, dtype=np.float64)
        mean = level + trend * h
        return mean, math.sqrt(max(var, 0.0))

    def _holt_winters(self, values: np.ndarray, steps: int):
        n, m = len(values), self.season_length
        a, b, g, d = self.alpha, self.beta, self.gamma, self.VAR_DECAY
        first = float(np.mean(values[:m]))
        second = float(np.mean(values[m:2 * m]))
        level = first
        trend = (second - first) / m
        seasonal = (values[:m] - first).astype(np.float64).copy()
        var = 0.0
        for t in range(m, n):
            v = float(values[t])
            s = seasonal[t % m]
            pred = level + trend + s
            resid = v - pred
            var = (1.0 - d) * var + d * resid * resid
            last = level
            level = a * (v - s) + (1.0 - a) * (level + trend)
            trend = b * (level - last) + (1.0 - b) * trend
            seasonal[t % m] = g * (v - level) + (1.0 - g) * s
        h = np.arange(1, steps + 1, dtype=np.float64)
        season_idx = (n + np.arange(steps)) % m
        mean = level + trend * h + seasonal[season_idx]
        return mean, math.sqrt(max(var, 0.0))


_KINDS = {"ewma": EWMAForecaster, "holtwinters": HoltWintersForecaster}


def make_forecaster(kind: str, season_length: int = 24, **kw):
    """Forecaster registry: `kind` is "ewma" or "holtwinters"."""
    if kind not in _KINDS:
        raise ValueError(
            f"unknown forecaster {kind!r} (expected one of {sorted(_KINDS)})")
    if kind == "holtwinters":
        kw.setdefault("season_length", season_length)
    return _KINDS[kind](**kw)
