"""Demand forecasting + proactive provisioning (predictive headroom).

Karpenter's provisioning model is purely reactive: a node launches only
after pods are already unschedulable, so every demand spike pays the full
node-ready latency on the critical path.  This package closes that gap:

  * `series`  — bounded per-pod-class ring of arrival/departure
    observations, fed from the cluster's admission/bind path on the
    injectable clock (identical live and under ``sim/``);
  * `model`   — pluggable forecasters (EWMA baseline, Holt-Winters
    seasonal) producing a demand envelope with confidence bands, pure
    NumPy and deterministic given the series;
  * `headroom` — the HeadroomController that converts the envelope (plus
    a spot-risk prior learned from observed reclaim rates) into
    low-priority placeholder claims placed through the existing
    ``Provisioner.solve``/classpack path, TTL-protected from the
    consolidation sweep and evicted the instant a real pod needs the slot.

Gated off by default; enable with ``--forecast`` (or ``--feature-gates
Forecast=true``).  See docs/forecast.md.
"""

from .headroom import (HEADROOM_CLASS_LABEL, HEADROOM_EXPIRY_ANNOTATION,
                       HEADROOM_LABEL, HEADROOM_PRIORITY, ForecastResult,
                       HeadroomConfig, HeadroomController, SpotRiskPrior,
                       headroom_expiry, is_headroom)
from .model import (EWMAForecaster, ForecastEnvelope, HoltWintersForecaster,
                    make_forecaster)
from .series import DemandSeries, pod_class

__all__ = [
    "DemandSeries", "pod_class",
    "ForecastEnvelope", "EWMAForecaster", "HoltWintersForecaster",
    "make_forecaster",
    "HeadroomController", "HeadroomConfig", "ForecastResult",
    "SpotRiskPrior", "is_headroom", "headroom_expiry",
    "HEADROOM_LABEL", "HEADROOM_CLASS_LABEL", "HEADROOM_EXPIRY_ANNOTATION",
    "HEADROOM_PRIORITY",
]
