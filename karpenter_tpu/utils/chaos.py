"""Deterministic chaos injection: seeded fault schedules at named points.

The injector is a process-global singleton (`CHAOS`) that is OFF by
default and zero-cost on the happy path (`inject` returns after one
boolean check).  Tests, the sim harness (`ChaosSpec`), or the operator
(`--chaos-spec` / `KARPENTER_TPU_CHAOS_SPEC`) arm it with a list of
`ChaosRule`s; each rule owns an independent `numpy` Generator keyed on
``[seed, rule-index]`` and consumed in call order, so the same
(rules, seed, call sequence) always injects the same schedule — the
property the chaos golden report depends on.

Injection points are a closed registry (`POINTS`): graftlint RS002
rejects literal `CHAOS.inject("...")` names outside it, the same
two-way contract the tracing span registry uses.  The `key` argument is
the dynamic discriminator within a point (controller name, solver rung,
cloud API name) so one rule can target `controller.reconcile` for just
`disruption`.

Actions:
  * ``error``   — raise `ChaosError` (or `CloudError(error_code)` when the
    rule carries a cloud code, so the provider's retry/classification
    taxonomy sees a realistic failure);
  * ``latency`` — call the configured sleep for `latency_s` (wall sleep in
    live runs and threaded tests; the sim passes a no-op sleep because
    wall latency is meaningless under a virtual clock);
  * ``hang``    — sleep `latency_s` as one blocking call; meaningful under
    a watchdog deadline shorter than the hang (utils/watchdog.py), which
    is exactly how the hung-solver chaos tests trip the ladder.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import metrics

log = logging.getLogger("karpenter_tpu.chaos")

# The closed injection-point registry (graftlint RS002).  Every literal
# `CHAOS.inject("<point>")` call site must name a member; new seams
# register here first so the chaos scenario schema and docs stay in sync.
POINTS = frozenset({
    "controller.reconcile",   # manager tick, key = controller name
    "solver.pack",            # provisioning/disruption pack step, key = rung
    "solver.sweep",           # batched consolidation sweep
    "cloud.api",              # FakeCloud API entry, key = api name
    "refinery.refine",        # background guide refinement
    "leader.lease",           # lease I/O (acquire/release), key = op
})

ACTIONS = ("error", "latency", "hang")


class ChaosError(RuntimeError):
    """An injected fault (not a real bug): supervisors/ladders must treat
    it exactly like any other controller/solver exception."""


@dataclass
class ChaosRule:
    """One fault stream.  `at_s`/`until_s` are absolute clock values (the
    sim converts scenario-relative offsets before configuring); `rate` is
    the per-call injection probability drawn from the rule's own stream;
    `count` bounds total injections (0 = unbounded)."""
    point: str
    key: str = ""            # "" or "*" matches every key at the point
    action: str = "error"
    rate: float = 1.0
    at_s: float = float("-inf")
    until_s: float = float("inf")
    latency_s: float = 0.0
    count: int = 0
    error_code: str = ""     # raise CloudError(code) instead of ChaosError


class ChaosInjector:
    """Seeded, schedule-driven fault injector.  Single-threaded consumers
    only (the manager tick loop / sim); the enabled check is lock-free so
    the disarmed hot path costs one attribute read."""

    def __init__(self) -> None:
        self.enabled = False
        self.rules: List[ChaosRule] = []
        self.clock: Callable[[], float] = time.monotonic
        self.sleep: Callable[[float], None] = time.sleep
        self._rngs: List[np.random.Generator] = []
        self._fired: List[int] = []
        self._injected: Dict[Tuple[str, str], int] = {}

    def configure(self, rules: Sequence[ChaosRule], seed: int = 0,
                  clock: Callable[[], float] = time.monotonic,
                  sleep: Callable[[float], None] = time.sleep) -> None:
        for i, r in enumerate(rules):
            if r.point not in POINTS:
                raise ValueError(f"chaos rule {i}: unknown point {r.point!r} "
                                 f"(expected one of {sorted(POINTS)})")
            if r.action not in ACTIONS:
                raise ValueError(f"chaos rule {i}: unknown action "
                                 f"{r.action!r} (expected one of {ACTIONS})")
            if not 0.0 < r.rate <= 1.0:
                raise ValueError(f"chaos rule {i}: rate must be in (0, 1]")
        self.rules = list(rules)
        self.clock = clock
        self.sleep = sleep
        # one stream per rule: adding a rule never perturbs its siblings
        self._rngs = [np.random.default_rng([int(seed), i])
                      for i in range(len(self.rules))]
        self._fired = [0] * len(self.rules)
        self._injected = {}
        self.enabled = bool(self.rules)

    def reset(self) -> None:
        """Disarm and forget all schedules (test teardown / sim finally)."""
        self.enabled = False
        self.rules = []
        self._rngs = []
        self._fired = []
        self._injected = {}
        self.clock = time.monotonic
        self.sleep = time.sleep

    def inject(self, point: str, key: str = "") -> None:
        """Maybe fire at a named point.  Raises on an `error` action;
        sleeps on `latency`/`hang`; returns silently otherwise."""
        if not self.enabled:
            return
        if point not in POINTS:
            raise ValueError(f"unregistered chaos point {point!r}")
        now = self.clock()
        for i, r in enumerate(self.rules):
            if r.point != point:
                continue
            if r.key not in ("", "*") and r.key != key:
                continue
            if not (r.at_s <= now < r.until_s):
                continue
            if r.count and self._fired[i] >= r.count:
                continue
            if r.rate < 1.0 and float(self._rngs[i].random()) >= r.rate:
                continue
            self._fired[i] += 1
            self._injected[(point, r.action)] = \
                self._injected.get((point, r.action), 0) + 1
            metrics.chaos_injections().inc({"point": point,
                                            "action": r.action})
            log.debug("chaos: %s at %s[%s]", r.action, point, key)
            if r.action == "error":
                if r.error_code:
                    from ..cloud.fake import CloudError
                    raise CloudError(r.error_code,
                                     f"chaos injected at {point}[{key}]")
                raise ChaosError(f"chaos injected at {point}"
                                 + (f"[{key}]" if key else ""))
            self.sleep(r.latency_s)
            return

    def counts(self) -> Dict[str, int]:
        """Deterministic injection totals keyed "point/action" (the chaos
        section of the sim report)."""
        return {f"{p}/{a}": n
                for (p, a), n in sorted(self._injected.items())}

    def fired_total(self) -> int:
        return sum(self._fired)


CHAOS = ChaosInjector()


def parse_spec(spec: str) -> List[ChaosRule]:
    """Parse the `--chaos-spec` flag / `KARPENTER_TPU_CHAOS_SPEC` env
    format: semicolon-separated rules of comma-separated `k=v` pairs, e.g.
    ``point=controller.reconcile,key=disruption,action=error,rate=0.5;
    point=cloud.api,action=error,error_code=RequestLimitExceeded``."""
    rules: List[ChaosRule] = []
    for chunk in filter(None, (c.strip() for c in spec.split(";"))):
        kw: Dict[str, object] = {}
        for item in filter(None, (i.strip() for i in chunk.split(","))):
            k, _, v = item.partition("=")
            k = k.strip()
            if k in ("rate", "at_s", "until_s", "latency_s"):
                kw[k] = float(v)
            elif k == "count":
                kw[k] = int(v)
            elif k in ("point", "key", "action", "error_code"):
                kw[k] = v.strip()
            else:
                raise ValueError(f"chaos spec: unknown field {k!r}")
        if "point" not in kw:
            raise ValueError(f"chaos spec: rule {chunk!r} needs point=")
        rules.append(ChaosRule(**kw))  # type: ignore[arg-type]
    return rules


def maybe_configure_from_options(options) -> bool:
    """Arm the global injector from Options (live operator startup).
    Returns True when chaos was armed.  The sim harness configures the
    injector directly instead so schedules ride the virtual clock."""
    spec = getattr(options, "chaos_spec", "") or ""
    if not spec:
        return False
    CHAOS.configure(parse_spec(spec),
                    seed=int(getattr(options, "chaos_seed", 0)))
    log.warning("chaos injection ARMED: %d rule(s), seed=%d",
                len(CHAOS.rules), int(getattr(options, "chaos_seed", 0)))
    return True
