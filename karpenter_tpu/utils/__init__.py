"""Shared helpers (reference: /root/reference/pkg/utils/)."""

from __future__ import annotations

import re
from typing import Dict, Optional

# providerID format `<cloud>:///<zone>/<instance-id>` — parse analog of
# /root/reference/pkg/utils/utils.go:33-56 (aws:///$zone/$id regex).
_PROVIDER_ID_RE = re.compile(r"^[a-z-]+:///(?P<zone>[^/]+)/(?P<id>[^/]+)$")


def parse_instance_id(provider_id: str) -> Optional[str]:
    """Extract the instance id from a providerID URI; bare ids pass through
    (utils.go ParseInstanceID)."""
    m = _PROVIDER_ID_RE.match(provider_id)
    if m:
        return m.group("id")
    if provider_id.startswith("i-"):
        return provider_id
    return None


def merge_tags(*tag_maps: Dict[str, str]) -> Dict[str, str]:
    """Later maps win (utils.go MergeTags)."""
    out: Dict[str, str] = {}
    for m in tag_maps:
        out.update(m or {})
    return out
